"""Async shuffle fetcher — the hot read path.

Re-design of ``scala/RdmaShuffleFetcherIterator.scala``. Preserved semantics,
point by point:

* three-level fetch: driver table once per shuffle (:183 →
  RdmaShuffleManager.scala:341-376), per-map block-location reads out of the
  owning executor (:293-315), then grouped data fetches (:119-180);
* block grouping: consecutive partitions of one map output are fetched in
  requests of at most ``shuffle_read_block_size`` bytes (:240-263);
* flow control: a ``max_bytes_in_flight`` gate — fetches beyond the budget
  queue until the consumer drains results (:264-276, 366-374), with the
  single-oversized-fetch escape so one huge block can't deadlock;
* randomized pending order so one peer isn't oversubscribed (:74-79);
* local map outputs short-circuit the network entirely (:327-337);
* results flow through a blocking queue; a sentinel terminates iteration
  (:47-50, 113-117); failures surface as ``FetchFailedError`` so the engine
  can recompute the stage (:376-381);
* **bounded read-ahead per peer**: each peer thread keeps up to
  ``read_ahead_depth`` grouped fetches outstanding on the pipelined
  connection and overlaps STEP-2 location reads with STEP-3 data reads —
  the ``sendQueueDepth / cores`` in-flight split that the reference's
  whole speedup rides on (:82-83). ``read_ahead_depth=1`` reproduces the
  fully sequential pre-pipelining behavior exactly (regression escape
  hatch);
* **coalesced reads** (``coalesce_reads``, on by default): per-peer
  batching at BOTH fetch levels. STEP 2 becomes ONE batched location RPC
  per (shuffle, peer) covering every map this reducer needs there —
  O(peers) instead of O(maps) metadata round trips, the unit the
  reference fetches when it READs a peer's whole address table once
  (RdmaShuffleManager.scala:341-376). STEP 3 becomes VECTORED reads:
  per-map groups bound for the same peer merge across maps into single
  request frames (up to ``max_vectored_bytes``/frame caps), each landing
  in one refcounted multi-view pool lease the way the reference lands
  one scatter-READ of many blocks in a single registration
  (java/RdmaRegisteredBuffer.java:28-87). Per-map attribution is kept:
  every vectored response is sliced back into per-(map, range) results,
  and a corrupt sub-block (per-block CRC trailer) refetches ONLY the
  affected ranges, blaming the owning map. A peer that fails the first
  batched call (mixed-version: an old server drops the unknown frame)
  falls back to the per-map dataplane for that peer.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel.endpoints import (
    DeadExecutorError,
    ExecutorEndpoint,
)
from sparkrdma_tpu.parallel.messages import STATUS_CORRUPT, STATUS_OK
from sparkrdma_tpu.parallel.transport import (
    Backoff,
    ChecksumError,
    FetchStatusError,
    TransportError,
)
from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver
from sparkrdma_tpu.utils.stats import FetchPipelineStats

log = logging.getLogger(__name__)


class _Aborted(Exception):
    """Internal: the consumer abandoned/failed the iteration."""


class FetchFailedError(Exception):
    """A remote block could not be fetched; the engine should recompute the
    producing stage (reference surfaces Spark's FetchFailedException,
    scala/RdmaShuffleFetcherIterator.scala:376-381).

    ``verdict`` tells the recovery loop WHY: ``"peer_lost"`` (default —
    the slot may be dead; recompute everything it owned, maybe tombstone)
    vs ``"corrupt_output"`` (the owner is alive but THIS map's committed
    output failed its at-rest verification; re-execute just that map, on
    any live executor including the owner, and never tombstone a live
    peer over bit-rot)."""

    def __init__(self, shuffle_id: int, map_id: int, exec_index: int,
                 cause: str, verdict: str = "peer_lost"):
        super().__init__(f"shuffle {shuffle_id} map {map_id} "
                         f"(executor slot {exec_index}): {cause}")
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.exec_index = exec_index
        self.verdict = verdict


@dataclass
class FetchResult:
    """One successful grouped fetch (or the failure/sentinel marker).

    ``data`` is bytes, or — when a vectored response landed in a pool
    lease — a uint8 numpy view into the shared
    :class:`~sparkrdma_tpu.runtime.pool.RegisteredBuffer` (``lease``).
    Lease-backed results must be :meth:`free`\\ d once consumed so the
    pool buffer returns on last release; ``free`` is a no-op otherwise.
    Use ``len(data)``, not truthiness (ndarray truthiness raises)."""

    map_id: int = -1
    start_partition: int = 0
    end_partition: int = 0
    data: bytes = b""
    is_local: bool = False
    failure: Optional[FetchFailedError] = None
    is_sentinel: bool = False
    lease: Optional[object] = None  # RegisteredBuffer holding `data`'s view
    _free_lock: threading.Lock = field(default_factory=threading.Lock,
                                       repr=False, compare=False)

    def free(self) -> None:
        """Release this result's reference on the shared pool lease.

        Idempotent AND race-safe: the native fetch engine completes
        results from a non-consumer thread, so a consumer ``free`` can
        race an unwind ``free`` — exactly one of them may hand the
        reference back or the pool double-frees the backing buffer."""
        with self._free_lock:
            lease, self.lease = self.lease, None
        if lease is not None:
            lease.release()


@dataclass
class ReadMetrics:
    """Reference: Spark task metrics wiring
    (scala/RdmaShuffleFetcherIterator.scala:104-106, 330-332, 349-361).
    Updated from concurrent peer threads — mutate via the record_* methods."""

    remote_bytes: int = 0
    local_bytes: int = 0
    remote_fetches: int = 0
    local_fetches: int = 0
    fetch_wait_s: float = 0.0
    fetch_latencies_s: List[float] = field(default_factory=list)
    # failure path: transient retries absorbed, CRC mismatches refetched,
    # terminal failures escalated to FetchFailed (stage retry)
    retries: int = 0
    checksum_failures: int = 0
    failed_fetches: int = 0
    # request frames this reducer put on the wire: location RPCs (per-map
    # or batched) + data reads (grouped or vectored), retries included —
    # the RPC-count the coalesced dataplane exists to shrink. The
    # coalescing tier-1 test asserts this drops vs the per-map path.
    requests_per_reduce: int = 0
    # METADATA RPCs only (driver-table/shard syncs + block-location
    # reads) — the count the epoch-versioned location plane exists to
    # zero: a warm superstep over an unchanged shuffle must read as 0
    # here (asserted by the wire-traffic test and the iterative bench).
    metadata_rpcs_per_stage: int = 0
    # location-plane cache hits this reducer resolved without the wire
    location_cache_hits: int = 0
    # warm read-range hits (warm_read_cache): whole partition ranges
    # served from dist_cache without starting a fetch at all
    warm_range_hits: int = 0
    # push-merge dataplane: partitions served by ONE merged-segment read
    # instead of the M-way per-map fan-in, the bytes they carried, and
    # partitions that DEGRADED back to per-map (replica unreachable or
    # its segment failed the entry CRC)
    merged_reads: int = 0
    merged_bytes: int = 0
    merged_fallbacks: int = 0
    # planned-push dataplane: (map, partition) ranges served from the
    # local PushedInputStore — zero metadata RPCs, zero data RPCs — and
    # the bytes they carried. A fully-pushed reducer's whole input reads
    # as pushed here (the pushplan bench and the zero-RPC test assert it).
    pushed_reads: int = 0
    pushed_bytes: int = 0
    # cold table syncs whose shard phase came up short (owner/replica
    # lost or lagging) and burned the driver-authoritative fallback —
    # the partitioned-ownership health signal: sustained nonzero here
    # means the shard fan-in is not actually absorbing reads
    shard_fallbacks: int = 0
    # cold-tier dataplane: partitions restored from tiered blobs (the
    # LAST resolve rung before re-execution), the bytes they carried,
    # and restores that DEGRADED onward (blob missing/rotten/torn —
    # per-partition, down to re-execution of exactly the covered maps)
    tiered_reads: int = 0
    tiered_bytes: int = 0
    tiered_fallbacks: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record_remote(self, nbytes: int, latency_s: float) -> None:
        with self._lock:
            self.remote_bytes += nbytes
            self.remote_fetches += 1
            self.fetch_latencies_s.append(latency_s)

    def record_request(self) -> None:
        with self._lock:
            self.requests_per_reduce += 1

    def record_metadata_rpc(self) -> None:
        with self._lock:
            self.metadata_rpcs_per_stage += 1

    def record_location_hit(self, n: int = 1) -> None:
        with self._lock:
            self.location_cache_hits += n

    def record_local(self, nbytes: int) -> None:
        with self._lock:
            self.local_bytes += nbytes
            self.local_fetches += 1

    def record_merged(self, nbytes: int) -> None:
        with self._lock:
            self.merged_reads += 1
            self.merged_bytes += nbytes

    def record_merged_fallback(self) -> None:
        with self._lock:
            self.merged_fallbacks += 1

    def record_pushed(self, nbytes: int) -> None:
        with self._lock:
            self.pushed_reads += 1
            self.pushed_bytes += nbytes

    def record_shard_fallback(self) -> None:
        with self._lock:
            self.shard_fallbacks += 1

    def record_tiered(self, nbytes: int) -> None:
        with self._lock:
            self.tiered_reads += 1
            self.tiered_bytes += nbytes

    def record_tiered_fallback(self) -> None:
        with self._lock:
            self.tiered_fallbacks += 1

    def record_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def record_checksum_failure(self) -> None:
        with self._lock:
            self.checksum_failures += 1

    def record_failure(self) -> None:
        with self._lock:
            self.failed_fetches += 1


@dataclass
class _PendingFetch:
    exec_index: int
    map_id: int
    start_partition: int
    end_partition: int
    blocks: List  # [(buf, offset, length)]
    total_bytes: int


@dataclass
class _VectoredFetch:
    """One coalesced data request: per-map groups merged across maps for
    one peer. ``blocks`` is the request-order concatenation of every
    segment's ranges; the response payload slices back into per-segment
    results positionally, so per-map attribution survives the merge."""

    exec_index: int
    segments: List[_PendingFetch]
    blocks: List  # [(buf, offset, length)] across all segments
    total_bytes: int


class ShuffleFetcher:
    """Iterator of FetchResults for one reducer's partition range."""

    def __init__(self, endpoint: ExecutorEndpoint,
                 resolver: Optional[TpuShuffleBlockResolver],
                 conf: TpuShuffleConf, shuffle_id: int, num_maps: int,
                 start_partition: int, end_partition: int,
                 seed: Optional[int] = None, reader_stats=None, tracer=None,
                 pool=None, map_range=None):
        from sparkrdma_tpu.utils import trace as trace_mod
        self.endpoint = endpoint
        self.resolver = resolver
        self.conf = conf
        # map-range restriction (adaptive reduce planning): a SPLIT task
        # reads its partition from a disjoint [map_start, map_end) slice
        # of the map space — the rest of the fetch machinery (grouping,
        # coalescing, retries, blame) is untouched, it just sees fewer
        # maps. None = the full map space (every pre-planner caller).
        self.map_start, self.map_end = map_range or (0, num_maps)
        if not 0 <= self.map_start <= self.map_end <= num_maps:
            raise ValueError(f"bad map_range ({self.map_start}, "
                             f"{self.map_end}) for {num_maps} maps")
        # staging pool (runtime/pool.py): when present, each vectored
        # response lands in ONE refcounted multi-view RegisteredBuffer
        # lease — many logical blocks, one pool buffer, returned on last
        # consumer release (java/RdmaRegisteredBuffer.java:28-87)
        self.pool = pool
        # tenancy: staging leases charge the shuffle's owning tenant
        self.tenant = (resolver.tenant_of(shuffle_id)
                       if resolver is not None
                       and hasattr(resolver, "tenant_of")
                       else endpoint.tenant_of(shuffle_id)
                       if hasattr(endpoint, "tenant_of") else 0)
        self.reader_stats = reader_stats  # ShuffleReaderStats | None
        self.tracer = tracer or trace_mod.NULL
        self.shuffle_id = shuffle_id
        self.num_maps = num_maps
        self.start_partition = start_partition
        self.end_partition = end_partition
        self.metrics = ReadMetrics()
        # per-peer read-ahead telemetry (depth + queue-wait histograms).
        # When stats collection is on this IS reader_stats.pipeline — one
        # object, one lock per issue, one source of truth in snapshots
        self.pipeline = (reader_stats.pipeline if reader_stats is not None
                         else FetchPipelineStats())
        self._results: "queue.Queue[FetchResult]" = queue.Queue()
        self._expected_results = 0
        self._consumed = 0
        # max_bytes_in_flight gate (:264-276)
        self._in_flight = 0
        self._in_flight_cv = threading.Condition()
        self._failed = False
        self._aborted = threading.Event()
        self._rng = random.Random(seed)
        # retry backoff shares the fetcher seed so a chaos scenario's
        # sleep schedule replays with it
        self._backoff = Backoff.from_conf(conf, rng=random.Random(seed))
        self._threads: List[threading.Thread] = []
        # location-state version this fetch resolved against (stamped by
        # start() from the table sync): cached locations and warm
        # partition ranges store under it, pushed epoch bumps invalidate
        self.epoch = 0
        self._started = False
        self._reducer_bytes_recorded = False
        # push-merge: partitions satisfied by merged-segment reads, per
        # map — the per-map paths (grouping, local short-circuit) skip
        # them so every (map, partition) is served EXACTLY once; the
        # driver table is kept for the merged threads' per-map fallback
        self._skip: Dict[int, set] = {}
        # planned push: partitions with at least one staged pushed range
        # — merged resolution skips them entirely (a merged segment
        # cannot be sliced around the pushed maps; the leftover maps of
        # a partially-pushed partition ride the per-map plane instead)
        self._pushed_parts: set = set()
        self._table = None
        # cold tier: the tiered-directory snapshot this fetch resolved
        # against (sibling-blob fallback consults it on a failed restore)
        self._tiered_dir = None

    # -- setup: plan + launch (initialize/startAsyncRemoteFetches) -------

    def start(self) -> "ShuffleFetcher":
        self._started = True
        # planned push: resolve staged pushed ranges FIRST — before the
        # driver-table sync, before merged segments, before per-map
        # pull. A reducer whose inputs ALL arrived serves entirely from
        # the local PushedInputStore and returns here with ZERO metadata
        # RPCs and ZERO data RPCs; any hole falls through to the
        # ordinary dataplanes below, byte-identically.
        self._resolve_pushed()
        all_parts = set(range(self.start_partition, self.end_partition))
        if all(self._skip.get(m, set()) >= all_parts
               for m in range(self.map_start, self.map_end)):
            self._peer_threads_left = 0
            self._results.put(FetchResult(is_sentinel=True))
            return self
        with self.tracer.span("fetch.driver_table", "fetch",
                              shuffle=self.shuffle_id):
            table, self.epoch = self.endpoint.get_driver_table_v(
                self.shuffle_id, self.num_maps, metrics=self.metrics)
        my_index = self._my_index()
        self._table = table
        # push-merge: resolve merged-segment coverage FIRST — partitions
        # a live replica covers become one sequential vectored read each,
        # and the per-map machinery below only plans what is left
        merged_by_slot = self._resolve_merged(my_index)
        all_parts = set(range(self.start_partition, self.end_partition))
        local_maps: List[int] = []
        by_peer: Dict[int, List[int]] = {}
        # cold tier: maps no earlier rung can serve — never published
        # (full-fleet restart: the fresh table is empty) or published on
        # a slot the membership has TOMBSTONED (authoritative death, not
        # mere lag) — divert to the TIERED rung instead of escalating.
        # Live owners never divert: tiered resolves LAST by precedence.
        cold_maps: List[int] = []
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        cold_on = bool(self.conf.cold_tier)
        members = self.endpoint.members() if cold_on else []
        for m in range(self.map_start, self.map_end):
            if self._skip.get(m, set()) >= all_parts:
                continue  # every partition rides a merged segment
            entry = table.entry(m)
            if entry is None:
                if cold_on:
                    cold_maps.append(m)
                    continue
                raise FetchFailedError(self.shuffle_id, m, -1,
                                       "map output never published")
            _, exec_idx = entry
            if exec_idx == my_index:
                local_maps.append(m)
            elif (cold_on and exec_idx < len(members)
                    and members[exec_idx] == TOMBSTONE):
                cold_maps.append(m)
            else:
                by_peer.setdefault(exec_idx, []).append(m)
        tiered_tasks = self._resolve_tiered(cold_maps, all_parts)

        # Local short-circuit (:327-337): serve directly, count
        # separately — per uncovered contiguous run when merged segments
        # satisfy part of the range.
        for m in local_maps:
            skip = self._skip.get(m, set())
            run_lo = None
            for p in range(self.start_partition, self.end_partition + 1):
                if p < self.end_partition and p not in skip:
                    if run_lo is None:
                        run_lo = p
                    continue
                if run_lo is not None:
                    data = self._local_read(m, run_lo, p, my_index)
                    self.metrics.record_local(len(data))
                    self._expected_results += 1
                    self._results.put(FetchResult(m, run_lo, p, data,
                                                  is_local=True))
                    run_lo = None

        # A freshly-joined reducer can hold driver-table entries referencing
        # executor slots its membership list hasn't caught up to yet (the
        # announce is async); wait for the list to cover the highest slot we
        # need before resolving peers.
        if by_peer:
            try:
                self.endpoint.wait_for_members(
                    max(by_peer) + 1,
                    timeout=self.conf.connect_timeout_ms / 1000)
            except TimeoutError as e:
                raise FetchFailedError(self.shuffle_id, -1, max(by_peer),
                                       f"membership never covered slot: {e}"
                                       ) from e

        # One fetch thread per peer: location reads then grouped data reads.
        # The per-peer thread bounds per-channel outstanding work the way the
        # reference divides sendQueueDepth across cores (:82-83).
        peers = list(by_peer.items())
        self._rng.shuffle(peers)  # randomized order (:74-79)
        count_lock = threading.Lock()
        for exec_idx, maps in peers:
            t = threading.Thread(target=self._fetch_from_peer,
                                 args=(exec_idx, maps, count_lock),
                                 daemon=True,
                                 name=f"fetch-s{self.shuffle_id}-e{exec_idx}")
            self._threads.append(t)
        # Merged-segment threads: one per replica slot, sequential wide
        # reads (already one request per partition — a window buys
        # nothing over the per-slot thread parallelism).
        for slot, entries in sorted(merged_by_slot.items()):
            t = threading.Thread(
                target=self._fetch_merged_from_slot,
                args=(slot, entries, my_index, count_lock),
                daemon=True,
                name=f"fetch-merged-s{self.shuffle_id}-e{slot}")
            self._threads.append(t)
        # Tiered-restore thread: blob reads are local-FS/object GETs with
        # no per-peer channel to parallelize over — one thread drains the
        # whole plan sequentially, same containment contract as a peer.
        if tiered_tasks:
            t = threading.Thread(
                target=self._fetch_tiered,
                args=(tiered_tasks, count_lock),
                daemon=True, name=f"fetch-tiered-s{self.shuffle_id}")
            self._threads.append(t)
        # Expected-result accounting: each peer thread registers its request
        # count before its first enqueue; the sentinel goes in when all
        # threads have finished (tracked by _peer_threads_left).
        self._peer_threads_left = (len(peers) + len(merged_by_slot)
                                   + (1 if tiered_tasks else 0))
        if self._peer_threads_left == 0:
            self._results.put(FetchResult(is_sentinel=True))
        for t in self._threads:
            t.start()
        return self

    def _local_read(self, m: int, lo: int, hi: int,
                    my_index: int) -> bytes:
        """One local short-circuit read under the bounded retry policy
        (transient EIO retries; at-rest rot escalates with a
        corrupt_output verdict so ONLY this map re-executes)."""
        from sparkrdma_tpu.utils.integrity import CorruptOutputError
        attempts = 1 + max(0, self.conf.fetch_retry_budget)
        for attempt in range(attempts):
            try:
                data = self.resolver.local_blocks(self.shuffle_id, m,
                                                  lo, hi)
                break
            except CorruptOutputError as e:
                # our OWN committed output rotted: same demotion as the
                # remote case — re-execute the map (a reread cannot heal
                # persistent rot), don't fail the job
                raise FetchFailedError(
                    self.shuffle_id, m, my_index,
                    f"local map output corrupt at rest: {e}",
                    verdict="corrupt_output") from e
            except OSError as e:
                # transient local disk error: same bounded retry the
                # remote path gets (a remote serve answers the retryable
                # STATUS_ERROR for this) — escalating on the first EIO
                # would recompute every local map elsewhere over a hiccup
                if attempt + 1 >= attempts:
                    raise FetchFailedError(
                        self.shuffle_id, m, my_index,
                        f"local map output unreadable after "
                        f"{attempts} attempt(s): {e}") from e
                self.metrics.record_retry()
                # abort-aware like every other retry wait in this file: a
                # concurrent teardown must not sit out the full backoff
                if self._aborted.wait(self._backoff.delay(attempt)):
                    raise FetchFailedError(
                        self.shuffle_id, m, my_index,
                        "fetch aborted during local read retry") from e
        if data is None:
            raise FetchFailedError(self.shuffle_id, m, my_index,
                                   "local map output missing")
        return data

    def _my_index(self) -> int:
        try:
            return self.endpoint.exec_index()
        except KeyError:
            return -1

    # -- pushed-first resolution (planned-push dataplane) ----------------

    def _resolve_pushed(self) -> None:
        """Serve every (map, partition) range the local PushedInputStore
        staged under the CACHED plan's exact epoch — no wire traffic of
        any kind. Served pairs join ``_skip`` (the same dedupe contract
        as merged segments: every pair is served exactly once) and their
        partitions are excluded from merged resolution. Cache-only plan
        lookup: no cached plan means no pushes were routed here under
        it, so there is nothing to consume — the ordinary dataplanes own
        the stage."""
        store = getattr(self.endpoint, "pushed_store", None)
        if store is None or not self.conf.planned_push:
            return
        plane = getattr(self.endpoint, "location_plane", None)
        plan = plane.plan(self.shuffle_id) if plane is not None else None
        if plan is None:
            return
        epoch = plan.plan_epoch
        need = set(range(self.map_start, self.map_end))
        served = bytes_total = 0
        for p in range(self.start_partition, self.end_partition):
            blobs = store.take(self.shuffle_id, p, epoch)
            if not blobs:
                continue
            self._pushed_parts.add(p)
            for m in sorted(need & set(blobs)):
                data = blobs[m]
                self.metrics.record_pushed(len(data))
                self._expected_results += 1
                self._results.put(FetchResult(m, p, p + 1, data,
                                              is_local=True))
                self._skip.setdefault(m, set()).add(p)
                served += 1
                bytes_total += len(data)
        if served:
            self.tracer.instant("fetch.pushed", "fetch",
                                shuffle=self.shuffle_id, epoch=epoch,
                                ranges=served, bytes=bytes_total)

    # -- merged-segment-first resolution (push-merge dataplane) ----------

    def _resolve_merged(self, my_index: int) -> Dict[int, list]:
        """Pick ONE live merged entry per partition (widest coverage
        first) and build the per-map skip sets. Returns entries grouped
        by hosting slot. Empty when push-merge is off, this is a
        map-range-SPLIT task (a merged segment holds every covered map's
        rows — it cannot be sliced to a map subset), or nothing has
        finalized yet."""
        if not self.conf.push_merge:
            return {}
        if (self.map_start, self.map_end) != (0, self.num_maps):
            return {}
        directory = self.endpoint.get_merged_directory(
            self.shuffle_id, metrics=self.metrics)
        if directory is None:
            return {}
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        members = self.endpoint.members()
        by_slot: Dict[int, list] = {}
        for p in range(self.start_partition, self.end_partition):
            if p in self._pushed_parts:
                # planned push already serves (some of) this partition;
                # a merged segment cannot be sliced around the pushed
                # maps, so the leftovers ride the per-map plane
                continue
            for entry in directory.entries(p):
                s = entry.slot
                if (s != my_index
                        and (s >= len(members) or members[s] == TOMBSTONE
                             or self.endpoint.peer_suspect(s))):
                    continue
                covered = entry.covered_maps(self.num_maps)
                if not covered:
                    continue
                by_slot.setdefault(s, []).append(entry)
                for m in covered:
                    self._skip.setdefault(m, set()).add(p)
                break
        return by_slot

    def _fetch_merged_from_slot(self, slot: int, entries: list,
                                my_index: int,
                                count_lock: threading.Lock) -> None:
        """Drain one replica slot's merged segments: ONE sequential
        vectored read per partition (local when this executor hosts the
        replica), entry-CRC verified; a failed or CRC-bad segment
        DEGRADES to the per-map dataplane for exactly that partition."""
        try:
            peer = None
            if slot != my_index:
                peer = self.endpoint.member_at(slot)
                self.endpoint.watch_peer(slot, peer)
            try:
                for entry in entries:
                    if self._aborted.is_set():
                        raise _Aborted()
                    data = self._merged_segment_data(peer, slot, entry,
                                                     my_index)
                    if data is None:
                        self.metrics.record_merged_fallback()
                        self.tracer.instant(
                            "fetch.merged_fallback", "fetch", peer=slot,
                            partition=entry.partition_id)
                        self._merged_fallback(entry, my_index, count_lock)
                        continue
                    self.metrics.record_merged(len(data))
                    p = entry.partition_id
                    if peer is None:
                        self.metrics.record_local(len(data))
                        with count_lock:
                            self._expected_results += 1
                        self._results.put(FetchResult(-2, p, p + 1, data,
                                                      is_local=True))
                    else:
                        with count_lock:
                            self._expected_results += 1
                        self._results.put(FetchResult(-2, p, p + 1, data))
            finally:
                if peer is not None:
                    self.endpoint.unwatch_peer(slot)
        except _Aborted:
            pass
        except Exception as e:  # noqa: BLE001 — same containment contract
            # as _fetch_from_peer: any thread failure must surface as a
            # FetchFailedError result, never a silent dead thread
            failure = (e if isinstance(e, FetchFailedError) else
                       FetchFailedError(self.shuffle_id, -2, slot,
                                        f"{type(e).__name__}: {e}"))
            self._results.put(FetchResult(failure=failure))
        finally:
            with count_lock:
                self._peer_threads_left -= 1
                last = self._peer_threads_left == 0
                if last:
                    self._results.put(FetchResult(is_sentinel=True))
            if last and self._aborted.is_set():
                self._drain_unconsumed()

    def _merged_segment_data(self, peer, slot: int, entry,
                             my_index: int) -> Optional[bytes]:
        """One merged segment's bytes, or None -> per-map fallback.
        Remote reads get the bounded transient-retry treatment but never
        ESCALATE from here — a dead replica degrades, it does not blame
        the hosting slot's map outputs (it owns none of them); at-rest
        rot (entry-CRC mismatch) degrades immediately (a refetch re-reads
        the same rotted file)."""
        import zlib
        blocks = [(entry.token, off, ln) for off, ln in entry.ranges]

        def crc_ok(data: bytes) -> bool:
            if zlib.crc32(data) == entry.crc32:
                return True
            self.metrics.record_checksum_failure()
            log.warning("merged segment for shuffle %d partition %d on "
                        "slot %d failed its entry CRC; degrading to "
                        "per-map fetch", self.shuffle_id,
                        entry.partition_id, slot)
            return False

        if peer is None:
            parts = []
            for token, off, ln in blocks:
                seg = (self.resolver.read_block(self.shuffle_id, token,
                                                off, ln)
                       if self.resolver is not None else None)
                if seg is None:
                    return None
                parts.append(seg)
            data = b"".join(parts)
            return data if crc_ok(data) else None
        attempts = 1 + max(0, self.conf.fetch_retry_budget)
        total = sum(ln for _, _, ln in blocks)
        # the in-flight byte gate covers merged reads like every other
        # remote fetch; the consumer's dequeue releases on success, every
        # other exit releases here
        self._acquire_in_flight(total)
        delivered = False
        try:
            data = None
            for attempt in range(attempts):
                if self._aborted.is_set():
                    raise _Aborted()
                if self.endpoint.peer_suspect(slot):
                    return None
                try:
                    self.metrics.record_request()
                    t0 = time.monotonic()
                    with self.tracer.span("fetch.merged", "fetch",
                                          peer=slot,
                                          partition=entry.partition_id,
                                          bytes=total):
                        data = self.endpoint.fetch_blocks(
                            peer, self.shuffle_id, blocks)
                    dt = time.monotonic() - t0
                    self.metrics.record_remote(len(data), dt)
                    if self.reader_stats is not None:
                        self.reader_stats.update(slot, dt,
                                                 nbytes=len(data))
                    break
                except (TransportError, TimeoutError) as e:
                    self._note_transient(e, "merged", slot,
                                         -2, attempt + 1 < attempts,
                                         attempt + 1)
                    if attempt + 1 >= attempts:
                        return None
                    if self._aborted.wait(self._backoff.delay(attempt)):
                        raise _Aborted()
            if data is None or not crc_ok(data):
                return None
            delivered = True
            return data
        finally:
            if not delivered:
                self._release_in_flight(total)

    def _merged_fallback(self, entry, my_index: int,
                         count_lock: threading.Lock) -> None:
        """Per-map fetch of ONE partition whose merged segment degraded:
        each covered map's bytes come from its table owner under the
        ordinary retry envelope, so blame and recovery semantics are
        exactly the per-map dataplane's (a dead owner escalates into
        FetchFailed -> recovery, which may re-point to ANOTHER replica)."""
        p = entry.partition_id
        for m in entry.covered_maps(self.num_maps):
            if not self.map_start <= m < self.map_end:
                continue
            e = self._table.entry(m)
            if e is None:
                raise FetchFailedError(self.shuffle_id, m, -1,
                                       "map output never published")
            owner = e[1]
            if owner == my_index:
                data = self._local_read(m, p, p + 1, my_index)
                self.metrics.record_local(len(data))
                with count_lock:
                    self._expected_results += 1
                self._results.put(FetchResult(m, p, p + 1, data,
                                              is_local=True))
                continue
            try:
                owner_peer = self.endpoint.member_at(owner)
            except DeadExecutorError as exc:
                raise FetchFailedError(
                    self.shuffle_id, m, owner,
                    f"merged replica degraded and owner tombstoned: "
                    f"{exc}") from exc

            def read_locs(m=m, owner_peer=owner_peer):
                self.metrics.record_request()
                self.metrics.record_metadata_rpc()
                return self.endpoint.fetch_output_range(
                    owner_peer, self.shuffle_id, m, p, p + 1)

            locs = self._with_retries("locations", owner, m, read_locs)
            blocks = [(loc.buf, loc.offset, loc.length) for loc in locs]
            nbytes = sum(b[2] for b in blocks)
            self._acquire_in_flight(nbytes)

            def read_blocks(m=m, owner_peer=owner_peer, blocks=blocks):
                self.metrics.record_request()
                return self.endpoint.fetch_blocks(
                    owner_peer, self.shuffle_id, blocks)

            try:
                data = self._with_retries("blocks", owner, m, read_blocks)
            except BaseException:
                self._release_in_flight(nbytes)
                raise
            self.metrics.record_remote(len(data), 0.0)
            with count_lock:
                self._expected_results += 1
            self._results.put(FetchResult(m, p, p + 1, data))

    # -- tiered (cold) resolution: the LAST rung before re-execution -----

    def _resolve_tiered(self, cold_maps: List[int], all_parts: set):
        """Plan the TIERED rung for maps no earlier rung can serve.

        Per partition, greedily pick blob entries (widest coverage
        first) whose ENTIRE covered map set is still needed there — a
        blob is the concatenation of all its covered maps' rows and
        cannot be sliced to a subset, exactly like a merged segment; an
        entry overlapping a map some earlier rung already serves is
        unusable (precedence: live owners never resolve tiered). A
        (map, partition) pair left uncovered escalates NOW as
        FetchFailedError — the rung below tiered is re-execution.

        Returns ``[(partition, entry, covered_maps)]`` restore tasks."""
        if not cold_maps:
            return []
        directory = self.endpoint.get_tiered_directory(
            self.shuffle_id, metrics=self.metrics)
        self._tiered_dir = directory
        need: Dict[int, set] = {
            m: {p for p in all_parts if p not in self._skip.get(m, set())}
            for m in cold_maps}
        tasks: List = []
        if directory is not None:
            for p in range(self.start_partition, self.end_partition):
                for entry in directory.entries(p):
                    covered = entry.covered_maps(self.num_maps)
                    if not covered:
                        continue
                    if any(m not in need or p not in need[m]
                           for m in covered):
                        continue  # overlaps a served map: unusable
                    tasks.append((p, entry, tuple(covered)))
                    for m in covered:
                        need[m].discard(p)
                        self._skip.setdefault(m, set()).add(p)
        for m in sorted(need):
            if need[m]:
                raise FetchFailedError(
                    self.shuffle_id, m, -1,
                    "map output never published and no cold coverage "
                    f"(partitions {sorted(need[m])})")
        return tasks

    def _blob_store(self):
        """The blob store for restores: the installed TieringService's
        (one handle per process) or a fresh one off the conf — a pure
        reducer (no merge role) still restores."""
        svc = getattr(self.endpoint, "tiering", None)
        if svc is not None and getattr(svc, "store", None) is not None:
            return svc.store
        from sparkrdma_tpu.shuffle.cold_tier import open_store
        return open_store(self.conf)

    def _fetch_tiered(self, tasks: List,
                      count_lock: threading.Lock) -> None:
        """Drain the tiered-restore plan: one blob GET per task under
        the bounded retry envelope, whole-blob CRC verified against the
        ledger CRC the entry carries. A missing/rotten/torn blob first
        tries a SIBLING blob with identical coverage (another merge
        target's upload of the same partition), then escalates as
        FetchFailedError blaming a covered map — the rung below is
        re-execution of exactly that map set, never corrupt output."""
        try:
            store = self._blob_store()
            if store is None:
                raise FetchFailedError(
                    self.shuffle_id, tasks[0][2][0] if tasks else -1, -1,
                    "cold tier unavailable (no blob store)")
            for p, entry, maps_served in tasks:
                if self._aborted.is_set():
                    raise _Aborted()
                data = self._tiered_blob_data(store, p, entry,
                                              maps_served)
                self.metrics.record_tiered(len(data))
                self.tracer.instant("fetch.tiered", "fetch",
                                    shuffle=self.shuffle_id, partition=p,
                                    bytes=len(data))
                self._emit_tiered(p, data, count_lock)
        except _Aborted:
            pass
        except Exception as e:  # noqa: BLE001 — same containment as the
            # peer threads: any failure surfaces as a result, never a
            # silent dead thread
            failure = (e if isinstance(e, FetchFailedError) else
                       FetchFailedError(self.shuffle_id, -3, -1,
                                        f"{type(e).__name__}: {e}"))
            self._results.put(FetchResult(failure=failure))
        finally:
            with count_lock:
                self._peer_threads_left -= 1
                last = self._peer_threads_left == 0
                if last:
                    self._results.put(FetchResult(is_sentinel=True))
            if last and self._aborted.is_set():
                self._drain_unconsumed()

    def _tiered_blob_data(self, store, p: int, entry,
                          maps_served) -> bytes:
        """One task's verified bytes. Store unavailability retries with
        backoff (the same transient envelope remote fetches get); a CRC
        mismatch or absence moves to the next candidate immediately (a
        re-get re-reads the same rotted bytes; absence is
        authoritative — the blob was reaped)."""
        import zlib
        candidates = [entry]
        directory = getattr(self, "_tiered_dir", None)
        if directory is not None:
            want = set(maps_served)
            candidates += [
                e for e in directory.entries(p)
                if e.blob_key != entry.blob_key
                and set(e.covered_maps(self.num_maps)) == want]
        attempts = 1 + max(0, self.conf.fetch_retry_budget)
        last_err = "no candidate blob"
        for cand in candidates:
            for attempt in range(attempts):
                if self._aborted.is_set():
                    raise _Aborted()
                try:
                    blob = store.get(cand.blob_key)
                except KeyError:
                    last_err = f"blob {cand.blob_key} absent (reaped?)"
                    break
                except OSError as e:
                    last_err = f"blob {cand.blob_key} unreadable: {e}"
                    if attempt + 1 < attempts:
                        self.metrics.record_retry()
                        if self._aborted.wait(self._backoff.delay(attempt)):
                            raise _Aborted()
                    continue
                if (len(blob) == cand.nbytes
                        and zlib.crc32(blob) == cand.crc32 & 0xFFFFFFFF):
                    return blob
                self.metrics.record_checksum_failure()
                last_err = f"blob {cand.blob_key} failed its ledger CRC"
                log.warning("tiered blob for shuffle %d partition %d "
                            "failed verification (%s); degrading",
                            self.shuffle_id, p, last_err)
                break
        self.metrics.record_tiered_fallback()
        # "cold_unusable": every candidate blob for this partition was
        # rotten, torn, or gone — recovery must NOT re-point the map
        # back at the same directory entries (that would retry the same
        # dead blob forever); re-executing publishes a repair, which
        # drops the bad entries driver-side
        raise FetchFailedError(
            self.shuffle_id, maps_served[0], -1,
            f"tiered restore of partition {p} failed: {last_err}",
            verdict="cold_unusable")

    def _emit_tiered(self, p: int, data: bytes,
                     count_lock: threading.Lock) -> None:
        """One restored partition through the ordinary pool-leased
        landing: the blob's bytes copy into ONE RegisteredBuffer lease
        (BufferPool accounting, tenant-charged) exactly like a vectored
        response; no pool means plain bytes. map_id -3 marks the cold
        dataplane (merged reads use -2)."""
        payload, lease = data, None
        if self.pool is not None and len(data):
            lease = self.pool.get_registered(len(data),
                                             tenant=self.tenant)
            view = lease.slice(len(data))
            view[:] = np.frombuffer(data, dtype=np.uint8)
            payload = view
        with count_lock:
            self._expected_results += 1
        self._results.put(FetchResult(-3, p, p + 1, payload,
                                      is_local=True, lease=lease))
        if lease is not None:
            lease.release()

    # -- per-peer fetch pipeline ----------------------------------------

    def _fetch_from_peer(self, exec_idx: int, maps: List[int],
                         count_lock: threading.Lock) -> None:
        try:
            peer = self.endpoint.member_at(exec_idx)
            depth = self.conf.resolved_read_ahead_depth()
            # register heartbeat interest for the duration of the fetch:
            # if the peer dies silently mid-window, the monitor closes the
            # connection (failing the window NOW) and marks the slot
            # suspect so the retry envelope escalates instead of re-dialing
            self.endpoint.watch_peer(exec_idx, peer)
            try:
                served = False
                if self.conf.coalesce_reads:
                    served = self._fetch_coalesced(peer, exec_idx, maps,
                                                   count_lock, depth)
                if not served:
                    if depth <= 1:
                        self._fetch_sequential(peer, exec_idx, maps,
                                               count_lock)
                    else:
                        self._fetch_pipelined(peer, exec_idx, maps,
                                              count_lock, depth)
            finally:
                self.endpoint.unwatch_peer(exec_idx)
        except _Aborted:
            pass  # consumer went away; exit quietly
        except Exception as e:  # noqa: BLE001 — ANY peer-thread failure must
            # surface as a FetchFailedError result, never a silent dead
            # thread (which would truncate the reduce input undetected)
            failure = (e if isinstance(e, FetchFailedError) else
                       FetchFailedError(self.shuffle_id,
                                        maps[0] if maps else -1,
                                        exec_idx, f"{type(e).__name__}: {e}"))
            self._results.put(FetchResult(failure=failure))
        finally:
            with count_lock:
                self._peer_threads_left -= 1
                last = self._peer_threads_left == 0
                if last:
                    self._results.put(FetchResult(is_sentinel=True))
            # an aborted iteration stops consuming: once nothing more
            # can be enqueued, pool leases parked in the queue must be
            # returned (close() drains too, but a completion racing it
            # can land after that drain — this one cannot be raced)
            if last and self._aborted.is_set():
                self._drain_unconsumed()

    def _group_locations(self, exec_idx: int, m: int,
                         locs) -> List[_PendingFetch]:
        """STEP 3 grouping: consecutive partitions, ≤ read block size
        (:240-263). Zero-length blocks ride along byte-free but still
        count toward a block-count bound so a wide, mostly-empty
        partition range can't build a request frame past the native
        server's inbound frame cap — the bound is DERIVED from that cap
        (csrc/blockserver.cpp kMaxReqFrame via
        ``resolved_max_fetch_blocks``), not a constant that can drift
        from the C++ limit."""
        pending: List[_PendingFetch] = []
        group: List = []
        group_start = self.start_partition
        group_bytes = 0
        limit = self.conf.shuffle_read_block_size
        max_blocks = self.conf.resolved_max_fetch_blocks()
        # push-merge: partitions a merged segment already serves are
        # skipped (groups seal at the hole so ranges stay contiguous).
        # getattr: unit tests build bare fetchers around this method
        skip = getattr(self, "_skip", {}).get(m, ())
        for i, loc in enumerate(locs):
            p = self.start_partition + i
            if p in skip:
                if group:
                    pending.append(_PendingFetch(
                        exec_idx, m, group_start, p, group, group_bytes))
                    group, group_bytes = [], 0
                group_start = p + 1
                continue
            if group and (group_bytes + loc.length > limit
                          or len(group) >= max_blocks):
                pending.append(_PendingFetch(
                    exec_idx, m, group_start, p, group, group_bytes))
                group, group_start, group_bytes = [], p, 0
            group.append((loc.buf, loc.offset, loc.length))
            group_bytes += loc.length
        if group:
            pending.append(_PendingFetch(
                exec_idx, m, group_start,
                self.start_partition + len(locs), group, group_bytes))
        return pending

    # -- coalesced dataplane (per-peer batching at both levels) ----------

    def _coalesce_plan(self, exec_idx: int,
                       groups: List[_PendingFetch]) -> List[_VectoredFetch]:
        """Merge per-map groups bound for one peer into vectored requests
        of at most ``max_vectored_bytes`` (floored at the per-map read
        block size — coalescing must never shrink a request the per-map
        planner would have sent whole) and the frame-derived block-count
        cap. A single oversized group still rides alone, preserving the
        per-map path's single-oversized-fetch escape."""
        # clamp to what the servers will actually serve: multi-block
        # responses past max(256 MiB, read block size) are answered
        # BAD_RANGE — authoritative, so an oversized plan would re-fail
        # identically on every stage retry (endpoints._MAX_RESP_PAYLOAD,
        # csrc kMaxRespPayload)
        from sparkrdma_tpu.parallel.endpoints import ExecutorEndpoint
        limit = max(min(self.conf.max_vectored_bytes,
                        ExecutorEndpoint._MAX_RESP_PAYLOAD),
                    self.conf.shuffle_read_block_size)
        max_blocks = self.conf.resolved_max_fetch_blocks()
        plan: List[_VectoredFetch] = []
        cur: List[_PendingFetch] = []
        cur_bytes = cur_blocks = 0

        def seal():
            plan.append(_VectoredFetch(
                exec_idx, list(cur), [b for s in cur for b in s.blocks],
                cur_bytes))

        for g in groups:
            if cur and (cur_bytes + g.total_bytes > limit
                        or cur_blocks + len(g.blocks) > max_blocks):
                seal()
                cur, cur_bytes, cur_blocks = [], 0, 0
            cur.append(g)
            cur_bytes += g.total_bytes
            cur_blocks += len(g.blocks)
        if cur:
            seal()
        return plan

    def _fetch_coalesced(self, peer, exec_idx: int, maps: List[int],
                         count_lock: threading.Lock, depth: int) -> bool:
        """The coalesced dataplane for one peer: ONE batched location RPC
        (chunked only past the endpoint's response-size bound), then
        vectored cross-map data reads through the read-ahead window.
        Returns False — caller falls back to the per-map dataplane —
        when the first batched call fails at the transport level TWICE
        (one guarded retry absorbs a transient blip): a mixed-version
        peer doesn't know the frame type and tears the connection down
        on every attempt, which lands here as TransportErrors. Later
        failures ride the normal retry envelope (the peer has already
        proven it speaks the batched protocol)."""
        # cache-first resolution (location_plane): maps whose entries are
        # already held under the current epoch never touch the wire —
        # the warm path resolves the WHOLE peer from cache and issues
        # zero metadata RPCs
        plane = self.endpoint.location_plane
        locs_by_map: Dict[int, List] = {}
        uncached: List[int] = []
        for m in maps:
            locs = plane.locations(self.shuffle_id, m,
                                   self.start_partition, self.end_partition)
            if locs is None:
                uncached.append(m)
            else:
                locs_by_map[m] = locs
        if locs_by_map:
            self.metrics.record_location_hit(len(locs_by_map))
        per = self.endpoint.outputs_batch_maps(self.start_partition,
                                               self.end_partition)
        try:
            for i in range(0, len(uncached), per):
                chunk = uncached[i:i + per]

                def read_chunk(chunk=chunk):
                    self.metrics.record_request()
                    self.metrics.record_metadata_rpc()
                    with self.tracer.span("fetch.locations", "fetch",
                                          peer=exec_idx, maps=len(chunk),
                                          batched=True):
                        return self.endpoint.fetch_outputs(
                            peer, self.shuffle_id, chunk,
                            self.start_partition, self.end_partition)

                if i == 0:
                    self._suspect_check(exec_idx, chunk[0])
                    try:
                        fetched = read_chunk()
                    except FetchStatusError:
                        raise
                    except (TransportError, TimeoutError) as e:
                        # one guarded retry separates a transient blip
                        # from a genuine mixed-version peer: demoting a
                        # new-version peer to the per-map dataplane over
                        # one dropped connection would silently erase the
                        # RPC reduction for the whole reduce. A zero
                        # retry budget means fail-fast everywhere — honor
                        # it here too (straight to the per-map fallback)
                        if self.conf.fetch_retry_budget <= 0:
                            raise
                        self._suspect_check(exec_idx, chunk[0])
                        self._note_transient(e, "locations", exec_idx,
                                             chunk[0], True, 1)
                        if self._aborted.wait(self._backoff.delay(0)):
                            raise _Aborted()
                        fetched = read_chunk()
                else:
                    fetched = self._with_retries(
                        "locations", exec_idx, chunk[0], read_chunk)
                locs_by_map.update(fetched)
                for m, locs in fetched.items():
                    plane.put_locations(self.shuffle_id, m,
                                        self.start_partition,
                                        self.end_partition, locs,
                                        self.epoch)
        except FetchStatusError as e:
            # authoritative per-map answer (unknown map / bad range): the
            # per-map path would re-fail identically — escalate now
            # (_fail blames the exact map the peer named when the status
            # carries one)
            self._fail("locations", exec_idx, maps[0], 1, e)
        except (TransportError, TimeoutError) as e:
            # a suspect verdict is what FAILED the batched call (the
            # monitor closed the connection under it): falling back would
            # re-dial a fresh connection the monitor never closes and
            # wait out the full request deadline — escalate now instead
            self._suspect_check(exec_idx, maps[0])
            log.debug("batched location fetch from peer %d failed (%s); "
                      "falling back to the per-map dataplane", exec_idx, e)
            self.tracer.instant("fetch.coalesce_fallback", "fetch",
                                peer=exec_idx, error=type(e).__name__)
            return False
        groups: List[_PendingFetch] = []
        for m in maps:
            groups.extend(self._group_locations(exec_idx, m,
                                                locs_by_map[m]))
        plan = self._coalesce_plan(exec_idx, groups)
        # randomized issue order (:74-79), at vectored-request granularity
        self._rng.shuffle(plan)
        with count_lock:
            self._expected_results += sum(len(v.segments) for v in plan)
        # 4th resolution engine: the native client (csrc/fetchclient.cpp)
        # lands response payloads directly in lease memory — engaged only
        # where the wire bytes ARE the lease bytes (native block port, no
        # wire compression/codec, pool present). Declines (engine not
        # built, connect failure) fall through to the Python dispatch.
        if (self._native_fetch_usable(peer)
                and self._fetch_vectored_native(peer, exec_idx, plan,
                                                depth)):
            return True
        if depth <= 1:
            self._fetch_vectored_sequential(peer, exec_idx, plan)
        else:
            self._fetch_vectored_windowed(peer, exec_idx, plan, depth)
        return True

    def _fetch_vectored_sequential(self, peer, exec_idx: int,
                                   plan: List[_VectoredFetch]) -> None:
        for vf in plan:
            if self._aborted.is_set():
                raise _Aborted()
            # same pre-issue fail-fast as the windowed path: the first
            # attempt dials outside the retry envelope, and a fresh
            # post-verdict connection is one the monitor never closes
            self._suspect_check(exec_idx, vf.segments[0].map_id)
            self._acquire_in_flight(vf.total_bytes)
            t0 = time.monotonic()
            try:
                with self.tracer.span("fetch.vectored", "fetch",
                                      peer=exec_idx,
                                      maps=len(vf.segments),
                                      blocks=len(vf.blocks),
                                      bytes=vf.total_bytes):
                    data = self._vectored_data(peer, exec_idx, vf)
            except BaseException:
                self._release_in_flight(vf.total_bytes)
                raise
            dt = time.monotonic() - t0
            self.metrics.record_remote(len(data), dt)
            if self.reader_stats is not None:
                self.reader_stats.update(exec_idx, dt, nbytes=len(data))
            self._emit_vectored(vf, data)

    def _fetch_vectored_windowed(self, peer, exec_idx: int,
                                 plan: List[_VectoredFetch],
                                 depth: int) -> None:
        """The read-ahead window over vectored requests: locations are
        already in hand (one batched RPC), so the window carries only
        STEP-3 data reads — same budget interplay as the per-map
        pipelined path (never block on the byte gate while holding
        completions)."""
        ready: deque = deque((vf, time.monotonic()) for vf in plan)
        inflight: deque = deque()  # (vf, AsyncFetch, t_ready, t_issue)
        try:
            while ready or inflight:
                if self._aborted.is_set():
                    raise _Aborted()
                while ready and len(inflight) < depth:
                    vf, t_ready = ready[0]
                    # never issue into a suspect peer: a request on a
                    # fresh post-verdict connection would wait out its
                    # whole deadline (the monitor only closes cached
                    # connections once, at verdict time)
                    self._suspect_check(exec_idx, vf.segments[0].map_id)
                    if not self._try_acquire_in_flight(
                            vf.total_bytes, nonblocking=bool(inflight)):
                        break
                    ready.popleft()
                    t_issue = time.monotonic()
                    self.metrics.record_request()
                    handle = self.endpoint.fetch_blocks_async(
                        peer, self.shuffle_id, vf.blocks)
                    inflight.append((vf, handle, t_ready, t_issue))
                    self.pipeline.record_issue(exec_idx, len(inflight),
                                               t_issue - t_ready)
                if inflight:
                    self._complete_oldest_vectored(peer, exec_idx, inflight)
        except BaseException:
            # same unwind contract as _fetch_pipelined: window-held budget
            # and send-budget slots must not outlive the window
            for vf, handle, _tr, _ti in inflight:
                handle.cancel()
                self._release_in_flight(vf.total_bytes)
            raise

    def _complete_oldest_vectored(self, peer, exec_idx: int,
                                  inflight: deque) -> None:
        vf, handle, t_ready, t_issue = inflight[0]
        wire_done_s = None
        try:
            data = handle.result()
            wire_done_s = handle.wire_done_s
        except (TransportError, TimeoutError, AssertionError) as e:
            inflight.popleft()
            t_issue = time.monotonic()  # latency covers the serving retry
            try:
                data = self._vectored_data(peer, exec_idx, vf,
                                           first_error=e)
            except BaseException:
                self._release_in_flight(vf.total_bytes)
                raise
        else:
            inflight.popleft()
        now = time.monotonic()
        dt = now - t_issue
        self.metrics.record_remote(len(data), dt)
        if self.reader_stats is not None:
            self.reader_stats.update(exec_idx, dt, nbytes=len(data))
        if self.tracer.enabled:
            end_us = self.tracer.now_us()
            issue_us = end_us - (now - t_issue) * 1e6
            ready_us = end_us - (now - t_ready) * 1e6
            wire_us = (end_us - (now - wire_done_s) * 1e6
                       if wire_done_s is not None else end_us)
            wire_us = min(max(wire_us, issue_us), end_us)
            map0 = vf.segments[0].map_id
            # the per-map pipelined path's issue→wire→complete contract
            # is kept (one trace schema either way); fetch.vectored adds
            # the coalescing shape on top
            self.tracer.complete_span("fetch.issue", "fetch",
                                      ready_us, issue_us,
                                      map=map0, peer=exec_idx)
            self.tracer.complete_span("fetch.blocks", "fetch",
                                      issue_us, wire_us, map=map0,
                                      peer=exec_idx, bytes=vf.total_bytes)
            self.tracer.complete_span("fetch.complete", "fetch",
                                      wire_us, end_us,
                                      map=map0, peer=exec_idx)
            self.tracer.complete_span("fetch.vectored", "fetch",
                                      issue_us, end_us, peer=exec_idx,
                                      maps=len(vf.segments),
                                      blocks=len(vf.blocks),
                                      bytes=vf.total_bytes)
        self._emit_vectored(vf, data)

    # -- native client engine (csrc/fetchclient.cpp) ---------------------

    def _native_fetch_usable(self, peer) -> bool:
        """The native engine engages only where the wire bytes are
        already exactly the lease bytes: a pool to lease from, the peer
        advertising a native block port, and nothing (compression, wire
        codec) transforming payloads between the wire and the reader."""
        if not (self.conf.native_fetch and self.pool is not None):
            return False
        if not getattr(peer, "block_port", 0) or self.conf.wire_compress:
            return False
        if getattr(self.endpoint, "_codec", None) is not None:
            return False
        from sparkrdma_tpu.shuffle.native_fetch import NativeFetchEngine
        return NativeFetchEngine.available()

    def _fetch_vectored_native(self, peer, exec_idx: int,
                               plan: List[_VectoredFetch],
                               depth: int) -> bool:
        """Drive one peer's vectored plan through the native client
        engine: requests are doorbell-batched (one writev carries up to
        ``fetch_doorbell_batch`` frames) and each response payload is
        scattered by the C epoll loop straight into a pool lease — no
        Python bytes object, no copy; ``_emit_vectored_lease`` just
        hands out views. CRC trailers verify in C.

        Returns False only before any request was consumed (engine not
        built, dial failed) — the caller then runs the ordinary Python
        dispatch. Once engaged it always returns True: happy-path
        requests complete natively, and ANY anomaly (connection death,
        truncation, CRC mismatch, non-OK status) re-runs that request
        through ``_vectored_data``'s retry/suspect/checksum envelope,
        so failure behavior stays byte-identical with the Python path.
        A dead connection degrades the not-yet-issued remainder of the
        plan to the Python dispatch too."""
        from sparkrdma_tpu.shuffle import native_fetch as nf
        try:
            eng = nf.NativeFetchEngine()
        except RuntimeError:
            return False
        conn = eng.connect(peer.rpc_host, peer.block_port,
                           timeout_ms=self.conf.connect_timeout_ms)
        if not conn:
            eng.close()
            return False
        deadline_s = self.conf.resolved_request_deadline_s()
        batch = max(1, self.conf.fetch_doorbell_batch)
        window = max(1, depth)
        ready: deque = deque(plan)
        outstanding: Dict[int, tuple] = {}  # req_id -> (vf, lease, t_issue)
        next_req = 1
        unsent = 0
        try:
            while (ready and eng.alive(conn)) or outstanding:
                if self._aborted.is_set():
                    raise _Aborted()
                while (ready and len(outstanding) < window
                       and eng.alive(conn)):
                    vf = ready[0]
                    # same pre-issue fail-fast as the Python paths
                    self._suspect_check(exec_idx, vf.segments[0].map_id)
                    if not self._try_acquire_in_flight(
                            vf.total_bytes,
                            nonblocking=bool(outstanding)):
                        break
                    ready.popleft()
                    lease = addr = None
                    if vf.total_bytes:
                        lease = self.pool.get_registered(vf.total_bytes,
                                                         tenant=self.tenant)
                        addr = lease._buf.view.ctypes.data
                    req_id, next_req = next_req, next_req + 1
                    self.metrics.record_request()
                    t_issue = time.monotonic()
                    rc = eng.submit(conn, req_id, self.shuffle_id,
                                    vf.blocks, addr, vf.total_bytes)
                    if rc != 0:
                        # rejected before the wire (dead conn, frame too
                        # big): this request runs through the Python
                        # envelope; the rest keep their native path
                        if lease is not None:
                            lease.release()
                        self._vectored_fallback(
                            peer, exec_idx, vf,
                            TransportError(
                                f"native fetch submit failed rc={rc}"),
                            t_issue)
                        continue
                    outstanding[req_id] = (vf, lease, t_issue)
                    unsent += 1
                    if unsent >= batch:
                        eng.flush()
                        unsent = 0
                if unsent:
                    eng.flush()  # ring the doorbell on a partial batch
                    unsent = 0
                if not outstanding:
                    continue
                comps = eng.poll(timeout_ms=50)
                now = time.monotonic()
                for c in comps:
                    ent = outstanding.pop(c.req_id, None)
                    if ent is not None:
                        vf, lease, t_issue = ent
                        self._finish_native(peer, exec_idx, vf, lease, c,
                                            now - t_issue)
                if outstanding and not comps:
                    oldest = min(t for _v, _l, t in outstanding.values())
                    if now - oldest > deadline_s:
                        # server stalled under the oldest request: kill
                        # the connection — every in-flight request fails
                        # over to the Python envelope via its kErrConn
                        # completion, the unissued rest degrade below
                        eng.close_conn(conn)
        except BaseException:
            # unwind contract: window budget and leases held by requests
            # that will never complete must not outlive this call
            for vf, lease, _t in outstanding.values():
                if lease is not None:
                    lease.release()
                self._release_in_flight(vf.total_bytes)
            raise
        finally:
            eng.close()
        if ready:  # connection died: Python dispatch for the remainder
            leftovers = list(ready)
            if depth <= 1:
                self._fetch_vectored_sequential(peer, exec_idx, leftovers)
            else:
                self._fetch_vectored_windowed(peer, exec_idx, leftovers,
                                              depth)
        return True

    def _finish_native(self, peer, exec_idx: int, vf: _VectoredFetch,
                       lease, comp, dt: float) -> None:
        """Settle one native completion: emit zero-copy on the happy
        path, otherwise release the lease and re-run the request through
        the Python envelope (which re-classifies the failure itself —
        per-block CRC blame, corrupt-output isolation, retry budget)."""
        if (comp.status == STATUS_OK and comp.crc_state >= 0
                and comp.nbytes == vf.total_bytes):
            self.metrics.record_remote(vf.total_bytes, dt)
            if self.reader_stats is not None:
                self.reader_stats.update(exec_idx, dt,
                                         nbytes=vf.total_bytes)
            if self.tracer.enabled:
                end_us = self.tracer.now_us()
                issue_us = end_us - dt * 1e6
                self.tracer.complete_span("fetch.vectored", "fetch",
                                          issue_us, end_us, peer=exec_idx,
                                          maps=len(vf.segments),
                                          blocks=len(vf.blocks),
                                          bytes=vf.total_bytes,
                                          native=True)
            self._emit_vectored_lease(vf, lease)
            return
        if lease is not None:
            lease.release()
        if comp.crc_state < 0:
            # C-side CRC mismatch: the Python refetch re-verifies and —
            # if the rot persists — raises the per-block ChecksumError
            # the heal path wants, so blame lands on the right map
            self.metrics.record_checksum_failure()
            err = None
        elif comp.status > 0:
            # the server named a status: refetch fresh so the Python
            # client classifies it (BAD_RANGE size-cap retry, CORRUPT
            # isolation) exactly as it would its own response
            err = None
        else:
            err = TransportError("native fetch engine: connection "
                                 f"failed (status {comp.status})")
        self._vectored_fallback(peer, exec_idx, vf, err, time.monotonic())

    def _vectored_fallback(self, peer, exec_idx: int, vf: _VectoredFetch,
                           err: Optional[BaseException],
                           t_issue: float) -> None:
        """Re-run one request through the Python envelope — the same
        contract torn async fetches use in _complete_oldest_vectored."""
        try:
            data = self._vectored_data(peer, exec_idx, vf,
                                       first_error=err)
        except BaseException:
            self._release_in_flight(vf.total_bytes)
            raise
        dt = time.monotonic() - t_issue
        self.metrics.record_remote(len(data), dt)
        if self.reader_stats is not None:
            self.reader_stats.update(exec_idx, dt, nbytes=len(data))
        self._emit_vectored(vf, data)

    def _emit_vectored_lease(self, vf: _VectoredFetch, lease) -> None:
        """Slice per-(map, range) results off an ALREADY-FILLED lease:
        the native engine scattered the response payload into the
        lease's backing buffer in request order, the same order
        ``slice`` bump-allocates — handing out views is the whole job.
        ``lease`` is None only for an all-empty request."""
        for seg in vf.segments:
            payload = (lease.slice(seg.total_bytes)
                       if lease is not None else b"")
            self._results.put(FetchResult(
                seg.map_id, seg.start_partition, seg.end_partition,
                payload, lease=lease))
        if lease is not None:
            lease.release()  # creator's ref; results hold theirs

    def _vectored_data(self, peer, exec_idx: int, vf: _VectoredFetch,
                       first_error: Optional[BaseException] = None) -> bytes:
        """The payload of one vectored request, healed: a CRC failure
        that names its bad blocks refetches ONLY the affected segments
        (per-map blame); anything else retries whole-request under the
        envelope, blamed on the request's first map."""

        def read_all():
            self.metrics.record_request()
            return self.endpoint.fetch_blocks(peer, self.shuffle_id,
                                              vf.blocks)

        err = first_error
        if err is None:
            try:
                return read_all()
            except (TransportError, TimeoutError, AssertionError) as e:
                err = e
        if (isinstance(err, ChecksumError) and err.bad_blocks is not None
                and err.body is not None and len(vf.segments) > 1):
            return self._heal_vectored(peer, exec_idx, vf, err)
        if (isinstance(err, FetchStatusError)
                and err.status == STATUS_CORRUPT and len(vf.segments) > 1):
            return self._isolate_corrupt_vectored(peer, exec_idx, vf)
        return self._with_retries("blocks", exec_idx,
                                  vf.segments[0].map_id, read_all,
                                  first_error=err)

    def _isolate_corrupt_vectored(self, peer, exec_idx: int,
                                  vf: _VectoredFetch) -> bytes:
        """A server-side at-rest CORRUPT verdict covers a whole vectored
        response (the serve aborts before sending any torn byte), so a
        multi-map request can't tell WHICH map's committed output rotted.
        Refetch each segment alone: healthy maps keep their bytes, and
        the corrupt one fails under the envelope with ITS map charged —
        the re-execution (corrupt_output verdict) then recomputes exactly
        the rotten output, not the first map that happened to share the
        frame."""
        parts: List[bytes] = []
        for seg in vf.segments:

            def refetch(seg=seg):
                self.metrics.record_request()
                with self.tracer.span("fetch.refetch_range", "fault",
                                      map=seg.map_id, peer=exec_idx,
                                      bytes=seg.total_bytes,
                                      blocks=len(seg.blocks)):
                    return self.endpoint.fetch_blocks(
                        peer, self.shuffle_id, seg.blocks)

            parts.append(self._with_retries("blocks", exec_idx, seg.map_id,
                                            refetch))
        return b"".join(parts)

    def _heal_vectored(self, peer, exec_idx: int, vf: _VectoredFetch,
                       err: ChecksumError) -> bytes:
        """Salvage a partially-corrupt vectored response: segments whose
        sub-blocks all verified keep their bytes from ``err.body``; each
        affected segment refetches alone under the retry envelope with
        ITS map charged (retry counters, trace events, and — on
        exhaustion — the FetchFailedError all blame the map that owns
        the corrupt range, not the whole request)."""
        bad = set(err.bad_blocks)
        parts: List[Optional[bytes]] = []
        dirty: List[int] = []
        pos = block_index = 0
        for si, seg in enumerate(vf.segments):
            nblocks = len(seg.blocks)
            if bad.isdisjoint(range(block_index, block_index + nblocks)):
                parts.append(err.body[pos:pos + seg.total_bytes])
            else:
                parts.append(None)
                dirty.append(si)
            pos += seg.total_bytes
            block_index += nblocks
        for si in dirty:
            seg = vf.segments[si]

            def refetch(seg=seg):
                self.metrics.record_request()
                with self.tracer.span("fetch.refetch_range", "fault",
                                      map=seg.map_id, peer=exec_idx,
                                      bytes=seg.total_bytes,
                                      blocks=len(seg.blocks)):
                    return self.endpoint.fetch_blocks(
                        peer, self.shuffle_id, seg.blocks)

            # the vectored attempt was attempt one FOR EACH affected
            # segment: charge it so the budget spans the same wall-clock
            # either way and the retry counters attribute per map
            parts[si] = self._with_retries("blocks", exec_idx, seg.map_id,
                                           refetch, first_error=err)
        return b"".join(parts)

    def _emit_vectored(self, vf: _VectoredFetch, data: bytes) -> None:
        """Slice one vectored payload back into per-(map, range) results.
        With a pool, the whole response lands in ONE refcounted
        multi-view lease (each result holds a reference; the buffer
        returns to the pool on the last consumer's ``free``)."""
        lease = None
        if self.pool is not None and vf.total_bytes:
            lease = self.pool.get_registered(vf.total_bytes,
                                             tenant=self.tenant)
        pos = 0
        for seg in vf.segments:
            n = seg.total_bytes
            if lease is not None:
                view = lease.slice(n)
                if n:
                    view[:] = np.frombuffer(data, dtype=np.uint8,
                                            count=n, offset=pos)
                payload = view
            else:
                payload = data[pos:pos + n]
            pos += n
            self._results.put(FetchResult(
                seg.map_id, seg.start_partition, seg.end_partition,
                payload, lease=lease))
        if lease is not None:
            lease.release()  # creator's ref; results hold theirs

    # -- retry envelope (deadline + backoff, transient vs fatal) ---------

    def _suspect_check(self, exec_idx: int, map_id: int) -> None:
        if self.endpoint.peer_suspect(exec_idx):
            raise FetchFailedError(
                self.shuffle_id, map_id, exec_idx,
                "peer declared suspect by the heartbeat monitor")

    def _note_transient(self, e: BaseException, what: str, exec_idx: int,
                        map_id: int, will_retry: bool, attempt: int) -> None:
        if isinstance(e, ChecksumError):
            self.metrics.record_checksum_failure()
            if self.reader_stats is not None:
                self.reader_stats.failures.incr("checksum_mismatches")
        if will_retry:
            self.metrics.record_retry()
            if self.reader_stats is not None:
                self.reader_stats.failures.incr("fetch_retries")
            self.tracer.instant("fetch.retry", "fault", what=what,
                                peer=exec_idx, map=map_id,
                                attempt=attempt, error=type(e).__name__)
            log.debug("fetch retry %d (%s, map %d, peer %d): %s",
                      attempt, what, map_id, exec_idx, e)

    def _fail(self, what: str, exec_idx: int, map_id: int, consumed: int,
              err: BaseException):
        self.metrics.record_failure()
        if self.reader_stats is not None:
            self.reader_stats.failures.incr("fetch_failures")
        # an authoritative status that names its map (batched location
        # responses do) beats the caller's request-level blame
        named = getattr(err, "map_id", None)
        if isinstance(named, int):
            map_id = named
        verdict = ("corrupt_output"
                   if getattr(err, "status", None) == STATUS_CORRUPT
                   else "peer_lost")
        # staleness backstop: whatever location view led here is now
        # suspect — drop it (warm cached BYTES included) so the
        # post-recovery retry re-syncs a fresh snapshot instead of
        # re-serving the cache that just failed (covers a lost epoch
        # push: invalidation by failure, the hard way, costs one refetch
        # — never a wrong result)
        self.endpoint.location_plane.invalidate(self.shuffle_id)
        from sparkrdma_tpu.shuffle import dist_cache
        dist_cache.drop(self.shuffle_id)
        raise FetchFailedError(
            self.shuffle_id, map_id, exec_idx,
            f"{what} failed after {consumed} attempt(s): {err}",
            verdict=verdict) from err

    def _with_retries(self, what: str, exec_idx: int, map_id: int, fn,
                      first_error: Optional[BaseException] = None):
        """Run one remote call under the failure policy: TRANSIENT
        outcomes (connection loss, connect refusal, request deadline,
        CRC mismatch, transient server status) retry with exponential
        backoff + jitter up to ``fetch_retry_budget``; FATAL outcomes
        (suspect peer, authoritative non-OK status, protocol bugs)
        escalate immediately as :class:`FetchFailedError` so
        ``run_reduce_with_retry`` recomputes the stage. ``first_error``
        charges an already-failed async attempt against the budget (the
        pipelined window's in-flight issue was attempt one)."""
        attempts = 1 + max(0, self.conf.fetch_retry_budget)
        consumed = 0
        if first_error is not None:
            consumed = 1
            retryable = (getattr(first_error, "retryable", True)
                         and not isinstance(first_error, AssertionError))
            self._note_transient(first_error, what, exec_idx, map_id,
                                 retryable and consumed < attempts, consumed)
            if not retryable or consumed >= attempts:
                self._fail(what, exec_idx, map_id, consumed, first_error)
            self._suspect_check(exec_idx, map_id)
            if self._aborted.wait(self._backoff.delay(consumed - 1)):
                raise _Aborted()
        while True:
            if self._aborted.is_set():
                raise _Aborted()
            self._suspect_check(exec_idx, map_id)
            try:
                return fn()
            except (TransportError, TimeoutError, AssertionError) as e:
                consumed += 1
                retryable = (getattr(e, "retryable", True)
                             and not isinstance(e, AssertionError))
                self._note_transient(e, what, exec_idx, map_id,
                                     retryable and consumed < attempts,
                                     consumed)
                if not retryable or consumed >= attempts:
                    self._fail(what, exec_idx, map_id, consumed, e)
                if self._aborted.wait(self._backoff.delay(consumed - 1)):
                    raise _Aborted()

    def _fetch_sequential(self, peer, exec_idx: int, maps: List[int],
                          count_lock: threading.Lock) -> None:
        """``read_ahead_depth=1``: the fully serialized fetch — every
        location read then every data read, one at a time. Kept verbatim
        as the regression escape hatch the pipelined path is diffed
        against."""
        plane = self.endpoint.location_plane
        pending: List[_PendingFetch] = []
        for m in maps:
            # STEP 2: block locations (:293-315) — cache-first: an
            # epoch-current cached range resolves without the wire
            locs = plane.locations(self.shuffle_id, m,
                                   self.start_partition,
                                   self.end_partition)
            if locs is not None:
                self.metrics.record_location_hit()
                pending.extend(self._group_locations(exec_idx, m, locs))
                continue

            def read_locs(m=m):
                self.metrics.record_request()
                self.metrics.record_metadata_rpc()
                with self.tracer.span("fetch.locations", "fetch",
                                      map=m, peer=exec_idx):
                    return self.endpoint.fetch_output_range(
                        peer, self.shuffle_id, m,
                        self.start_partition, self.end_partition)

            locs = self._with_retries("locations", exec_idx, m, read_locs)
            plane.put_locations(self.shuffle_id, m, self.start_partition,
                                self.end_partition, locs, self.epoch)
            pending.extend(self._group_locations(exec_idx, m, locs))
        self._rng.shuffle(pending)
        with count_lock:
            self._expected_results += len(pending)
        for fetch in pending:
            if self._aborted.is_set():
                raise _Aborted()
            self._acquire_in_flight(fetch.total_bytes)
            t0 = time.monotonic()

            def read_blocks(fetch=fetch):
                self.metrics.record_request()
                with self.tracer.span("fetch.blocks", "fetch",
                                      map=fetch.map_id, peer=exec_idx,
                                      bytes=fetch.total_bytes):
                    return self.endpoint.fetch_blocks(
                        peer, self.shuffle_id, fetch.blocks)

            try:
                data = self._with_retries("blocks", exec_idx, fetch.map_id,
                                          read_blocks)
            except BaseException:
                # envelope exhausted (FetchFailedError) or abort: this
                # fetch's budget must not leak past its failure
                self._release_in_flight(fetch.total_bytes)
                raise
            dt = time.monotonic() - t0
            self.metrics.record_remote(len(data), dt)
            if self.reader_stats is not None:
                self.reader_stats.update(exec_idx, dt, nbytes=len(data))
            self._results.put(FetchResult(
                fetch.map_id, fetch.start_partition, fetch.end_partition,
                data))

    def _fetch_pipelined(self, peer, exec_idx: int, maps: List[int],
                         count_lock: threading.Lock, depth: int) -> None:
        """Bounded read-ahead window: up to ``depth`` location reads AND
        up to ``depth`` grouped data fetches outstanding at once on the
        shared pipelined connection, completions drained oldest-first.
        This is the structure the reference's speedup comes from — many
        one-sided READs in flight per channel (:82-83) — mapped onto the
        transport's req-id multiplexing.

        Budget interplay: a data fetch is only ISSUED once its bytes fit
        the ``max_bytes_in_flight`` gate. When the gate is full and this
        window still holds issued fetches, the oldest is completed first
        (its enqueue lets the consumer drain and release budget) — never
        block on the gate while holding completions, or the release that
        would unblock it could never happen."""
        maps = list(maps)
        self._rng.shuffle(maps)  # randomized order (:74-79)
        loc_pending: deque = deque()  # (map_id, AsyncFetch, t_issue)
        ready: deque = deque()        # (_PendingFetch, t_ready)
        inflight: deque = deque()     # (_PendingFetch, AsyncFetch,
        #                                t_ready, t_issue)
        # cache-first: maps with epoch-current cached locations feed the
        # data window directly; only misses enter the STEP-2 read-ahead
        plane = self.endpoint.location_plane
        misses: List[int] = []
        now0 = time.monotonic()
        for m in maps:
            locs = plane.locations(self.shuffle_id, m,
                                   self.start_partition,
                                   self.end_partition)
            if locs is None:
                misses.append(m)
                continue
            self.metrics.record_location_hit()
            groups = self._group_locations(exec_idx, m, locs)
            self._rng.shuffle(groups)
            with count_lock:
                self._expected_results += len(groups)
            ready.extend((g, now0) for g in groups)
        maps = misses
        mi = 0
        try:
            while mi < len(maps) or loc_pending or ready or inflight:
                if self._aborted.is_set():
                    raise _Aborted()
                # top up STEP-2 read-ahead: overlap location reads with
                # everything else
                while mi < len(maps) and len(loc_pending) < depth:
                    m = maps[mi]
                    # same fail-fast as the sequential path's envelope: a
                    # suspect verdict must stop NEW issues (a fresh dial
                    # after the verdict is a connection the monitor will
                    # never close for us)
                    self._suspect_check(exec_idx, m)
                    mi += 1
                    self.metrics.record_request()
                    self.metrics.record_metadata_rpc()
                    loc_pending.append((
                        m,
                        self.endpoint.fetch_output_range_async(
                            peer, self.shuffle_id, m,
                            self.start_partition, self.end_partition),
                        time.monotonic()))
                # harvest landed location reads in issue order
                while loc_pending and loc_pending[0][1].done():
                    self._harvest_locations(peer, exec_idx,
                                            loc_pending.popleft(),
                                            ready, count_lock)
                # issue STEP-3 data fetches while the window has room and
                # the in-flight byte budget admits them. With an empty
                # window the acquire may block (same as the sequential
                # path — nothing of ours is withheld from the consumer);
                # with fetches in flight it must not: the release that
                # would unblock it needs their completions enqueued first.
                while ready and len(inflight) < depth:
                    fetch, t_ready = ready[0]
                    if not self._try_acquire_in_flight(
                            fetch.total_bytes, nonblocking=bool(inflight)):
                        break
                    ready.popleft()
                    t_issue = time.monotonic()
                    self.metrics.record_request()
                    handle = self.endpoint.fetch_blocks_async(
                        peer, self.shuffle_id, fetch.blocks)
                    inflight.append((fetch, handle, t_ready, t_issue))
                    self.pipeline.record_issue(exec_idx, len(inflight),
                                               t_issue - t_ready)
                # complete: whenever the window holds fetches the oldest
                # completion is both the progress path and the budget-
                # release path; with an empty window, block on the oldest
                # location read instead
                if inflight:
                    self._complete_oldest(peer, exec_idx, inflight)
                elif loc_pending:
                    self._harvest_locations(peer, exec_idx,
                                            loc_pending.popleft(),
                                            ready, count_lock)
        except BaseException:
            # window-held budget must not outlive the window: the issued-
            # but-uncompleted fetches' bytes were acquired above and their
            # results will never reach the consumer (who releases on
            # dequeue). The abandoned handles are cancelled too — a
            # pending request holds a send-budget slot on the SHARED
            # connection until its future resolves, so walking away
            # without cancelling would leak one slot per abandoned fetch
            # on every failed attempt (the sequential path's blocking
            # request() cancels on timeout for the same reason)
            for _m, handle, _t in loc_pending:
                handle.cancel()
            for fetch, handle, _tr, _ti in inflight:
                handle.cancel()
                self._release_in_flight(fetch.total_bytes)
            raise

    def _harvest_locations(self, peer, exec_idx: int, entry, ready: deque,
                           count_lock: threading.Lock) -> None:
        m, handle, t_issue = entry
        try:
            locs = handle.result()
        except (TransportError, TimeoutError, AssertionError) as e:
            # the windowed async issue was attempt one; run the remaining
            # retry budget synchronously (re-queueing into the window
            # would reorder the drain for no benefit)
            def retry_locs(m=m):
                self.metrics.record_request()
                self.metrics.record_metadata_rpc()
                return self.endpoint.fetch_output_range(
                    peer, self.shuffle_id, m,
                    self.start_partition, self.end_partition)

            locs = self._with_retries("locations", exec_idx, m, retry_locs,
                                      first_error=e)
        self.endpoint.location_plane.put_locations(
            self.shuffle_id, m, self.start_partition, self.end_partition,
            locs, self.epoch)
        if self.tracer.enabled:
            # same span the sequential path brackets around its blocking
            # location read — STEP-2 latency stays measurable in the
            # mode built to hide it
            end_us = self.tracer.now_us()
            start_us = end_us - (time.monotonic() - t_issue) * 1e6
            self.tracer.complete_span("fetch.locations", "fetch",
                                      start_us, end_us,
                                      map=m, peer=exec_idx)
        groups = self._group_locations(exec_idx, m, locs)
        # randomized issue order within the map (:74-79), like the
        # sequential path's shuffle of `pending` — without it every
        # reducer walks each map's groups in identical ascending
        # partition order and hotspots the same serving range
        self._rng.shuffle(groups)
        with count_lock:
            self._expected_results += len(groups)
        now = time.monotonic()
        ready.extend((g, now) for g in groups)

    def _complete_oldest(self, peer, exec_idx: int, inflight: deque) -> None:
        """Finish the window's oldest data fetch: decode on this thread,
        record metrics + issue→wire→complete trace spans, enqueue. A
        transient failure retries synchronously within the budget (each
        window entry heals independently — one bit-flipped response costs
        one refetch, not the whole window); exhaustion unwinds the window
        via the FetchFailedError."""
        fetch, handle, t_ready, t_issue = inflight[0]
        wire_done_s = None
        try:
            data = handle.result()
            wire_done_s = handle.wire_done_s
        except (TransportError, TimeoutError, AssertionError) as e:
            inflight.popleft()
            # re-stamp the issue time: the recorded latency should cover
            # the retry that actually served the bytes, not the failed
            # wait + backoff sleeps (which would skew the histograms the
            # pipeline analysis reads); the failed handle's wire stamp is
            # stale for the same reason
            t_issue = time.monotonic()

            def retry_blocks(fetch=fetch):
                self.metrics.record_request()
                return self.endpoint.fetch_blocks(
                    peer, self.shuffle_id, fetch.blocks)

            try:
                data = self._with_retries("blocks", exec_idx, fetch.map_id,
                                          retry_blocks, first_error=e)
            except BaseException:
                # this entry's budget is released here; the rest of the
                # window is released by _fetch_pipelined's unwind
                self._release_in_flight(fetch.total_bytes)
                raise
        else:
            inflight.popleft()
        now = time.monotonic()
        dt = now - t_issue
        self.metrics.record_remote(len(data), dt)
        if self.reader_stats is not None:
            self.reader_stats.update(exec_idx, dt, nbytes=len(data))
        if self.tracer.enabled:
            end_us = self.tracer.now_us()
            issue_us = end_us - (now - t_issue) * 1e6
            ready_us = end_us - (now - t_ready) * 1e6
            wire_us = (end_us - (now - wire_done_s) * 1e6
                       if wire_done_s is not None else end_us)
            # the stamp rides the future's done-callback, which can run
            # AFTER result() already returned — clamp so a late stamp
            # can't put the wire phase outside [issue, complete]
            wire_us = min(max(wire_us, issue_us), end_us)
            self.tracer.complete_span(
                "fetch.issue", "fetch", ready_us, issue_us,
                map=fetch.map_id, peer=exec_idx)
            # the wire phase keeps the sequential path's span name so
            # existing trace consumers see one contract either way
            self.tracer.complete_span(
                "fetch.blocks", "fetch", issue_us, wire_us,
                map=fetch.map_id, peer=exec_idx, bytes=fetch.total_bytes)
            self.tracer.complete_span(
                "fetch.complete", "fetch", wire_us, end_us,
                map=fetch.map_id, peer=exec_idx)
        self._results.put(FetchResult(
            fetch.map_id, fetch.start_partition, fetch.end_partition,
            data))

    # -- flow control ----------------------------------------------------

    def _acquire_in_flight(self, nbytes: int) -> None:
        with self._in_flight_cv:
            # single-oversized-fetch escape: proceed when nothing's in flight
            while (self._in_flight > 0
                   and self._in_flight + nbytes > self.conf.max_bytes_in_flight):
                if self._aborted.is_set():
                    raise _Aborted()
                self._in_flight_cv.wait(timeout=0.5)
            if self._aborted.is_set():
                raise _Aborted()
            self._in_flight += nbytes

    def _try_acquire_in_flight(self, nbytes: int,
                               nonblocking: bool) -> bool:
        """Window-aware acquire: blocking when the caller holds no
        outstanding completions (identical to ``_acquire_in_flight``,
        single-oversized escape included), one-shot when it does."""
        if not nonblocking:
            self._acquire_in_flight(nbytes)
            return True
        with self._in_flight_cv:
            if self._aborted.is_set():
                raise _Aborted()
            if (self._in_flight > 0
                    and self._in_flight + nbytes > self.conf.max_bytes_in_flight):
                return False
            self._in_flight += nbytes
            return True

    def _release_in_flight(self, nbytes: int) -> None:
        with self._in_flight_cv:
            self._in_flight -= nbytes
            self._in_flight_cv.notify_all()

    @property
    def bytes_in_flight(self) -> int:
        with self._in_flight_cv:
            return self._in_flight

    def _drain_unconsumed(self) -> None:
        """Free pool leases of results the consumer will never take
        (failure/early-exit teardown; a plain-bytes or sentinel result's
        free() is a no-op)."""
        while True:
            try:
                self._results.get_nowait().free()
            except queue.Empty:
                return

    def close(self) -> None:
        """Abort outstanding work: wakes budget waiters, stops peer
        threads at their next checkpoint (teardown semantics of
        RdmaChannel.java:872-956 — outstanding work must not outlive the
        consumer). Unconsumed lease-backed results return their pool
        buffers (the last peer thread re-drains for completions that
        race this)."""
        self._aborted.set()
        with self._in_flight_cv:
            self._in_flight_cv.notify_all()
        self._drain_unconsumed()
        # skew observability: this reducer's input-byte total lands in
        # the pow2 bytes_per_reducer histogram exactly once per fetch
        # lifetime (every read path funnels through close) — and ONLY
        # for a cleanly COMPLETED fetch: a failed or abandoned fetch
        # would record partial bytes, and its stage retry would record
        # the same logical reducer again, skewing the reduce_balance
        # gauge with tasks that never existed
        if (self.reader_stats is not None and self._started
                and not self._reducer_bytes_recorded
                and not self._failed
                and self._consumed >= self._expected_results):
            self._reducer_bytes_recorded = True
            self.reader_stats.record_reducer_bytes(
                self.metrics.remote_bytes + self.metrics.local_bytes
                + self.metrics.tiered_bytes)

    # -- iteration (:342-382) -------------------------------------------

    def __iter__(self):
        sentinel_seen = False
        while True:
            if sentinel_seen and self._consumed >= self._expected_results:
                return
            t0 = time.monotonic()
            result = self._results.get()
            self.metrics.fetch_wait_s += time.monotonic() - t0
            if result.is_sentinel:
                sentinel_seen = True
                continue
            if result.failure is not None:
                self._failed = True
                self.close()
                # any escalated failure makes this shuffle's cached
                # locations AND warm bytes suspect (peer-thread crashes
                # included, which never went through _fail):
                # refetch-snapshot on retry
                self.endpoint.location_plane.invalidate(self.shuffle_id)
                from sparkrdma_tpu.shuffle import dist_cache
                dist_cache.drop(self.shuffle_id)
                raise result.failure
            self._consumed += 1
            if not result.is_local:
                # grouped-fetch payload length == sum of its block lengths
                self._release_in_flight(len(result.data))
            yield result
