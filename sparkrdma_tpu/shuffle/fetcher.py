"""Async shuffle fetcher — the hot read path.

Re-design of ``scala/RdmaShuffleFetcherIterator.scala``. Preserved semantics,
point by point:

* three-level fetch: driver table once per shuffle (:183 →
  RdmaShuffleManager.scala:341-376), per-map block-location reads out of the
  owning executor (:293-315), then grouped data fetches (:119-180);
* block grouping: consecutive partitions of one map output are fetched in
  requests of at most ``shuffle_read_block_size`` bytes (:240-263);
* flow control: a ``max_bytes_in_flight`` gate — fetches beyond the budget
  queue until the consumer drains results (:264-276, 366-374), with the
  single-oversized-fetch escape so one huge block can't deadlock;
* randomized pending order so one peer isn't oversubscribed (:74-79);
* local map outputs short-circuit the network entirely (:327-337);
* results flow through a blocking queue; a sentinel terminates iteration
  (:47-50, 113-117); failures surface as ``FetchFailedError`` so the engine
  can recompute the stage (:376-381);
* **bounded read-ahead per peer**: each peer thread keeps up to
  ``read_ahead_depth`` grouped fetches outstanding on the pipelined
  connection and overlaps STEP-2 location reads with STEP-3 data reads —
  the ``sendQueueDepth / cores`` in-flight split that the reference's
  whole speedup rides on (:82-83). ``read_ahead_depth=1`` reproduces the
  fully sequential pre-pipelining behavior exactly (regression escape
  hatch).
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel.endpoints import (
    DeadExecutorError,
    ExecutorEndpoint,
)
from sparkrdma_tpu.parallel.transport import (
    Backoff,
    ChecksumError,
    TransportError,
)
from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver
from sparkrdma_tpu.utils.stats import FetchPipelineStats

log = logging.getLogger(__name__)


class _Aborted(Exception):
    """Internal: the consumer abandoned/failed the iteration."""


class FetchFailedError(Exception):
    """A remote block could not be fetched; the engine should recompute the
    producing stage (reference surfaces Spark's FetchFailedException,
    scala/RdmaShuffleFetcherIterator.scala:376-381)."""

    def __init__(self, shuffle_id: int, map_id: int, exec_index: int, cause: str):
        super().__init__(f"shuffle {shuffle_id} map {map_id} "
                         f"(executor slot {exec_index}): {cause}")
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.exec_index = exec_index


@dataclass
class FetchResult:
    """One successful grouped fetch (or the failure/sentinel marker)."""

    map_id: int = -1
    start_partition: int = 0
    end_partition: int = 0
    data: bytes = b""
    is_local: bool = False
    failure: Optional[FetchFailedError] = None
    is_sentinel: bool = False


@dataclass
class ReadMetrics:
    """Reference: Spark task metrics wiring
    (scala/RdmaShuffleFetcherIterator.scala:104-106, 330-332, 349-361).
    Updated from concurrent peer threads — mutate via the record_* methods."""

    remote_bytes: int = 0
    local_bytes: int = 0
    remote_fetches: int = 0
    local_fetches: int = 0
    fetch_wait_s: float = 0.0
    fetch_latencies_s: List[float] = field(default_factory=list)
    # failure path: transient retries absorbed, CRC mismatches refetched,
    # terminal failures escalated to FetchFailed (stage retry)
    retries: int = 0
    checksum_failures: int = 0
    failed_fetches: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record_remote(self, nbytes: int, latency_s: float) -> None:
        with self._lock:
            self.remote_bytes += nbytes
            self.remote_fetches += 1
            self.fetch_latencies_s.append(latency_s)

    def record_local(self, nbytes: int) -> None:
        with self._lock:
            self.local_bytes += nbytes
            self.local_fetches += 1

    def record_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def record_checksum_failure(self) -> None:
        with self._lock:
            self.checksum_failures += 1

    def record_failure(self) -> None:
        with self._lock:
            self.failed_fetches += 1


@dataclass
class _PendingFetch:
    exec_index: int
    map_id: int
    start_partition: int
    end_partition: int
    blocks: List  # [(buf, offset, length)]
    total_bytes: int


class ShuffleFetcher:
    """Iterator of FetchResults for one reducer's partition range."""

    def __init__(self, endpoint: ExecutorEndpoint,
                 resolver: Optional[TpuShuffleBlockResolver],
                 conf: TpuShuffleConf, shuffle_id: int, num_maps: int,
                 start_partition: int, end_partition: int,
                 seed: Optional[int] = None, reader_stats=None, tracer=None):
        from sparkrdma_tpu.utils import trace as trace_mod
        self.endpoint = endpoint
        self.resolver = resolver
        self.conf = conf
        self.reader_stats = reader_stats  # ShuffleReaderStats | None
        self.tracer = tracer or trace_mod.NULL
        self.shuffle_id = shuffle_id
        self.num_maps = num_maps
        self.start_partition = start_partition
        self.end_partition = end_partition
        self.metrics = ReadMetrics()
        # per-peer read-ahead telemetry (depth + queue-wait histograms).
        # When stats collection is on this IS reader_stats.pipeline — one
        # object, one lock per issue, one source of truth in snapshots
        self.pipeline = (reader_stats.pipeline if reader_stats is not None
                         else FetchPipelineStats())
        self._results: "queue.Queue[FetchResult]" = queue.Queue()
        self._expected_results = 0
        self._consumed = 0
        # max_bytes_in_flight gate (:264-276)
        self._in_flight = 0
        self._in_flight_cv = threading.Condition()
        self._failed = False
        self._aborted = threading.Event()
        self._rng = random.Random(seed)
        # retry backoff shares the fetcher seed so a chaos scenario's
        # sleep schedule replays with it
        self._backoff = Backoff.from_conf(conf, rng=random.Random(seed))
        self._threads: List[threading.Thread] = []

    # -- setup: plan + launch (initialize/startAsyncRemoteFetches) -------

    def start(self) -> "ShuffleFetcher":
        with self.tracer.span("fetch.driver_table", "fetch",
                              shuffle=self.shuffle_id):
            table = self.endpoint.get_driver_table(self.shuffle_id,
                                                   self.num_maps)
        my_index = self._my_index()
        local_maps: List[int] = []
        by_peer: Dict[int, List[int]] = {}
        for m in range(self.num_maps):
            entry = table.entry(m)
            if entry is None:
                raise FetchFailedError(self.shuffle_id, m, -1,
                                       "map output never published")
            _, exec_idx = entry
            if exec_idx == my_index:
                local_maps.append(m)
            else:
                by_peer.setdefault(exec_idx, []).append(m)

        # Local short-circuit (:327-337): serve directly, count separately.
        for m in local_maps:
            data = self.resolver.local_blocks(
                self.shuffle_id, m, self.start_partition, self.end_partition)
            if data is None:
                raise FetchFailedError(self.shuffle_id, m, my_index,
                                       "local map output missing")
            self.metrics.record_local(len(data))
            self._expected_results += 1
            self._results.put(FetchResult(m, self.start_partition,
                                          self.end_partition, data,
                                          is_local=True))

        # A freshly-joined reducer can hold driver-table entries referencing
        # executor slots its membership list hasn't caught up to yet (the
        # announce is async); wait for the list to cover the highest slot we
        # need before resolving peers.
        if by_peer:
            try:
                self.endpoint.wait_for_members(
                    max(by_peer) + 1,
                    timeout=self.conf.connect_timeout_ms / 1000)
            except TimeoutError as e:
                raise FetchFailedError(self.shuffle_id, -1, max(by_peer),
                                       f"membership never covered slot: {e}"
                                       ) from e

        # One fetch thread per peer: location reads then grouped data reads.
        # The per-peer thread bounds per-channel outstanding work the way the
        # reference divides sendQueueDepth across cores (:82-83).
        peers = list(by_peer.items())
        self._rng.shuffle(peers)  # randomized order (:74-79)
        count_lock = threading.Lock()
        for exec_idx, maps in peers:
            t = threading.Thread(target=self._fetch_from_peer,
                                 args=(exec_idx, maps, count_lock),
                                 daemon=True,
                                 name=f"fetch-s{self.shuffle_id}-e{exec_idx}")
            self._threads.append(t)
        # Expected-result accounting: each peer thread registers its request
        # count before its first enqueue; the sentinel goes in when all
        # threads have finished (tracked by _peer_threads_left).
        self._peer_threads_left = len(peers)
        if not peers:
            self._results.put(FetchResult(is_sentinel=True))
        for t in self._threads:
            t.start()
        return self

    def _my_index(self) -> int:
        try:
            return self.endpoint.exec_index()
        except KeyError:
            return -1

    # -- per-peer fetch pipeline ----------------------------------------

    def _fetch_from_peer(self, exec_idx: int, maps: List[int],
                         count_lock: threading.Lock) -> None:
        try:
            peer = self.endpoint.member_at(exec_idx)
            depth = self.conf.resolved_read_ahead_depth()
            # register heartbeat interest for the duration of the fetch:
            # if the peer dies silently mid-window, the monitor closes the
            # connection (failing the window NOW) and marks the slot
            # suspect so the retry envelope escalates instead of re-dialing
            self.endpoint.watch_peer(exec_idx, peer)
            try:
                if depth <= 1:
                    self._fetch_sequential(peer, exec_idx, maps, count_lock)
                else:
                    self._fetch_pipelined(peer, exec_idx, maps, count_lock,
                                          depth)
            finally:
                self.endpoint.unwatch_peer(exec_idx)
        except _Aborted:
            pass  # consumer went away; exit quietly
        except Exception as e:  # noqa: BLE001 — ANY peer-thread failure must
            # surface as a FetchFailedError result, never a silent dead
            # thread (which would truncate the reduce input undetected)
            failure = (e if isinstance(e, FetchFailedError) else
                       FetchFailedError(self.shuffle_id,
                                        maps[0] if maps else -1,
                                        exec_idx, f"{type(e).__name__}: {e}"))
            self._results.put(FetchResult(failure=failure))
        finally:
            with count_lock:
                self._peer_threads_left -= 1
                if self._peer_threads_left == 0:
                    self._results.put(FetchResult(is_sentinel=True))

    def _group_locations(self, exec_idx: int, m: int,
                         locs) -> List[_PendingFetch]:
        """STEP 3 grouping: consecutive partitions, ≤ read block size
        (:240-263). Zero-length blocks ride along byte-free but still
        count toward a block-count bound so a wide, mostly-empty
        partition range can't build a request frame past the native
        server's 1 MiB inbound cap (csrc/blockserver.cpp kMaxReqFrame;
        8192 blocks ~= 128 KiB of frame)."""
        pending: List[_PendingFetch] = []
        group: List = []
        group_start = self.start_partition
        group_bytes = 0
        limit = self.conf.shuffle_read_block_size
        max_blocks = 8192
        for i, loc in enumerate(locs):
            p = self.start_partition + i
            if group and (group_bytes + loc.length > limit
                          or len(group) >= max_blocks):
                pending.append(_PendingFetch(
                    exec_idx, m, group_start, p, group, group_bytes))
                group, group_start, group_bytes = [], p, 0
            group.append((loc.buf, loc.offset, loc.length))
            group_bytes += loc.length
        if group:
            pending.append(_PendingFetch(
                exec_idx, m, group_start,
                self.start_partition + len(locs), group, group_bytes))
        return pending

    # -- retry envelope (deadline + backoff, transient vs fatal) ---------

    def _suspect_check(self, exec_idx: int, map_id: int) -> None:
        if self.endpoint.peer_suspect(exec_idx):
            raise FetchFailedError(
                self.shuffle_id, map_id, exec_idx,
                "peer declared suspect by the heartbeat monitor")

    def _note_transient(self, e: BaseException, what: str, exec_idx: int,
                        map_id: int, will_retry: bool, attempt: int) -> None:
        if isinstance(e, ChecksumError):
            self.metrics.record_checksum_failure()
            if self.reader_stats is not None:
                self.reader_stats.failures.incr("checksum_mismatches")
        if will_retry:
            self.metrics.record_retry()
            if self.reader_stats is not None:
                self.reader_stats.failures.incr("fetch_retries")
            self.tracer.instant("fetch.retry", "fault", what=what,
                                peer=exec_idx, map=map_id,
                                attempt=attempt, error=type(e).__name__)
            log.debug("fetch retry %d (%s, map %d, peer %d): %s",
                      attempt, what, map_id, exec_idx, e)

    def _fail(self, what: str, exec_idx: int, map_id: int, consumed: int,
              err: BaseException):
        self.metrics.record_failure()
        if self.reader_stats is not None:
            self.reader_stats.failures.incr("fetch_failures")
        raise FetchFailedError(
            self.shuffle_id, map_id, exec_idx,
            f"{what} failed after {consumed} attempt(s): {err}") from err

    def _with_retries(self, what: str, exec_idx: int, map_id: int, fn,
                      first_error: Optional[BaseException] = None):
        """Run one remote call under the failure policy: TRANSIENT
        outcomes (connection loss, connect refusal, request deadline,
        CRC mismatch, transient server status) retry with exponential
        backoff + jitter up to ``fetch_retry_budget``; FATAL outcomes
        (suspect peer, authoritative non-OK status, protocol bugs)
        escalate immediately as :class:`FetchFailedError` so
        ``run_reduce_with_retry`` recomputes the stage. ``first_error``
        charges an already-failed async attempt against the budget (the
        pipelined window's in-flight issue was attempt one)."""
        attempts = 1 + max(0, self.conf.fetch_retry_budget)
        consumed = 0
        if first_error is not None:
            consumed = 1
            retryable = (getattr(first_error, "retryable", True)
                         and not isinstance(first_error, AssertionError))
            self._note_transient(first_error, what, exec_idx, map_id,
                                 retryable and consumed < attempts, consumed)
            if not retryable or consumed >= attempts:
                self._fail(what, exec_idx, map_id, consumed, first_error)
            self._suspect_check(exec_idx, map_id)
            if self._aborted.wait(self._backoff.delay(consumed - 1)):
                raise _Aborted()
        while True:
            if self._aborted.is_set():
                raise _Aborted()
            self._suspect_check(exec_idx, map_id)
            try:
                return fn()
            except (TransportError, TimeoutError, AssertionError) as e:
                consumed += 1
                retryable = (getattr(e, "retryable", True)
                             and not isinstance(e, AssertionError))
                self._note_transient(e, what, exec_idx, map_id,
                                     retryable and consumed < attempts,
                                     consumed)
                if not retryable or consumed >= attempts:
                    self._fail(what, exec_idx, map_id, consumed, e)
                if self._aborted.wait(self._backoff.delay(consumed - 1)):
                    raise _Aborted()

    def _fetch_sequential(self, peer, exec_idx: int, maps: List[int],
                          count_lock: threading.Lock) -> None:
        """``read_ahead_depth=1``: the fully serialized fetch — every
        location read then every data read, one at a time. Kept verbatim
        as the regression escape hatch the pipelined path is diffed
        against."""
        pending: List[_PendingFetch] = []
        for m in maps:
            # STEP 2: block locations (:293-315).
            def read_locs(m=m):
                with self.tracer.span("fetch.locations", "fetch",
                                      map=m, peer=exec_idx):
                    return self.endpoint.fetch_output_range(
                        peer, self.shuffle_id, m,
                        self.start_partition, self.end_partition)

            locs = self._with_retries("locations", exec_idx, m, read_locs)
            pending.extend(self._group_locations(exec_idx, m, locs))
        self._rng.shuffle(pending)
        with count_lock:
            self._expected_results += len(pending)
        for fetch in pending:
            if self._aborted.is_set():
                raise _Aborted()
            self._acquire_in_flight(fetch.total_bytes)
            t0 = time.monotonic()

            def read_blocks(fetch=fetch):
                with self.tracer.span("fetch.blocks", "fetch",
                                      map=fetch.map_id, peer=exec_idx,
                                      bytes=fetch.total_bytes):
                    return self.endpoint.fetch_blocks(
                        peer, self.shuffle_id, fetch.blocks)

            try:
                data = self._with_retries("blocks", exec_idx, fetch.map_id,
                                          read_blocks)
            except BaseException:
                # envelope exhausted (FetchFailedError) or abort: this
                # fetch's budget must not leak past its failure
                self._release_in_flight(fetch.total_bytes)
                raise
            dt = time.monotonic() - t0
            self.metrics.record_remote(len(data), dt)
            if self.reader_stats is not None:
                self.reader_stats.update(exec_idx, dt)
            self._results.put(FetchResult(
                fetch.map_id, fetch.start_partition, fetch.end_partition,
                data))

    def _fetch_pipelined(self, peer, exec_idx: int, maps: List[int],
                         count_lock: threading.Lock, depth: int) -> None:
        """Bounded read-ahead window: up to ``depth`` location reads AND
        up to ``depth`` grouped data fetches outstanding at once on the
        shared pipelined connection, completions drained oldest-first.
        This is the structure the reference's speedup comes from — many
        one-sided READs in flight per channel (:82-83) — mapped onto the
        transport's req-id multiplexing.

        Budget interplay: a data fetch is only ISSUED once its bytes fit
        the ``max_bytes_in_flight`` gate. When the gate is full and this
        window still holds issued fetches, the oldest is completed first
        (its enqueue lets the consumer drain and release budget) — never
        block on the gate while holding completions, or the release that
        would unblock it could never happen."""
        maps = list(maps)
        self._rng.shuffle(maps)  # randomized order (:74-79)
        loc_pending: deque = deque()  # (map_id, AsyncFetch, t_issue)
        ready: deque = deque()        # (_PendingFetch, t_ready)
        inflight: deque = deque()     # (_PendingFetch, AsyncFetch,
        #                                t_ready, t_issue)
        mi = 0
        try:
            while mi < len(maps) or loc_pending or ready or inflight:
                if self._aborted.is_set():
                    raise _Aborted()
                # top up STEP-2 read-ahead: overlap location reads with
                # everything else
                while mi < len(maps) and len(loc_pending) < depth:
                    m = maps[mi]
                    mi += 1
                    loc_pending.append((
                        m,
                        self.endpoint.fetch_output_range_async(
                            peer, self.shuffle_id, m,
                            self.start_partition, self.end_partition),
                        time.monotonic()))
                # harvest landed location reads in issue order
                while loc_pending and loc_pending[0][1].done():
                    self._harvest_locations(peer, exec_idx,
                                            loc_pending.popleft(),
                                            ready, count_lock)
                # issue STEP-3 data fetches while the window has room and
                # the in-flight byte budget admits them. With an empty
                # window the acquire may block (same as the sequential
                # path — nothing of ours is withheld from the consumer);
                # with fetches in flight it must not: the release that
                # would unblock it needs their completions enqueued first.
                while ready and len(inflight) < depth:
                    fetch, t_ready = ready[0]
                    if not self._try_acquire_in_flight(
                            fetch.total_bytes, nonblocking=bool(inflight)):
                        break
                    ready.popleft()
                    t_issue = time.monotonic()
                    handle = self.endpoint.fetch_blocks_async(
                        peer, self.shuffle_id, fetch.blocks)
                    inflight.append((fetch, handle, t_ready, t_issue))
                    self.pipeline.record_issue(exec_idx, len(inflight),
                                               t_issue - t_ready)
                # complete: whenever the window holds fetches the oldest
                # completion is both the progress path and the budget-
                # release path; with an empty window, block on the oldest
                # location read instead
                if inflight:
                    self._complete_oldest(peer, exec_idx, inflight)
                elif loc_pending:
                    self._harvest_locations(peer, exec_idx,
                                            loc_pending.popleft(),
                                            ready, count_lock)
        except BaseException:
            # window-held budget must not outlive the window: the issued-
            # but-uncompleted fetches' bytes were acquired above and their
            # results will never reach the consumer (who releases on
            # dequeue). The abandoned handles are cancelled too — a
            # pending request holds a send-budget slot on the SHARED
            # connection until its future resolves, so walking away
            # without cancelling would leak one slot per abandoned fetch
            # on every failed attempt (the sequential path's blocking
            # request() cancels on timeout for the same reason)
            for _m, handle, _t in loc_pending:
                handle.cancel()
            for fetch, handle, _tr, _ti in inflight:
                handle.cancel()
                self._release_in_flight(fetch.total_bytes)
            raise

    def _harvest_locations(self, peer, exec_idx: int, entry, ready: deque,
                           count_lock: threading.Lock) -> None:
        m, handle, t_issue = entry
        try:
            locs = handle.result()
        except (TransportError, TimeoutError, AssertionError) as e:
            # the windowed async issue was attempt one; run the remaining
            # retry budget synchronously (re-queueing into the window
            # would reorder the drain for no benefit)
            locs = self._with_retries(
                "locations", exec_idx, m,
                lambda: self.endpoint.fetch_output_range(
                    peer, self.shuffle_id, m,
                    self.start_partition, self.end_partition),
                first_error=e)
        if self.tracer.enabled:
            # same span the sequential path brackets around its blocking
            # location read — STEP-2 latency stays measurable in the
            # mode built to hide it
            end_us = self.tracer.now_us()
            start_us = end_us - (time.monotonic() - t_issue) * 1e6
            self.tracer.complete_span("fetch.locations", "fetch",
                                      start_us, end_us,
                                      map=m, peer=exec_idx)
        groups = self._group_locations(exec_idx, m, locs)
        # randomized issue order within the map (:74-79), like the
        # sequential path's shuffle of `pending` — without it every
        # reducer walks each map's groups in identical ascending
        # partition order and hotspots the same serving range
        self._rng.shuffle(groups)
        with count_lock:
            self._expected_results += len(groups)
        now = time.monotonic()
        ready.extend((g, now) for g in groups)

    def _complete_oldest(self, peer, exec_idx: int, inflight: deque) -> None:
        """Finish the window's oldest data fetch: decode on this thread,
        record metrics + issue→wire→complete trace spans, enqueue. A
        transient failure retries synchronously within the budget (each
        window entry heals independently — one bit-flipped response costs
        one refetch, not the whole window); exhaustion unwinds the window
        via the FetchFailedError."""
        fetch, handle, t_ready, t_issue = inflight[0]
        wire_done_s = None
        try:
            data = handle.result()
            wire_done_s = handle.wire_done_s
        except (TransportError, TimeoutError, AssertionError) as e:
            inflight.popleft()
            # re-stamp the issue time: the recorded latency should cover
            # the retry that actually served the bytes, not the failed
            # wait + backoff sleeps (which would skew the histograms the
            # pipeline analysis reads); the failed handle's wire stamp is
            # stale for the same reason
            t_issue = time.monotonic()
            try:
                data = self._with_retries(
                    "blocks", exec_idx, fetch.map_id,
                    lambda: self.endpoint.fetch_blocks(
                        peer, self.shuffle_id, fetch.blocks),
                    first_error=e)
            except BaseException:
                # this entry's budget is released here; the rest of the
                # window is released by _fetch_pipelined's unwind
                self._release_in_flight(fetch.total_bytes)
                raise
        else:
            inflight.popleft()
        now = time.monotonic()
        dt = now - t_issue
        self.metrics.record_remote(len(data), dt)
        if self.reader_stats is not None:
            self.reader_stats.update(exec_idx, dt)
        if self.tracer.enabled:
            end_us = self.tracer.now_us()
            issue_us = end_us - (now - t_issue) * 1e6
            ready_us = end_us - (now - t_ready) * 1e6
            wire_us = (end_us - (now - wire_done_s) * 1e6
                       if wire_done_s is not None else end_us)
            # the stamp rides the future's done-callback, which can run
            # AFTER result() already returned — clamp so a late stamp
            # can't put the wire phase outside [issue, complete]
            wire_us = min(max(wire_us, issue_us), end_us)
            self.tracer.complete_span(
                "fetch.issue", "fetch", ready_us, issue_us,
                map=fetch.map_id, peer=exec_idx)
            # the wire phase keeps the sequential path's span name so
            # existing trace consumers see one contract either way
            self.tracer.complete_span(
                "fetch.blocks", "fetch", issue_us, wire_us,
                map=fetch.map_id, peer=exec_idx, bytes=fetch.total_bytes)
            self.tracer.complete_span(
                "fetch.complete", "fetch", wire_us, end_us,
                map=fetch.map_id, peer=exec_idx)
        self._results.put(FetchResult(
            fetch.map_id, fetch.start_partition, fetch.end_partition,
            data))

    # -- flow control ----------------------------------------------------

    def _acquire_in_flight(self, nbytes: int) -> None:
        with self._in_flight_cv:
            # single-oversized-fetch escape: proceed when nothing's in flight
            while (self._in_flight > 0
                   and self._in_flight + nbytes > self.conf.max_bytes_in_flight):
                if self._aborted.is_set():
                    raise _Aborted()
                self._in_flight_cv.wait(timeout=0.5)
            if self._aborted.is_set():
                raise _Aborted()
            self._in_flight += nbytes

    def _try_acquire_in_flight(self, nbytes: int,
                               nonblocking: bool) -> bool:
        """Window-aware acquire: blocking when the caller holds no
        outstanding completions (identical to ``_acquire_in_flight``,
        single-oversized escape included), one-shot when it does."""
        if not nonblocking:
            self._acquire_in_flight(nbytes)
            return True
        with self._in_flight_cv:
            if self._aborted.is_set():
                raise _Aborted()
            if (self._in_flight > 0
                    and self._in_flight + nbytes > self.conf.max_bytes_in_flight):
                return False
            self._in_flight += nbytes
            return True

    def _release_in_flight(self, nbytes: int) -> None:
        with self._in_flight_cv:
            self._in_flight -= nbytes
            self._in_flight_cv.notify_all()

    @property
    def bytes_in_flight(self) -> int:
        with self._in_flight_cv:
            return self._in_flight

    def close(self) -> None:
        """Abort outstanding work: wakes budget waiters, stops peer
        threads at their next checkpoint (teardown semantics of
        RdmaChannel.java:872-956 — outstanding work must not outlive the
        consumer)."""
        self._aborted.set()
        with self._in_flight_cv:
            self._in_flight_cv.notify_all()

    # -- iteration (:342-382) -------------------------------------------

    def __iter__(self):
        sentinel_seen = False
        while True:
            if sentinel_seen and self._consumed >= self._expected_results:
                return
            t0 = time.monotonic()
            result = self._results.get()
            self.metrics.fetch_wait_s += time.monotonic() - t0
            if result.is_sentinel:
                sentinel_seen = True
                continue
            if result.failure is not None:
                self._failed = True
                self.close()
                raise result.failure
            self._consumed += 1
            if not result.is_local:
                # grouped-fetch payload length == sum of its block lengths
                self._release_in_flight(len(result.data))
            yield result
