"""Planned-push receive path: staged reduce inputs, resolved first.

The sender-driven half of the shuffle ("RPC Considered Harmful",
PAPERS.md): once the driver's ReducePlan names a reducer slot for a
partition, the map stage PUSHES that partition's committed bytes to the
slot instead of waiting for the reduce stage to pull them. This module
is the receiving side — a MergeStore sibling that stages pushed ranges
per ``(partition, map)`` until the local reducer consumes them:

* **Double fence.** Every push carries the committing attempt's fencing
  token AND the plan epoch the sender routed by. A stale attempt's push
  is rejected (newer fence supersedes, exactly the merge-ledger
  discipline); a stale PLAN's push is rejected, and when a re-plan
  lands (:meth:`on_plan`) every staged range stamped with an older
  epoch is released — a mid-stage re-plan supersedes stale pushes, and
  the orphaned tasks re-pull over the ordinary dataplanes. The
  ``push_vs_replan`` / ``push_vs_tombstone`` model-check scenarios
  (analysis/modelcheck.py) pin these invariants over every interleaving.
* **Staging budget** (NP-RDMA's dynamic-registration discipline,
  PAPERS.md): ranges stage in BufferPool leases up to
  ``push_staging_budget``; past it they spill to
  ``<spill_dir>/pushed/``, charged to the owning tenant's spill quota.
  A range neither budget admits is SHED — never an error, the
  partitions simply stay pull-fetched.
* **Consume.** The fetcher resolves pushed ranges FIRST — before merged
  segments, before per-map pull — via :meth:`take`, which serves only
  ranges stamped with the consuming reducer's exact plan epoch. A
  reducer whose inputs all arrived starts with zero metadata RPCs and
  zero data RPCs; any hole falls back byte-identically.
* **Lifecycle.** State is TTL'd with the shuffle: unregister / location
  epoch death drops everything (leases freed, disk charges repaid per
  tenant, files unlinked) and leaves a tombstone so a racing push can't
  park bytes nothing will ever release; any location-epoch ADVANCE
  conservatively drops the shuffle's staged rows (a repaired map's
  re-push re-stages them) while keeping the plan epoch.

Unlike :class:`~sparkrdma_tpu.shuffle.push_merge.MergeStore`, staging
stays under the store lock: push bodies are small (one map x one plan
task's partition run), there is no pwrite fan-out worth overlapping,
and the lock is leaf-ordered (store -> pool / ledger only).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from sparkrdma_tpu.parallel import messages as M

log = logging.getLogger(__name__)


class _PushedRange:
    """One staged ``(partition, map)`` range: bytes in memory (pool
    lease held as the charge token) or spilled to ``path`` (tenant's
    disk ledger charged)."""

    __slots__ = ("fence", "plan_epoch", "nbytes", "data", "lease",
                 "path", "tenant")

    def __init__(self, fence: int, plan_epoch: int, nbytes: int,
                 data: Optional[bytes], lease, path: Optional[str],
                 tenant: int):
        self.fence = fence
        self.plan_epoch = plan_epoch
        self.nbytes = nbytes
        self.data = data
        self.lease = lease
        self.path = path
        self.tenant = tenant


class _PushedShuffle:
    """One shuffle's staged state on a planned-push target."""

    __slots__ = ("plan_epoch", "rows", "charged", "seq")

    def __init__(self):
        self.plan_epoch = 0
        # (partition, map_id) -> _PushedRange
        self.rows: Dict[Tuple[int, int], _PushedRange] = {}
        # disk-ledger charges BY TENANT (same repay-exactly discipline
        # as MergeStore._ShuffleSegments.charged)
        self.charged: Dict[int, int] = {}
        self.seq = 0  # uniquifies spill file names across supersessions


class PushedInputStore:
    """Executor-side planned-push target: stages pushed reduce inputs
    until the local reducer consumes them (or a fence supersedes them).

    Spill files live under ``<spill_dir>/pushed/`` so they share the
    storage-health namespace without colliding with committed-output or
    merge-segment naming; cleanup rides :meth:`drop_shuffle`, driven by
    unregister / epoch death."""

    def __init__(self, resolver, conf, pool=None, tracer=None):
        from sparkrdma_tpu.utils import trace as trace_mod
        from sparkrdma_tpu.utils.tombstones import TombstoneCache
        self.resolver = resolver
        self.conf = conf
        self.pool = pool
        self.tracer = tracer or trace_mod.NULL
        self.dir = os.path.join(resolver.spill_dir, "pushed")
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._shuffles: Dict[int, _PushedShuffle] = {}
        self._dropped = TombstoneCache(ttl_s=30.0, cap=1024)
        self.budget = int(conf.push_staging_budget)
        self._mem_bytes = 0  # store-wide lease-staged bytes
        # audit counters
        self.pushes_accepted = 0
        self.pushes_rejected = 0
        self.pushes_superseded = 0
        self.ranges_shed = 0
        self.spilled_bytes = 0
        self.takes_served = 0

    # -- push side -------------------------------------------------------

    def _spill_path(self, shuffle_id: int, partition: int, map_id: int,
                    seq: int) -> str:
        return os.path.join(
            self.dir, f"push_{shuffle_id}_{partition}_{map_id}.{seq}.bin")

    def _free_row_locked(self, row: _PushedRange) -> None:
        """Release one staged range's resources (lock held). Lease and
        ledger releases are leaf calls; the unlink is best-effort."""
        if row.lease is not None:
            row.lease.free()
            self._mem_bytes -= row.nbytes
        elif row.data is not None:
            self._mem_bytes -= row.nbytes
        if row.path is not None:
            if row.nbytes > 0:
                self.resolver.disk_ledger.release(row.tenant, row.nbytes)
            try:
                os.unlink(row.path)
            except OSError:
                pass

    def _stage_locked(self, state: _PushedShuffle, shuffle_id: int,
                      partition: int, map_id: int, seg: memoryview,
                      tenant: int) -> Optional[_PushedRange]:
        """Stage one range's bytes (lock held): lease-backed memory
        inside the budget, else tenant-charged disk spill, else None
        (shed). The lease is a pure charge/backpressure token — the
        bytes themselves are kept as-is, never copied into the view."""
        size = len(seg)
        if self.budget > 0 and self._mem_bytes + size <= self.budget:
            lease = None
            if self.pool is not None and size > 0:
                from sparkrdma_tpu.shuffle.tenancy import TenantQuotaError
                try:
                    lease = self.pool.get(size, tenant=tenant)
                except (TenantQuotaError, MemoryError):
                    lease = None  # degrade to disk below
            if lease is not None or self.pool is None or size == 0:
                self._mem_bytes += size
                return _PushedRange(0, 0, size, bytes(seg), lease, None,
                                    tenant)
        # spill: charge the tenant's disk quota, then write
        try:
            # analysis: leak-ok(staged rows transfer to state.charged-equivalent; _free_row_locked repays per tenant)
            if size > 0:
                self.resolver.disk_ledger.charge(tenant, size)
        except Exception:
            return None  # over quota: shed
        path = self._spill_path(shuffle_id, partition, map_id, state.seq)
        state.seq += 1
        try:
            with open(path, "wb") as f:
                f.write(seg)
        except OSError as e:
            log.warning("pushed-range spill to %s failed: %s", path, e)
            if size > 0:
                self.resolver.disk_ledger.release(tenant, size)
            return None
        self.spilled_bytes += size
        return _PushedRange(0, 0, size, None, None, path, tenant)

    def push(self, shuffle_id: int, map_id: int, fence: int,
             plan_epoch: int, start_partition: int,
             sizes: Sequence[int], data: bytes) -> Tuple[int, bytes]:
        """Stage one map's bytes for partitions [start, start+len);
        returns ``(status, accepted)`` — one byte per pushed partition.

        Acceptance mirrors ``PushedStoreModel`` (analysis/modelcheck.py)
        exactly: a push stamped older than the store's plan epoch is
        rejected wholesale; a NEWER stamp adopts the epoch first (the
        push beat the plan broadcast here — both ride async channels),
        superseding every staged range of the older epoch; per
        ``(partition, map)`` the newest attempt fence wins and the
        superseded range's charge is released in the same lock block,
        so the ledger can never leak across the swap."""
        accepted = bytearray(len(sizes))
        view = memoryview(data)
        segs = []
        pos = 0
        for size in sizes:
            segs.append(view[pos:pos + size])
            pos += size
        with self._lock:
            if shuffle_id in self._dropped:
                # unregister already dropped this shuffle here: accepting
                # would park bytes no drop will ever release. FINALIZED
                # stops the pusher for good (same contract as MergeStore).
                self.pushes_rejected += len(sizes)
                return M.STATUS_FINALIZED, bytes(accepted)
            state = self._shuffles.get(shuffle_id)
            if state is None:
                state = _PushedShuffle()
                self._shuffles[shuffle_id] = state
            if plan_epoch < state.plan_epoch:
                self.pushes_rejected += len(sizes)
                return M.STATUS_OK, bytes(accepted)  # stale plan: shed all
            if plan_epoch > state.plan_epoch:
                self._adopt_epoch_locked(shuffle_id, state, plan_epoch)
            for i, size in enumerate(sizes):
                p = start_partition + i
                prev = state.rows.get((p, map_id))
                if prev is not None:
                    if fence <= prev.fence:
                        self.pushes_rejected += 1
                        continue  # duplicate or stale attempt's push
                    self._free_row_locked(prev)
                    del state.rows[(p, map_id)]
                    self.pushes_superseded += 1
                row = self._stage_locked(state, shuffle_id, p, map_id,
                                         segs[i], self.resolver.tenant_of(
                                             shuffle_id))
                if row is None:
                    self.ranges_shed += 1
                    self.pushes_rejected += 1
                    continue  # over both budgets: stays pull-fetched
                row.fence = fence
                row.plan_epoch = plan_epoch
                state.rows[(p, map_id)] = row
                accepted[i] = 1
                self.pushes_accepted += 1
        return M.STATUS_OK, bytes(accepted)

    # -- plan / epoch discipline -----------------------------------------

    def _adopt_epoch_locked(self, shuffle_id: int, state: _PushedShuffle,
                            plan_epoch: int) -> None:
        state.plan_epoch = plan_epoch
        stale = [k for k, r in state.rows.items()
                 if r.plan_epoch < plan_epoch]
        for k in stale:
            self._free_row_locked(state.rows.pop(k))
        if stale:
            self.pushes_superseded += len(stale)
            self.tracer.instant("push.superseded", "push",
                                shuffle=shuffle_id, epoch=plan_epoch,
                                ranges=len(stale))

    def on_plan(self, shuffle_id: int, plan_epoch: int) -> None:
        """A ReducePlan landed (broadcast or fetched): adopt its epoch,
        releasing every staged range stamped older — the re-plan moved
        those partitions' placement, and their new slots are being
        pushed by the senders' replay. Also authoritative evidence the
        id is live (re-arms a tombstone, like MergeStore)."""
        with self._lock:
            self._dropped.discard(shuffle_id)
            state = self._shuffles.get(shuffle_id)
            if state is None:
                state = _PushedShuffle()
                self._shuffles[shuffle_id] = state
            if plan_epoch > state.plan_epoch:
                self._adopt_epoch_locked(shuffle_id, state, plan_epoch)

    def note_registered(self, shuffle_id: int) -> None:
        """Re-arm a dropped id on any registration push (TenantMapMsg /
        ShardMapMsg / pushed plan) — the id was reused for a NEW
        shuffle."""
        with self._lock:
            self._dropped.discard(shuffle_id)

    def on_location_epoch(self, shuffle_id: int, epoch: int) -> None:
        """A location-epoch advance names a recovery event (executor
        loss, repair republish): conservatively release the shuffle's
        staged rows — a corrupt-output repair may rewrite bytes, and
        re-pushes re-stage under their new fences — keeping the plan
        epoch (the plan only changes via :meth:`on_plan`)."""
        with self._lock:
            state = self._shuffles.get(shuffle_id)
            if state is None:
                return
            for row in state.rows.values():
                self._free_row_locked(row)
            state.rows.clear()

    # -- consume side ----------------------------------------------------

    def maps_staged(self, shuffle_id: int, partition: int,
                    plan_epoch: int) -> List[int]:
        """Which maps have a staged range for ``partition`` at exactly
        ``plan_epoch`` — the fetcher's coverage probe (no bytes read)."""
        with self._lock:
            state = self._shuffles.get(shuffle_id)
            if state is None or state.plan_epoch != plan_epoch:
                return []
            return sorted(m for (p, m), r in state.rows.items()
                          if p == partition
                          and r.plan_epoch == plan_epoch)

    def take(self, shuffle_id: int, partition: int, plan_epoch: int
             ) -> Dict[int, bytes]:
        """The staged bytes for ``partition``, keyed by map — serving
        ONLY ranges stamped with the consuming reducer's exact plan
        epoch (the ``push_vs_replan`` invariant: a stale-plan push is
        never consumed). Ranges stay staged after a take (warm
        iterative re-reads hit them again); they are released by
        supersession or :meth:`drop_shuffle`. Disk reads happen outside
        the lock; a failed read yields a hole the caller pull-fills."""
        with self._lock:
            state = self._shuffles.get(shuffle_id)
            if state is None or state.plan_epoch != plan_epoch:
                return {}
            mem: Dict[int, bytes] = {}
            spilled: List[Tuple[int, str, int]] = []
            for (p, m), row in state.rows.items():
                if p != partition or row.plan_epoch != plan_epoch:
                    continue
                if row.data is not None:
                    mem[m] = row.data
                elif row.path is not None:
                    spilled.append((m, row.path, row.nbytes))
        for m, path, nbytes in spilled:
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError as e:
                log.warning("pushed-range read of %s failed: %s", path, e)
                continue
            if len(blob) == nbytes:
                mem[m] = blob
        if mem:
            self.takes_served += 1
        return mem

    # -- lifecycle -------------------------------------------------------

    def drop_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            state = self._shuffles.pop(shuffle_id, None)
            self._dropped.add(shuffle_id)
            if state is None:
                return
            for row in state.rows.values():
                self._free_row_locked(row)
            state.rows.clear()

    def stop(self) -> None:
        with self._lock:
            sids = list(self._shuffles)
        for sid in sids:
            self.drop_shuffle(sid)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "shuffles": len(self._shuffles),
                "staged_ranges": sum(len(s.rows)
                                     for s in self._shuffles.values()),
                "mem_bytes": self._mem_bytes,
                "spilled_bytes": self.spilled_bytes,
                "pushes_accepted": self.pushes_accepted,
                "pushes_rejected": self.pushes_rejected,
                "pushes_superseded": self.pushes_superseded,
                "ranges_shed": self.ranges_shed,
                "takes_served": self.takes_served,
            }
