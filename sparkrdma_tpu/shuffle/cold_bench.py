"""Cold-tier microbench: what a full-fleet restart costs the job.

The A/B the disaggregated tier exists for (shuffle/cold_tier.py): the
ENTIRE fleet dies after map finalize (the spot-market / preemption
event), a fresh fleet attaches to the surviving driver, and the reduce
must complete —

* **cold restore** (``cold_tier`` on): the merged segments tiered to
  the blob store before the loss; recovery treats cold coverage like
  merged coverage and re-points, so the fresh fleet reduces straight
  from the blobs with ZERO map re-executions;
* **re-execution baseline** (``cold_tier`` off): nothing survived the
  fleet, so recovery re-executes every map on the fresh executors
  before the reduce can finish — paying the whole map stage again,
  one stage retry per dead owner slot.

``cold_restore_speedup`` is the makespan ratio (baseline / cold) of
the fresh fleet's time-to-answer.  A fixed per-map compute shim
(``map_cost_s``, the same stand-in discipline as the delay shims in
fetch_bench / iter_bench) prices the map work a re-execution repays
and a restore does not; both phases run in one process so the ratio
cancels host noise.

Gates (bench.py secondary + the tier-1 acceptance test in
tests/test_cold_tier.py, swept by scripts/run_cold_bench.sh): both
phases byte-identical to the fault-free ground truth, the cold phase's
post-restart re-executions exactly ZERO, the baseline's exactly
``NUM_MAPS``, and the speedup >= 1.5x.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

import numpy as np

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import (PartitionerSpec, ShuffleHandle,
                                           TpuShuffleManager)
from sparkrdma_tpu.shuffle.recovery import (run_map_stage,
                                            run_reduce_with_retry)

NUM_EXECUTORS = 2
NUM_MAPS = 6
NUM_PARTITIONS = 4
ROWS_PER_MAP = 400


def _conf(tmpdir: str, cold: bool) -> TpuShuffleConf:
    return TpuShuffleConf(connect_timeout_ms=5000,
                          max_connection_attempts=2,
                          retry_backoff_base_ms=10,
                          retry_backoff_cap_ms=80,
                          pre_warm_connections=False,
                          use_cpp_runtime=False, native_fetch=False,
                          push_merge=True, merge_replicas=1,
                          push_deadline_ms=8000,
                          cold_tier=cold,
                          cold_tier_path=f"{tmpdir}/cold")


def _expected(seed: int) -> np.ndarray:
    return np.sort(np.concatenate(
        [np.random.default_rng(seed * 1_000_003 + m)
         .integers(0, 50_000, ROWS_PER_MAP)
         for m in range(NUM_MAPS)]).astype(np.uint64))


def _phase(tmpdir: str, tag: str, seed: int, cold: bool,
           map_cost_s: float) -> Dict:
    """One full lifecycle: cluster up, map + finalize (+ tier when
    ``cold``), kill the WHOLE fleet, fresh fleet reduces.  Returns the
    fresh fleet's timed makespan and the post-restart re-execution
    count."""
    from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
    from sparkrdma_tpu.shuffle.cold_tier import wait_for_tiered_coverage
    from sparkrdma_tpu.shuffle.push_merge import wait_for_coverage

    conf = _conf(tmpdir, cold)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=f"{tag}{i}",
                               spill_dir=f"{tmpdir}/{tag}{i}")
             for i in range(NUM_EXECUTORS)]
    fresh = []
    counter: Dict[int, int] = {}
    lock = threading.Lock()
    try:
        for ex in execs:
            ex.executor.wait_for_members(NUM_EXECUTORS)
        handle = ShuffleHandle(7, NUM_MAPS, NUM_PARTITIONS, 0,
                               PartitionerSpec("modulo"))
        driver.driver.register_shuffle(7, num_maps=NUM_MAPS,
                                       num_partitions=NUM_PARTITIONS)

        def map_fn(writer, map_id):
            with lock:
                counter[map_id] = counter.get(map_id, 0) + 1
            time.sleep(map_cost_s)  # the compute a re-execution repays
            rng = np.random.default_rng(seed * 1_000_003 + map_id)
            writer.write_batch(
                rng.integers(0, 50_000, ROWS_PER_MAP).astype(np.uint64))

        run_map_stage(execs, handle, map_fn)
        for ex in execs:
            if not ex.pusher.drain(15):
                raise TimeoutError("pusher never drained")
        if not wait_for_coverage(driver.driver, 7, NUM_MAPS,
                                 NUM_PARTITIONS, timeout=15):
            raise TimeoutError("merged coverage never completed")
        if cold:
            for ex in execs:
                if ex.executor.tiering is None or \
                        not ex.executor.tiering.drain(20):
                    raise TimeoutError("tiering never drained")
            if not wait_for_tiered_coverage(driver.driver, 7, NUM_MAPS,
                                            NUM_PARTITIONS, timeout=10):
                raise TimeoutError("tiered coverage never completed")

        # the full-fleet loss: every executor dies, every slot
        # tombstones — with cold off, nothing of the map stage survives
        mids = [ex.executor.manager_id for ex in execs]
        for ex in execs:
            ex.stop()
        for mid in mids:
            driver.driver.remove_member(mid)

        fresh = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                                   executor_id=f"{tag}f{i}",
                                   spill_dir=f"{tmpdir}/{tag}f{i}")
                 for i in range(NUM_EXECUTORS)]
        for ex in fresh:
            ex.executor.wait_for_members(2 * NUM_EXECUTORS)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                members = ex.executor.members()
                if all(members[s] == TOMBSTONE
                       for s in range(NUM_EXECUTORS)):
                    break
                time.sleep(0.02)
        pre = sum(counter.values())

        def reduce_fn(mgr, h):
            reader = mgr.get_reader(h, 0, h.num_partitions)
            keys, _ = reader.read_all()
            return np.sort(keys)

        t0 = time.monotonic()
        got = run_reduce_with_retry(fresh, handle, map_fn, reduce_fn,
                                    reducer_index=0,
                                    max_stage_retries=NUM_EXECUTORS + 2,
                                    driver=driver)
        wall_s = time.monotonic() - t0
        return {"wall_s": wall_s,
                "identical": bool(np.array_equal(got, _expected(seed))),
                "reexec": sum(counter.values()) - pre}
    finally:
        for ex in fresh:
            ex.stop()
        driver.stop()


def run_cold_microbench(tmpdir: str, seed: int = 0,
                        map_cost_s: float = 0.05) -> Dict:
    cold = _phase(tmpdir, "c", seed, cold=True, map_cost_s=map_cost_s)
    base = _phase(tmpdir, "b", seed, cold=False, map_cost_s=map_cost_s)
    return {
        "speedup": base["wall_s"] / max(cold["wall_s"], 1e-9),
        "identical": cold["identical"] and base["identical"],
        "reexec": {"cold": cold["reexec"], "baseline": base["reexec"]},
        "wall_s": {"cold": round(cold["wall_s"], 4),
                   "reexec": round(base["wall_s"], 4)},
        "maps": NUM_MAPS,
        "map_cost_s": map_cost_s,
        "seed": seed,
    }
