"""Reference-shaped API aliases.

The reference's whole deployment story is "change one config line and the
engine's existing calls keep working" (README.md:69-71:
``spark.shuffle.manager org.apache.spark.shuffle.rdma.RdmaShuffleManager``).
This module exposes the identical method surface —
``registerShuffle / getWriter / getReader / unregisterShuffle /
shuffleBlockResolver / stop`` (scala/RdmaShuffleManager.scala:143-310),
writer ``write / stop`` (writer/wrapper/RdmaWrapperShuffleWriter.scala:
102-122), reader ``read`` (scala/RdmaShuffleReader.scala:43) — over the
native snake_case API, so code written against the reference's shapes ports
mechanically.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import (
    PartitionerSpec,
    ShuffleHandle,
    TpuShuffleManager,
)


class ShuffleDependency:
    """The slice of Spark's ShuffleDependency the reference consumes:
    partition count + partitioner (scala/RdmaShuffleManager.scala:143-183),
    plus the aggregator (``combiner``) Spark carries on the dependency —
    when set, every writer of this shuffle applies map-side combine
    (the engine and shipped tasks pick it up automatically)."""

    def __init__(self, num_partitions: int,
                 partitioner: Optional[PartitionerSpec] = None,
                 row_payload_bytes: int = 0,
                 combiner=None):
        self.num_partitions = num_partitions
        self.partitioner = partitioner or PartitionerSpec("hash")
        self.row_payload_bytes = row_payload_bytes
        self.combiner = combiner


class SparkCompatShuffleManager:
    """camelCase facade over :class:`TpuShuffleManager`."""

    def __init__(self, conf: Optional[TpuShuffleConf] = None,
                 isDriver: bool = False, driverAddr=None,
                 executorId: str = "driver", **kw):
        self._m = TpuShuffleManager(conf, is_driver=isDriver,
                                    driver_addr=driverAddr,
                                    executor_id=executorId, **kw)

    # -- ShuffleManager SPI (scala/RdmaShuffleManager.scala:143-310) ------

    def registerShuffle(self, shuffleId: int, numMaps: int,
                        dependency: ShuffleDependency) -> ShuffleHandle:
        return self._m.register_shuffle(shuffleId, numMaps,
                                        dependency.num_partitions,
                                        dependency.partitioner,
                                        dependency.row_payload_bytes,
                                        combiner=dependency.combiner)

    def getWriter(self, handle: ShuffleHandle, mapId: int,
                  context=None, combiner=None) -> "CompatWriter":
        """``combiner`` is the map-side-combine hook (the aggregator half
        Spark's write path applies before spilling)."""
        return CompatWriter(self._m.get_writer(handle, mapId,
                                               combiner=combiner))

    def getReader(self, handle: ShuffleHandle, startPartition: int,
                  endPartition: int, context=None,
                  mapRange=None) -> "CompatReader":
        """``mapRange`` is the adaptive plan's split-task map slice
        (``(map_lo, map_hi)``); None reads the full map space."""
        return CompatReader(self._m.get_reader(handle, startPartition,
                                               endPartition,
                                               map_range=mapRange))

    def unregisterShuffle(self, shuffleId: int) -> bool:
        self._m.unregister_shuffle(shuffleId)
        return True

    @property
    def shuffleBlockResolver(self):
        return self._m.resolver

    def stop(self) -> None:
        self._m.stop()

    # escape hatch to the native API
    @property
    def native(self) -> TpuShuffleManager:
        return self._m

    @property
    def driverAddr(self):
        return self._m.driver_addr


class CompatWriter:
    """``write(records)`` + ``stop(success)``
    (writer/wrapper/RdmaWrapperShuffleWriter.scala:102-122)."""

    def __init__(self, inner):
        self._w = inner

    def write(self, records: Iterable[Tuple[int, np.ndarray]]) -> None:
        """records: iterable of (key, payload-row) pairs, or
        (keys-array, payload-matrix) batches."""
        if (isinstance(records, tuple) and len(records) == 2
                and isinstance(records[0], np.ndarray)):
            self._w.write_batch(*records)
            return
        keys, payloads = [], []
        for k, v in records:
            keys.append(k)
            payloads.append(v)
        if keys:
            self._w.write_batch(np.asarray(keys, dtype=np.uint64),
                                np.asarray(payloads, dtype=np.uint8))

    def stop(self, success: bool = True):
        return self._w.close(success)


class CompatReader:
    """``read()`` -> record iterator (scala/RdmaShuffleReader.scala:43).

    ``readBatches()`` is the performance surface: it yields
    ``(keys u64[N], payload u8[N, W])`` numpy batches straight off the
    fetcher with no per-row Python. ``read()`` exists for reference-shaped
    row-at-a-time consumers and costs a Python loop per record — at
    TeraSort scale use the batch form (everything in-tree does).
    """

    def __init__(self, inner):
        self._r = inner

    def read(self) -> Iterator[Tuple[int, np.ndarray]]:
        """Row-at-a-time compatibility shim over ``readBatches``."""
        for keys, payload in self._r.read():
            for i in range(len(keys)):
                yield int(keys[i]), payload[i]

    def readBatches(self):
        """Vectorized record batches — the fast path."""
        return self._r.read()

    def readSortedSpilled(self, memoryBudgetBytes: int = 64 << 20):
        """Globally key-sorted batches with bounded memory (the
        ExternalSorter delegation, scala/RdmaShuffleReader.scala:100-114)."""
        return self._r.read_sorted_spilled(memory_budget_bytes=memoryBudgetBytes)

    def readAggregated(self, combine):
        """Vectorized combine over the sorted partition (the aggregator's
        merge half Spark applies on the read side)."""
        return self._r.read_aggregated(combine)

    def readAll(self):
        """The whole partition range as one (keys, payload) batch."""
        return self._r.read_all()

    @property
    def metrics(self):
        return self._r.metrics
