"""Shuffle block resolver: owns staged map-output data on one executor.

Commit writes a sidecar ``.index`` file (little-endian u64 partition
lengths) next to the data file — the same durability contract Spark's
``IndexShuffleBlockResolver`` provides in the reference's stack (the plugin
intercepts ``writeIndexFileAndCommit``, scala/RdmaShuffleBlockResolver.scala:
59-65, precisely because those index files exist). ``recover()`` rebuilds
the in-memory state from those files after an executor restart, enabling
elastic rejoin without recomputing committed maps.

Hardened storage semantics (the serving path is one-sided — no server CPU
notices a bad block, PAPER §0 — so integrity and fencing live in the data
and the commit protocol itself):

* **Commit fencing**: every writer attempt holds a fencing token
  (:meth:`begin_attempt`); commit is a compare-and-swap on it. A zombie
  speculative attempt that commits after a newer attempt gets
  :class:`StaleAttemptError` (its tmp reaped) instead of clobbering the
  winner's committed file, and its publish is rejected at the driver
  (``DriverTable.publish`` fence check).
* **At-rest integrity** (``at_rest_checksum``): commit writes a CRC32
  sidecar (``<data>.crc``, per-partition + whole-file CRCs + the fence;
  ``utils/integrity.py``) BEFORE the index, so index-present implies
  sidecar-present across every crash window. ``recover()`` verifies the
  whole file on mmap-open; serve time spot-checks each partition on its
  first Python-path read, or the whole file on first location serve when
  a native block server carries the data bytes (the only Python
  touchpoint on that dataplane). A corrupt output is QUARANTINED —
  unregistered from the native server, every later serve raising
  :class:`~sparkrdma_tpu.utils.integrity.CorruptOutputError`, demoted on
  the wire to the retryable ``STATUS_CORRUPT`` — and heals only by map
  re-execution (shuffle/recovery.py).
* **Spill-dir health**: the writer's fallback-directory selection and
  quarantine bookkeeping (``spill_dirs``/``spill_dir_max_failures``)
  live here, shared by every writer of the executor.

Re-design of ``scala/RdmaShuffleBlockResolver.scala`` + the data-ownership
half of ``writer/wrapper/RdmaWrapperShuffleWriter.scala`` (its
``RdmaWrapperShuffleData`` owns ``mapId -> RdmaMappedFile``, :36):

* ``commit`` renames the written temp file over the data file and maps it
  for serving (rename-commit, RdmaWrapperShuffleWriter.scala:58-63;
  mapping + location-table fill, RdmaMappedFile.java:95-157),
* remote peers read locations and bytes through the ``ShuffleDataSource``
  protocol the control plane serves,
* ``remove_shuffle`` disposes mappings and deletes files
  (scala/RdmaShuffleBlockResolver.scala:45-53).

File **tokens** are executor-unique ints naming each committed spill file —
the role the registered MR's rkey plays in the reference.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import faults as fault_mod
from sparkrdma_tpu.runtime.staging import SpillFile
from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
from sparkrdma_tpu.utils import integrity

log = logging.getLogger(__name__)

CorruptOutputError = integrity.CorruptOutputError


class StaleAttemptError(RuntimeError):
    """A commit lost the fencing compare-and-swap: a NEWER attempt of the
    same map already committed. The loser's tmp file is reaped before
    this is raised; the caller (writer.close) reaps its spills and must
    NOT publish."""

    def __init__(self, shuffle_id: int, map_id: int, fence: int,
                 committed_fence: int):
        super().__init__(
            f"shuffle {shuffle_id} map {map_id}: attempt fence {fence} is "
            f"stale (fence {committed_fence} already committed)")
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.fence = fence
        self.committed_fence = committed_fence


class _SpillIntegrity:
    """Serve-time verification state of one committed spill."""

    __slots__ = ("part_crcs", "part_verified", "full_verified", "corrupt",
                 "lock")

    def __init__(self, part_crcs: Optional[List[int]], num_partitions: int,
                 full_verified: bool):
        self.part_crcs = part_crcs  # None = unattested (no sidecar data)
        self.part_verified = bytearray(num_partitions)
        self.full_verified = full_verified
        self.corrupt = False
        self.lock = threading.Lock()


class TpuShuffleBlockResolver:
    """shuffle_id -> map_id -> committed SpillFile; implements
    ShuffleDataSource for the executor's control server."""

    def __init__(self, spill_dir: str, block_server=None,
                 conf: Optional[TpuShuffleConf] = None):
        self.conf = conf or TpuShuffleConf()
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self._shuffles: Dict[int, Dict[int, SpillFile]] = {}
        self._by_token: Dict[int, SpillFile] = {}
        # externally-owned served files (push-merge segments, spill
        # overflow blobs): token-addressable for the block dataplane but
        # NOT map outputs — no location-table entry, no at-rest spot
        # checks (merged integrity is entry-CRC-verified reducer-side)
        self._external: Dict[int, List[SpillFile]] = {}
        self._lock = threading.Lock()
        self._tokens = itertools.count(1)
        # attempt/fence allocator: a plain guarded int (not
        # itertools.count) because recover() must be able to BUMP it past
        # fences recovered from sidecars — a restarted executor whose
        # counter restarted at 1 would otherwise lose the commit CAS to
        # its own pre-crash commits (every re-execution of a recovered
        # map would raise StaleAttemptError forever)
        self._attempt_lock = threading.Lock()
        self._next_attempt = 1
        self._commit_lock = threading.Lock()  # serializes the on-disk
        # unlink-index/rename-data/write-sidecar/write-index sequence AND
        # the fence CAS: concurrent attempts of one map must not
        # interleave into a mismatched durable set
        self._map_fences: Dict[Tuple[int, int], int] = {}
        self._integrity: Dict[int, _SpillIntegrity] = {}
        # attested (offset, length, crc32) ranges per served token — the
        # at-rest sidecar's partition CRCs (or a merge ledger's row CRCs)
        # re-shaped for serve-time reuse: a CRC-trailer serve over blocks
        # that tile these ranges combines the committed CRCs instead of
        # re-hashing the bytes, on BOTH serving dataplanes (the native
        # server gets the same table via bs_set_file_crcs)
        self._crc_ranges: Dict[int, list] = {}
        self.at_rest_checksum = bool(self.conf.at_rest_checksum)
        # spill-dir health, shared by every writer of this executor:
        # consecutive-failure counts; a dir past spill_dir_max_failures
        # is quarantined for the resolver's lifetime. Each configured
        # fallback is NAMESPACED by a digest of the primary spill dir:
        # co-hosted executors share one spill_dirs conf value, and an
        # un-namespaced sweep (recover/remove_shuffle — spill names carry
        # no executor identity) would delete a live sibling's in-flight
        # spill files. A restarted executor adopting the same primary dir
        # maps to the same namespace, so ITS orphans still get swept.
        import hashlib
        ns = "spill-" + hashlib.sha1(
            os.path.abspath(spill_dir).encode()).hexdigest()[:12]
        self.fallback_spill_dirs: List[str] = []
        for d in self.conf.resolved_spill_dirs():
            d = os.path.join(d, ns)
            try:
                os.makedirs(d, exist_ok=True)
                self.fallback_spill_dirs.append(d)
            except OSError as e:
                log.warning("fallback spill dir %s unusable at startup: %s",
                            d, e)
        self._dir_lock = threading.Lock()
        self._dir_failures: Dict[str, int] = {}
        self._dir_quarantined: set = set()
        # failure-path audit counters
        self.fenced_commits = 0
        self.corrupt_outputs = 0
        # tenancy (shuffle/tenancy.py): shuffle -> owning tenant, taught
        # by the manager at writer/reader creation and by the driver's
        # TenantMapMsg push; the disk ledger charges committed outputs,
        # merged segments and overflow blobs to their owner so one
        # tenant filling its spill quota fails ITS commit cleanly
        # instead of ENOSPCing every co-hosted tenant's spill dir.
        from sparkrdma_tpu.shuffle.tenancy import TenantLedger
        self._tenant_map: Dict[int, int] = {}
        self.disk_ledger = TenantLedger("spill", self.conf.tenant_spill_quota)
        self._token_disk: Dict[int, Tuple[int, int]] = {}  # token -> (tenant, bytes)
        # native epoll server (runtime/blockserver.py): committed files are
        # registered there so peers fetch bytes without Python in the path
        self.block_server = block_server

    # -- tenancy ---------------------------------------------------------

    def note_tenant(self, shuffle_id: int, tenant: int) -> None:
        """Record the shuffle's owning tenant (idempotent)."""
        with self._lock:
            self._tenant_map[shuffle_id] = int(tenant)

    def tenant_of(self, shuffle_id: int) -> int:
        """The shuffle's owning tenant (DEFAULT_TENANT when untaught —
        a lost TenantMapMsg push degrades fairness, never correctness)."""
        with self._lock:
            return self._tenant_map.get(shuffle_id, 0)

    def _release_disk(self, token: int) -> None:
        with self._lock:
            entry = self._token_disk.pop(token, None)
        if entry is not None:
            self.disk_ledger.release(*entry)

    # -- write side ------------------------------------------------------

    def begin_attempt(self, shuffle_id: int, map_id: int) -> int:
        """Allocate this attempt's fencing token. Monotone per resolver —
        across restarts too (recover() bumps the allocator past every
        fence it reads back from a sidecar) — so attempts of one map ON
        THIS EXECUTOR are totally ordered; the commit CAS and the
        driver's publish fence compare within that order (cross-executor
        overwrites always apply — recovery depends on last-writer-wins
        across executors)."""
        with self._attempt_lock:
            a = self._next_attempt
            self._next_attempt += 1
            return a

    def _bump_attempts(self, floor: int) -> None:
        """Never hand out an attempt/fence at or below ``floor``."""
        with self._attempt_lock:
            self._next_attempt = max(self._next_attempt, floor + 1)

    def data_tmp_path(self, shuffle_id: int, map_id: int,
                      fence: Optional[int] = None) -> str:
        # attempt-unique: concurrent speculative attempts of one map task
        # must not interleave writes in a shared tmp file. The streaming
        # writer derives its spill-file names from this path
        # (``<tmp>.s<seq>.tmp``) — everything an uncommitted attempt puts
        # on disk ends in ``.tmp``, so recover() and remove_shuffle() can
        # reap orphans without knowing the writer's internals.
        attempt = (fence if fence is not None
                   else self.begin_attempt(shuffle_id, map_id))
        return os.path.join(self.spill_dir,
                            f"shuffle_{shuffle_id}_{map_id}.{attempt}.tmp")

    # -- spill-dir health (consulted by writers) -------------------------

    def spill_dir_candidates(self) -> List[str]:
        """Healthy spill directories in preference order (primary first).
        Empty only when EVERY directory is quarantined — the writer then
        fails its attempt cleanly instead of spinning."""
        with self._dir_lock:
            return [d for d in [self.spill_dir] + self.fallback_spill_dirs
                    if d not in self._dir_quarantined]

    def record_spill_dir_failure(self, d: str) -> bool:
        """Count one failure against ``d``; returns True when this crossed
        ``spill_dir_max_failures`` and quarantined it."""
        with self._dir_lock:
            n = self._dir_failures.get(d, 0) + 1
            self._dir_failures[d] = n
            if (n >= self.conf.spill_dir_max_failures
                    and d not in self._dir_quarantined):
                self._dir_quarantined.add(d)
                log.warning("spill dir %s quarantined after %d consecutive "
                            "failures", d, n)
                return True
        return False

    def record_spill_dir_success(self, d: str) -> None:
        with self._dir_lock:
            self._dir_failures.pop(d, None)

    def spill_dir_health(self) -> dict:
        with self._dir_lock:
            return {"failures": dict(self._dir_failures),
                    "quarantined": sorted(self._dir_quarantined)}

    # -- commit ----------------------------------------------------------

    def committed_fence(self, shuffle_id: int, map_id: int) -> int:
        with self._commit_lock:
            return self._map_fences.get((shuffle_id, map_id), 0)

    def commit(self, shuffle_id: int, map_id: int, tmp_path: str,
               partition_lengths: Iterable[int],
               fence: Optional[int] = None,
               partition_crcs: Optional[List[int]] = None
               ) -> Tuple[SpillFile, int]:
        """Rename-commit + map for serving. Returns (spill, file_token).

        ``fence`` arms the commit CAS: a stale attempt (an OLDER fence
        than the committed one for this map) raises
        :class:`StaleAttemptError` with its tmp reaped — it can neither
        clobber the winner's data file nor reach publication. ``None``
        skips the CAS (fence-less callers, kept for compatibility).

        Durable ordering, including RE-commits of the same map: drop the
        old index (and sidecar), rename the data, write the sidecar, then
        atomically publish the new index. Every crash window leaves data
        WITHOUT an index, which recover() treats as lost (recompute) —
        never a mismatched set.
        """
        final = os.path.join(self.spill_dir,
                             f"shuffle_{shuffle_id}_{map_id}.data")
        lengths_arr = np.asarray(list(partition_lengths), dtype=np.uint64)
        if self.at_rest_checksum and partition_crcs is None:
            # callers that didn't stream CRCs during their writes (the
            # monolithic baseline) pay one read of the tmp here
            partition_crcs = integrity.partition_crcs_of_file(
                tmp_path, lengths_arr.tolist())
        index = final + ".index"
        sidecar = integrity.sidecar_path(final)
        # tenancy: the commit's disk bytes charge the owning tenant
        # BEFORE anything durable happens — past the spill quota the
        # attempt fails cleanly (tmp reaped, TenantQuotaError; NOT a
        # transient disk error, so no retry envelope burns on it)
        total_bytes = int(lengths_arr.sum())
        tenant = self.tenant_of(shuffle_id)
        try:
            # analysis: leak-ok(ownership transfers to _token_disk on success; _release_disk repays at unregister)
            self.disk_ledger.charge(tenant, total_bytes)
        except Exception:
            self._reap_quietly(tmp_path)
            raise
        with self._commit_lock:
            if fence is not None:
                committed = self._map_fences.get((shuffle_id, map_id), 0)
                if fence <= committed:
                    self.fenced_commits += 1
                    self._reap_quietly(tmp_path)
                    self.disk_ledger.release(tenant, total_bytes)
                    raise StaleAttemptError(shuffle_id, map_id, fence,
                                            committed)
            fault_mod.storage_check("commit", final)
            if os.path.exists(index):
                os.unlink(index)
            if os.path.exists(sidecar):
                os.unlink(sidecar)
            os.replace(tmp_path, final)
            try:
                if self.at_rest_checksum:
                    fault_mod.storage_check("index_write", sidecar)
                    integrity.write_sidecar(final, fence or 0,
                                            partition_crcs,
                                            lengths_arr.tolist())
                fault_mod.storage_check("index_write", index)
                lengths_arr.tofile(index + ".tmp")
                os.replace(index + ".tmp", index)
            except BaseException:
                # UN-commit: the rename already consumed the tmp, so a
                # failed sidecar/index write would otherwise orphan a
                # full-size index-less .data no sweep ever reaps (the
                # writer's cleanup only knows .tmp names). Either the
                # commit returns registered, or this attempt leaves
                # nothing on disk.
                for p in (final, sidecar, sidecar + ".tmp",
                          index, index + ".tmp"):
                    self._reap_quietly(p)
                self.disk_ledger.release(tenant, total_bytes)
                raise
            if fence is not None:
                self._map_fences[(shuffle_id, map_id)] = fence
        token = next(self._tokens)
        crc_ranges = (integrity.partition_crc_ranges(lengths_arr.tolist(),
                                                     partition_crcs)
                      if self.at_rest_checksum and partition_crcs else None)
        try:
            fault_mod.storage_check("mmap_open", final)
            spill = SpillFile(final, lengths_arr.tolist(), file_token=token)
            if self.block_server is not None:
                self.block_server.register_file(token, final,
                                                crc_ranges=crc_ranges,
                                                tenant=tenant)
        except BaseException:
            # same invariant past the durable writes: a commit that can't
            # be mapped/served is no commit — a durable triplet that never
            # registers would leak (remove_shuffle only reaps registered
            # spills), and the re-execution replaces it anyway
            for p in (final, sidecar, index):
                self._reap_quietly(p)
            with self._commit_lock:
                recorded = self._map_fences.get((shuffle_id, map_id))
                # analysis: epoch-eq-ok(identity check, not ordering: un-commit only the fence THIS attempt recorded)
                if fence is not None and recorded == fence:
                    del self._map_fences[(shuffle_id, map_id)]
            self.disk_ledger.release(tenant, total_bytes)
            raise
        with self._lock:
            # speculative/retried map task: replace and dispose the old
            # mapping (its file was already clobbered by the rename)
            old = self._shuffles.setdefault(shuffle_id, {}).get(map_id)
            self._shuffles[shuffle_id][map_id] = spill
            self._by_token[token] = spill
            self._token_disk[token] = (tenant, total_bytes)
            if crc_ranges:
                self._crc_ranges[token] = crc_ranges
            self._integrity[token] = _SpillIntegrity(
                partition_crcs if self.at_rest_checksum else None,
                len(lengths_arr),
                # just written and attested by the commit itself; serve
                # spot-checks re-verify only what could have rotted since
                full_verified=not self.at_rest_checksum)
            if old is not None:
                self._by_token.pop(old.file_token, None)
                self._integrity.pop(old.file_token, None)
                self._crc_ranges.pop(old.file_token, None)
        if old is not None:
            if self.block_server is not None:
                self.block_server.unregister_file(old.file_token)
            old._delete = False  # the path now belongs to the new spill
            old.dispose()
            self._release_disk(old.file_token)
        # at-rest corruption chaos hook: bit-rot of the COMMITTED bytes,
        # after the (clean) sidecar landed — exactly what verification
        # exists to catch
        fault_mod.storage_corrupt("commit", final)
        return spill, token

    def _reap_quietly(self, path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    # -- at-rest verification --------------------------------------------

    def _integrity_of(self, spill: SpillFile) -> Optional[_SpillIntegrity]:
        with self._lock:
            return self._integrity.get(spill.file_token)

    def _quarantine(self, spill: SpillFile, integ: _SpillIntegrity,
                    detail: str) -> None:
        """Demote a corrupt committed output: the native server stops
        serving its raw bytes, every later serve answers CORRUPT fast,
        and only a re-execution (re-commit) replaces it."""
        integ.corrupt = True
        self.corrupt_outputs += 1
        log.error("at-rest corruption in %s: %s (quarantined; the map "
                  "will be re-executed)", spill.path, detail)
        with self._lock:
            # its committed CRCs attest bytes the file no longer holds —
            # no serve may reuse them for a trailer again
            self._crc_ranges.pop(spill.file_token, None)
        if self.block_server is not None:
            # pin-safe: the native server withdraws the token immediately
            # but defers the munmap until in-flight serve pins drain, so
            # quarantining never unmaps under a concurrent vectored read
            self.block_server.unregister_file(spill.file_token)

    def _verify_file(self, spill: SpillFile, integ: _SpillIntegrity) -> None:
        """Whole-file CRC check (one streamed read), once."""
        with integ.lock:
            if integ.corrupt:
                raise CorruptOutputError(spill.path, "previously quarantined")
            if integ.full_verified or integ.part_crcs is None:
                return
            expected = integrity.combine_parts(
                integ.part_crcs, spill.partition_lengths.tolist())
            actual = integrity.file_crc32(spill.path)
            if actual != expected:
                self._quarantine(spill, integ,
                                 f"file CRC {actual:#x} != committed "
                                 f"{expected:#x}")
                raise CorruptOutputError(
                    spill.path, "whole-file CRC mismatch at serve time")
            integ.full_verified = True
            for p in range(len(integ.part_verified)):
                integ.part_verified[p] = 1

    def _spot_check_range(self, spill: SpillFile, integ: _SpillIntegrity,
                          offset: int, length: int) -> None:
        """Verify (once) each partition a served byte range touches.
        Serving reads the partition's bytes anyway; the first serve pays
        one CRC pass over the partitions it covers."""
        if integ.part_crcs is None:
            return
        with integ.lock:
            if integ.corrupt:
                raise CorruptOutputError(spill.path, "previously quarantined")
            if integ.full_verified or length == 0:
                return
            offs = spill.partition_offsets
            lens = spill.partition_lengths
            first = int(np.searchsorted(offs, offset, side="right")) - 1
            first = max(0, first)
            end = offset + length
            import zlib
            for p in range(first, len(offs)):
                if int(offs[p]) >= end:
                    break
                if integ.part_verified[p] or int(lens[p]) == 0:
                    continue
                buf = np.empty(int(lens[p]), dtype=np.uint8)
                spill.gather([int(offs[p])], [int(lens[p])], buf)
                if zlib.crc32(memoryview(buf)) != integ.part_crcs[p]:
                    self._quarantine(
                        spill, integ,
                        f"partition {p} CRC mismatch on first serve")
                    raise CorruptOutputError(
                        spill.path, f"partition {p} failed its at-rest "
                        f"CRC spot check")
                integ.part_verified[p] = 1

    # -- ShuffleDataSource (served to remote peers) ----------------------

    def get_output_table(self, shuffle_id: int, map_id: int) -> Optional[MapTaskOutput]:
        with self._lock:
            spill = self._shuffles.get(shuffle_id, {}).get(map_id)
        if spill is None:
            return None
        integ = self._integrity_of(spill)
        if integ is not None:
            if integ.corrupt:
                raise CorruptOutputError(spill.path,
                                         "previously quarantined")
            if self.block_server is not None and not integ.full_verified:
                # the native server serves the data bytes with no CPU in
                # the loop: this location serve is the ONLY Python
                # touchpoint on that dataplane, so the whole-file check
                # happens here (first serve of each output)
                self._verify_file(spill, integ)
        return spill.map_output

    def read_block(self, shuffle_id: int, buf_token: int, offset: int,
                   length: int) -> Optional[bytes]:
        with self._lock:
            spill = self._by_token.get(buf_token)
        if spill is None or offset + length > spill.size or offset < 0:
            return None
        fault_mod.storage_check("serve_read", spill.path)
        integ = self._integrity_of(spill)
        if integ is not None:
            self._spot_check_range(spill, integ, offset, length)
        if length == 0:
            return b""
        out = np.empty(length, dtype=np.uint8)
        spill.gather([offset], [length], out)
        return out.tobytes()

    def block_crc(self, shuffle_id: int, buf_token: int, offset: int,
                  length: int) -> Optional[int]:
        """The attested CRC32 of one served block when committed ranges
        (sidecar partitions / ledger rows) tile ``[offset, offset +
        length)`` exactly; None = not covered, the server recomputes.
        The Python serve loop's half of the CRC-reuse contract the
        native server implements in C (parity-tested both paths)."""
        with self._lock:
            ranges = self._crc_ranges.get(buf_token)
        if not ranges:
            return None
        return integrity.ranges_crc(ranges, offset, length)

    # -- local reads (short-circuit path) --------------------------------

    def local_blocks(self, shuffle_id: int, map_id: int,
                     start_partition: int, end_partition: int) -> Optional[bytes]:
        """Concatenated local partitions [start, end) of one map output
        (scala/RdmaShuffleFetcherIterator.scala:327-337 short-circuit)."""
        with self._lock:
            spill = self._shuffles.get(shuffle_id, {}).get(map_id)
        if spill is None:
            return None
        fault_mod.storage_check("serve_read", spill.path)
        offs = spill.partition_offsets[start_partition:end_partition]
        lens = spill.partition_lengths[start_partition:end_partition]
        integ = self._integrity_of(spill)
        if integ is not None and len(offs):
            self._spot_check_range(spill, integ, int(offs[0]),
                                   int(lens.sum()))
        out = np.empty(int(lens.sum()), dtype=np.uint8)
        spill.gather(offs, lens, out)
        return out.tobytes()

    def map_ids(self, shuffle_id: int):
        with self._lock:
            return sorted(self._shuffles.get(shuffle_id, {}).keys())

    def local_shuffles(self):
        """Shuffle ids with committed outputs on this resolver (the
        graceful-drain replication pass enumerates from here)."""
        with self._lock:
            return sorted(self._shuffles)

    def committed_outputs(self, shuffle_id: int) -> Dict[int, list]:
        """``map_id -> per-partition byte lengths`` for every committed
        output of the shuffle — exactly the vector a push-merge
        ``SegmentPusher.submit`` needs, so a draining executor can
        re-push everything it owns without re-reading index files."""
        with self._lock:
            return {m: [int(x) for x in s.partition_lengths]
                    for m, s in self._shuffles.get(shuffle_id, {}).items()}

    def local_output_bytes(self, shuffle_id: int) -> Dict[int, int]:
        """``map_id -> committed data bytes`` this resolver holds for the
        shuffle (per-partition length sums from the in-memory index, no
        file I/O) — the device-plane cost model's stage-size input.
        Per-map so callers can dedupe the copies speculation/retry leave
        on two executors."""
        with self._lock:
            return {m: int(s.partition_lengths.sum())
                    for m, s in self._shuffles.get(shuffle_id, {}).items()}

    # -- externally-owned served files (push-merge) ----------------------

    def register_external(self, shuffle_id: int, path: str,
                          length: int, crc_ranges=None) -> int:
        """Make one externally-owned file (a finalized merged segment or
        an overflow blob, shuffle/push_merge.py) token-addressable on
        BOTH serving dataplanes — the Python ``read_block`` path and the
        native block server — without entering the map-output tables.
        ``crc_ranges`` — optional attested ``(offset, length, crc32)``
        ranges (the merge ledger's surviving rows) — feeds the same
        serve-time CRC reuse committed outputs get from their sidecar.
        The caller owns the file's content; :meth:`release_externals`
        (or ``remove_shuffle``) unregisters and deletes it."""
        token = next(self._tokens)
        spill = SpillFile(path, [length], file_token=token)
        if self.block_server is not None:
            self.block_server.register_file(token, path,
                                            crc_ranges=crc_ranges,
                                            tenant=self.tenant_of(shuffle_id))
        with self._lock:
            self._by_token[token] = spill
            if crc_ranges:
                self._crc_ranges[token] = sorted(
                    (int(o), int(ln), int(c) & 0xFFFFFFFF)
                    for o, ln, c in crc_ranges if int(ln) > 0)
            self._external.setdefault(shuffle_id, []).append(spill)
        return token

    def release_externals(self, shuffle_id: int) -> None:
        with self._lock:
            spills = self._external.pop(shuffle_id, [])
            for spill in spills:
                self._by_token.pop(spill.file_token, None)
                self._crc_ranges.pop(spill.file_token, None)
        for spill in spills:
            if self.block_server is not None:
                self.block_server.unregister_file(spill.file_token)
            spill.dispose()

    # -- lifecycle -------------------------------------------------------

    def _sweep_tmps(self, shuffle_prefix: Optional[str] = None) -> None:
        """Delete orphan ``.tmp`` attempt files (writer data tmps and
        ``.s<seq>.tmp`` spill files) in the primary AND every fallback
        spill dir, optionally scoped to one shuffle's prefix."""
        for d in [self.spill_dir] + self.fallback_spill_dirs:
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if not name.endswith(".tmp"):
                    continue
                if shuffle_prefix is not None \
                        and not name.startswith(shuffle_prefix):
                    continue
                self._reap_quietly(os.path.join(d, name))

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            spills = self._shuffles.pop(shuffle_id, {})
            for spill in spills.values():
                self._by_token.pop(spill.file_token, None)
                self._integrity.pop(spill.file_token, None)
                self._crc_ranges.pop(spill.file_token, None)
        for spill in spills.values():
            if self.block_server is not None:
                self.block_server.unregister_file(spill.file_token)
            index = spill.path + ".index"
            sidecar = integrity.sidecar_path(spill.path)
            spill.dispose()
            self._release_disk(spill.file_token)
            if os.path.exists(index):
                os.unlink(index)
            if os.path.exists(sidecar):
                os.unlink(sidecar)
        # reap this shuffle's uncommitted attempts (writer tmp + spill
        # files from crashed/aborted tasks) — in every spill dir
        self._sweep_tmps(f"shuffle_{shuffle_id}_")
        # externally-owned served files (merged segments, overflow
        # blobs) die with the shuffle too
        self.release_externals(shuffle_id)
        with self._lock:
            self._tenant_map.pop(shuffle_id, None)

    def reap_orphans(self, live_shuffle_ids, min_age_s: float = 60.0
                     ) -> int:
        """Driver-driven GC sweep: delete committed triplets
        (``shuffle_<id>_<map>.data`` + index + sidecar) whose shuffle is
        neither in ``live_shuffle_ids`` (the driver's registered set)
        nor registered in THIS resolver — the files a dead or wedged
        process left behind that no unregister push will ever name.
        ``min_age_s`` guards the snapshot race: a shuffle registering
        (and a commit renaming its tmp durable) AFTER the caller took
        the live set would otherwise look orphaned for a moment — only
        files older than the guard are eligible. Returns the number of
        data files reaped."""
        import re
        live = set(int(s) for s in live_shuffle_ids)
        with self._lock:
            local = set(self._shuffles)
        pat = re.compile(r"^shuffle_(\d+)_\d+\.data$")
        cutoff = time.time() - min_age_s
        reaped = 0
        for d in [self.spill_dir] + self.fallback_spill_dirs:
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                m = pat.match(name)
                if m is None:
                    continue
                sid = int(m.group(1))
                if sid in live or sid in local:
                    continue
                path = os.path.join(d, name)
                try:
                    if os.stat(path).st_mtime > cutoff:
                        continue  # too fresh: may be a racing commit
                except OSError:
                    continue
                self._reap_quietly(path)
                self._reap_quietly(path + ".index")
                self._reap_quietly(integrity.sidecar_path(path))
                reaped += 1
        return reaped

    def recover(self) -> Dict[int, list]:
        """Rebuild state from committed (data, index) pairs on disk.

        Returns {shuffle_id: [(map_id, file_token), ...]} of recovered
        outputs so the caller can re-publish them (elastic rejoin: the
        restarted executor gets a fresh slot, re-publishes, and reducers
        route to it); the fence each output committed with is readable
        via :meth:`committed_fence`. Orphaned ``.tmp`` spill attempts
        from the crashed process are deleted — fallback spill dirs
        included — and, with ``at_rest_checksum`` on, every recovered
        file is verified against its CRC sidecar on mmap-open: corrupt
        (or sidecar-less, hence unattested) files are treated as lost so
        the map recomputes instead of serving rot."""
        import re as _re
        recovered: Dict[int, list] = {}
        self._sweep_tmps()
        for name in sorted(os.listdir(self.spill_dir)):
            m = _re.fullmatch(r"shuffle_(\d+)_(\d+)\.data", name)
            if not m:
                continue
            data_path = os.path.join(self.spill_dir, name)
            index_path = data_path + ".index"
            if not os.path.exists(index_path):
                continue  # never fully committed
            shuffle_id, map_id = int(m.group(1)), int(m.group(2))
            lengths = np.fromfile(index_path, dtype=np.uint64)
            if len(lengths) == 0:
                continue
            fence = 0
            part_crcs: Optional[List[int]] = None
            if self.at_rest_checksum:
                sidecar = integrity.read_sidecar(data_path)
                if sidecar is None:
                    # committed without attestation (checksum was off, or
                    # a pre-sidecar build): a restart cannot tell rot
                    # from truth — recompute rather than serve blind, and
                    # REAP the pair (it will never be registered, so no
                    # later sweep would; leaving it leaks a full-size
                    # file and re-logs this on every restart)
                    log.warning("recover: %s has no CRC sidecar; treating "
                                "as lost", name)
                    for p in (data_path, index_path):
                        self._reap_quietly(p)
                    continue
                fence, part_crcs, file_crc = sidecar
                try:
                    fault_mod.storage_check("mmap_open", data_path)
                    actual = integrity.file_crc32(data_path)
                except OSError as e:
                    log.warning("recover: %s unreadable (%s); treating as "
                                "lost", name, e)
                    continue
                if actual != file_crc:
                    self.corrupt_outputs += 1
                    log.error("recover: %s failed its at-rest CRC "
                              "(%#x != committed %#x); dropping so the "
                              "map recomputes", name, actual, file_crc)
                    for p in (data_path, index_path,
                              integrity.sidecar_path(data_path)):
                        self._reap_quietly(p)
                    self._bump_attempts(fence)
                    continue
            try:
                token = next(self._tokens)
                fault_mod.storage_check("mmap_open", data_path)
                spill = SpillFile(data_path, lengths.tolist(),
                                  file_token=token)
            except (ValueError, OSError):
                continue  # truncated data file: treat as lost
            crc_ranges = (integrity.partition_crc_ranges(lengths.tolist(),
                                                         part_crcs)
                          if part_crcs else None)
            if self.block_server is not None:
                try:
                    self.block_server.register_file(token, data_path,
                                                    crc_ranges=crc_ranges)
                except OSError as e:
                    # one unmappable file must cost ONE output (treated
                    # as lost → recompute), not abort recovery of every
                    # other committed output
                    log.warning("recover: %s unservable by the native "
                                "block server (%s); treating as lost",
                                name, e)
                    spill._delete = False
                    spill.dispose()
                    continue
            with self._lock:
                self._shuffles.setdefault(shuffle_id, {})[map_id] = spill
                self._by_token[token] = spill
                if crc_ranges:
                    self._crc_ranges[token] = crc_ranges
                # the mmap-open verify above attested the file for
                # REGISTRATION, but must not exempt it from serve-time
                # spot checks: rot landing between recover and first
                # serve would otherwise be served silently (the fetch
                # CRC trailer is computed over the rotted bytes) — so
                # first serves re-verify, exactly like a fresh commit
                self._integrity[token] = _SpillIntegrity(
                    part_crcs, len(lengths),
                    full_verified=not self.at_rest_checksum)
            with self._commit_lock:
                prev = self._map_fences.get((shuffle_id, map_id), 0)
                self._map_fences[(shuffle_id, map_id)] = max(prev, fence)
            # the allocator restarted at 1 with this process: new attempts
            # of a recovered map must out-fence its pre-crash commit, or
            # every re-execution (corrupt-output healing included) would
            # lose the CAS to a dead process forever
            self._bump_attempts(fence)
            recovered.setdefault(shuffle_id, []).append((map_id, token))
        # orphan sidecars (data reaped or never committed) confuse nothing
        # but waste space; sweep them (sidecars live only in the primary
        # dir — they are written next to the committed data file)
        try:
            names = os.listdir(self.spill_dir)
        except OSError:
            names = []
        for name in names:
            if name.endswith(".data.crc") and not os.path.exists(
                    os.path.join(self.spill_dir, name[:-len(".crc")])):
                self._reap_quietly(os.path.join(self.spill_dir, name))
        return recovered

    def stop(self) -> None:
        with self._lock:
            shuffle_ids = set(self._shuffles) | set(self._external)
        for sid in sorted(shuffle_ids):
            self.remove_shuffle(sid)
