"""Shuffle block resolver: owns staged map-output data on one executor.

Commit writes a sidecar ``.index`` file (little-endian u64 partition
lengths) next to the data file — the same durability contract Spark's
``IndexShuffleBlockResolver`` provides in the reference's stack (the plugin
intercepts ``writeIndexFileAndCommit``, scala/RdmaShuffleBlockResolver.scala:
59-65, precisely because those index files exist). ``recover()`` rebuilds
the in-memory state from those files after an executor restart, enabling
elastic rejoin without recomputing committed maps.

Re-design of ``scala/RdmaShuffleBlockResolver.scala`` + the data-ownership
half of ``writer/wrapper/RdmaWrapperShuffleWriter.scala`` (its
``RdmaWrapperShuffleData`` owns ``mapId -> RdmaMappedFile``, :36):

* ``commit`` renames the written temp file over the data file and maps it
  for serving (rename-commit, RdmaWrapperShuffleWriter.scala:58-63;
  mapping + location-table fill, RdmaMappedFile.java:95-157),
* remote peers read locations and bytes through the ``ShuffleDataSource``
  protocol the control plane serves
  (scala/RdmaShuffleBlockResolver.scala:73-78 serves local partitions;
  remote reads bypass the resolver in the reference because the NIC serves
  them — here the executor endpoint calls back into the resolver),
* ``remove_shuffle`` disposes mappings and deletes files
  (scala/RdmaShuffleBlockResolver.scala:45-53).

File **tokens** are executor-unique ints naming each committed spill file —
the role the registered MR's rkey plays in the reference.
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from sparkrdma_tpu.runtime.staging import SpillFile
from sparkrdma_tpu.shuffle.map_output import MapTaskOutput


class TpuShuffleBlockResolver:
    """shuffle_id -> map_id -> committed SpillFile; implements
    ShuffleDataSource for the executor's control server."""

    def __init__(self, spill_dir: str, block_server=None):
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self._shuffles: Dict[int, Dict[int, SpillFile]] = {}
        self._by_token: Dict[int, SpillFile] = {}
        self._lock = threading.Lock()
        self._tokens = itertools.count(1)
        self._attempts = itertools.count(1)
        self._commit_lock = threading.Lock()  # serializes the on-disk
        # unlink-index/rename-data/write-index sequence: concurrent attempts
        # of one map must not interleave into a mismatched durable pair
        # native epoll server (runtime/blockserver.py): committed files are
        # registered there so peers fetch bytes without Python in the path
        self.block_server = block_server

    # -- write side ------------------------------------------------------

    def data_tmp_path(self, shuffle_id: int, map_id: int) -> str:
        # attempt-unique: concurrent speculative attempts of one map task
        # must not interleave writes in a shared tmp file. The streaming
        # writer derives its spill-file names from this path
        # (``<tmp>.s<seq>.tmp``) — everything an uncommitted attempt puts
        # on disk ends in ``.tmp``, so recover() and remove_shuffle() can
        # reap orphans without knowing the writer's internals.
        attempt = next(self._attempts)
        return os.path.join(self.spill_dir,
                            f"shuffle_{shuffle_id}_{map_id}.{attempt}.tmp")

    def commit(self, shuffle_id: int, map_id: int, tmp_path: str,
               partition_lengths: Iterable[int]) -> Tuple[SpillFile, int]:
        """Rename-commit + map for serving. Returns (spill, file_token)."""
        final = os.path.join(self.spill_dir,
                             f"shuffle_{shuffle_id}_{map_id}.data")
        lengths_arr = np.asarray(list(partition_lengths), dtype=np.uint64)
        # Crash-safe ordering, including RE-commits of the same map: drop
        # the old index, rename the data, then atomically publish the new
        # index. Every crash window leaves data WITHOUT an index, which
        # recover() treats as lost (recompute) — never a mismatched pair.
        # The lock keeps concurrent attempts of one map from interleaving
        # the three steps (which could durably pair A's index with B's data).
        index = final + ".index"
        with self._commit_lock:
            if os.path.exists(index):
                os.unlink(index)
            os.replace(tmp_path, final)
            lengths_arr.tofile(index + ".tmp")
            os.replace(index + ".tmp", index)
        token = next(self._tokens)
        spill = SpillFile(final, lengths_arr.tolist(), file_token=token)
        if self.block_server is not None:
            self.block_server.register_file(token, final)
        with self._lock:
            # speculative/retried map task: replace and dispose the old
            # mapping (its file was already clobbered by the rename)
            old = self._shuffles.setdefault(shuffle_id, {}).get(map_id)
            self._shuffles[shuffle_id][map_id] = spill
            self._by_token[token] = spill
            if old is not None:
                self._by_token.pop(old.file_token, None)
        if old is not None:
            if self.block_server is not None:
                self.block_server.unregister_file(old.file_token)
            old._delete = False  # the path now belongs to the new spill
            old.dispose()
        return spill, token

    # -- ShuffleDataSource (served to remote peers) ----------------------

    def get_output_table(self, shuffle_id: int, map_id: int) -> Optional[MapTaskOutput]:
        with self._lock:
            spill = self._shuffles.get(shuffle_id, {}).get(map_id)
        return spill.map_output if spill is not None else None

    def read_block(self, shuffle_id: int, buf_token: int, offset: int,
                   length: int) -> Optional[bytes]:
        with self._lock:
            spill = self._by_token.get(buf_token)
        if spill is None or offset + length > spill.size or offset < 0:
            return None
        if length == 0:
            return b""
        out = np.empty(length, dtype=np.uint8)
        spill.gather([offset], [length], out)
        return out.tobytes()

    # -- local reads (short-circuit path) --------------------------------

    def local_blocks(self, shuffle_id: int, map_id: int,
                     start_partition: int, end_partition: int) -> Optional[bytes]:
        """Concatenated local partitions [start, end) of one map output
        (scala/RdmaShuffleFetcherIterator.scala:327-337 short-circuit)."""
        with self._lock:
            spill = self._shuffles.get(shuffle_id, {}).get(map_id)
        if spill is None:
            return None
        offs = spill.partition_offsets[start_partition:end_partition]
        lens = spill.partition_lengths[start_partition:end_partition]
        out = np.empty(int(lens.sum()), dtype=np.uint8)
        spill.gather(offs, lens, out)
        return out.tobytes()

    def map_ids(self, shuffle_id: int):
        with self._lock:
            return sorted(self._shuffles.get(shuffle_id, {}).keys())

    # -- lifecycle -------------------------------------------------------

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            spills = self._shuffles.pop(shuffle_id, {})
            for spill in spills.values():
                self._by_token.pop(spill.file_token, None)
        for spill in spills.values():
            if self.block_server is not None:
                self.block_server.unregister_file(spill.file_token)
            index = spill.path + ".index"
            spill.dispose()
            if os.path.exists(index):
                os.unlink(index)
        # reap this shuffle's uncommitted attempts (writer tmp + spill
        # files from crashed/aborted tasks) — previously these lingered
        # until a restart's recover() swept the whole dir
        prefix = f"shuffle_{shuffle_id}_"
        try:
            names = os.listdir(self.spill_dir)
        except OSError:
            return
        for name in names:
            if name.startswith(prefix) and name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.spill_dir, name))
                except OSError:
                    pass

    def recover(self) -> Dict[int, list]:
        """Rebuild state from committed (data, index) pairs on disk.

        Returns {shuffle_id: [(map_id, file_token), ...]} of recovered
        outputs so the caller can re-publish them (elastic rejoin: the
        restarted executor gets a fresh slot, re-publishes, and reducers
        route to it). Orphaned ``.tmp`` spill attempts from the crashed
        process are deleted.
        """
        import re as _re
        recovered: Dict[int, list] = {}
        for name in sorted(os.listdir(self.spill_dir)):
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.spill_dir, name))
                except OSError:
                    pass
                continue
            m = _re.fullmatch(r"shuffle_(\d+)_(\d+)\.data", name)
            if not m:
                continue
            data_path = os.path.join(self.spill_dir, name)
            index_path = data_path + ".index"
            if not os.path.exists(index_path):
                continue  # never fully committed
            lengths = np.fromfile(index_path, dtype=np.uint64)
            if len(lengths) == 0:
                continue
            try:
                shuffle_id, map_id = int(m.group(1)), int(m.group(2))
                token = next(self._tokens)
                spill = SpillFile(data_path, lengths.tolist(),
                                  file_token=token)
            except ValueError:
                continue  # truncated data file: treat as lost
            if self.block_server is not None:
                self.block_server.register_file(token, data_path)
            with self._lock:
                self._shuffles.setdefault(shuffle_id, {})[map_id] = spill
                self._by_token[token] = spill
            recovered.setdefault(shuffle_id, []).append((map_id, token))
        return recovered

    def stop(self) -> None:
        with self._lock:
            shuffle_ids = list(self._shuffles.keys())
        for sid in shuffle_ids:
            self.remove_shuffle(sid)
