"""Skewed-workload microbench: the adaptive reduce planner's win, measured.

Real skewed traffic (zipfian keys, hot joins) makes one reducer the
stage straggler while the rest idle — the reduce stage's wall-clock is
the HOT partition's cost, not the mean. The planner splits the hot
partition across reducers by map-range and coalesces the tiny tail, so
the stage's makespan drops toward ``total / workers``.

Harness shape (same philosophy as ``fetch_bench``/``iter_bench``: a real
driver + multi-executor cluster over loopback, a deterministic cost shim
where loopback hides the real-world cost): per-task reduce COMPUTE is
modeled as ``bytes x compute_rate`` (the sort/merge work a reducer does
scales with its input bytes — exactly the cost that makes a hot
partition a straggler), and both plans run IN THE SAME PROCESS on the
same worker pool, so the reported ratio cancels host noise the way
``dense_exchange_guard`` does. The byte counts, plan shape, and
``identical`` parity gate are exact regardless of timing.

Two generators, the skew shapes named by ROADMAP item 3:

* ``zipfian_keys`` — zipf-distributed terasort keys (one hot partition
  holding most of the bytes plus a long tiny tail);
* ``skewed_join_keys`` — a join's probe side where one hot key carries
  most of the rows (the hot-join shape).

Shared by ``bench.py`` (the ``skew_speedup`` secondary), the tier-1
acceptance test (>= 1.5x, byte-identical, identity plan on uniform
input), and ``scripts/run_skew_bench.sh``'s seed sweep.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

import numpy as np

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.planner import identity_plan, reduce_balance


def zipfian_keys(n: int, num_partitions: int, a: float = 2.0,
                 seed: int = 0) -> np.ndarray:
    """Zipf-distributed terasort keys: rank r appears with p ~ r^-a, so
    rank 1 (and with it partition ``1 % num_partitions`` under the
    modulo partitioner) carries most of the mass — a ~60% hot partition
    at a=2.0 — while high ranks form the tiny coalescable tail."""
    rng = np.random.default_rng(seed)
    return rng.zipf(a, size=n).astype(np.uint64)


def skewed_join_keys(n: int, num_partitions: int, hot_frac: float = 0.6,
                     seed: int = 0) -> np.ndarray:
    """A skewed join's probe-side keys: ``hot_frac`` of the rows share
    ONE hot key (the celebrity-row shape of production joins); the rest
    spread uniformly over the key space."""
    rng = np.random.default_rng(seed)
    hot_key = np.uint64(1)
    uniform = rng.integers(0, num_partitions * 64, size=n,
                           dtype=np.uint64)
    return np.where(rng.random(n) < hot_frac, hot_key, uniform)


_GENERATORS = {"terasort": zipfian_keys, "join": skewed_join_keys}


def run_skew_microbench(spill_root: str, workload: str = "terasort",
                        num_maps: int = 6, num_partitions: int = 16,
                        rows_per_map: int = 4000,
                        payload_bytes: int = 24,
                        workers: int = 4,
                        compute_s_per_mb: float = 2.0,
                        seed: int = 0,
                        uniform: bool = False,
                        reps: int = 2) -> Dict:
    """Measure the reduce stage's makespan under the static plan (one
    reducer per partition) vs the adaptive plan (coalesce + split +
    placement), same process, same worker pool. Returns::

        {"wall_s": {"static": s, "adaptive": s}, "skew_speedup": ratio,
         "identical": bool, "plan": counts, "is_identity": bool,
         "reduce_balance": {"static": x, "adaptive": y}, "bytes": total}

    ``identical`` is byte-level over the canonicalized (key-sorted) full
    stage output. With ``uniform=True`` the keys are uniform instead —
    the plan must come out as the identity plan (no regression for
    balanced workloads)."""
    import os

    gen = _GENERATORS[workload]
    row_bytes = 8 + payload_bytes
    # thresholds sized against the UNIFORM per-partition share: a
    # partition past 3x the share is hot (splits), one under half of it
    # is tiny (coalesces) — so a balanced dataset sits between the two
    # and must come out as the identity plan (the no-regression gate)
    share = (num_maps * rows_per_map * row_bytes) // num_partitions
    conf_kw = dict(connect_timeout_ms=20000, use_cpp_runtime=False,
                   pre_warm_connections=False, adaptive_plan=True,
                   split_threshold_bytes=max(1024, 3 * share),
                   coalesce_target_bytes=max(1, share // 2))
    conf = TpuShuffleConf(**conf_kw)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(TpuShuffleConf(**conf_kw),
                               driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=os.path.join(spill_root, f"s{i}"))
             for i in range(3)]
    try:
        for ex in execs:
            ex.executor.wait_for_members(3)
        handle = driver.register_shuffle(
            3, num_maps, num_partitions, PartitionerSpec("modulo"),
            row_payload_bytes=payload_bytes)
        rng = np.random.default_rng(seed)
        for m in range(num_maps):
            if uniform:
                keys = np.arange(m, m + rows_per_map,
                                 dtype=np.uint64) % num_partitions
            else:
                keys = gen(rows_per_map, num_partitions,
                           seed=seed * 1000 + m)
            # every map commits on the REDUCER's executor: tasks read
            # through the local short-circuit, so the measured makespan
            # is the compute model's (the plan-balance win under test),
            # not loopback scheduling noise — the remote dataplanes'
            # parity for planned ranges has its own tests
            # (tests/test_planner.py sweeps all four combos)
            w = execs[0].get_writer(handle, m)
            w.write_batch(keys, rng.integers(
                0, 255, (len(keys), payload_bytes),
                dtype=np.uint64).astype(np.uint8))
            w.close()
        plan = driver.plan_reduce(handle)
        static = identity_plan(handle.shuffle_id, num_maps,
                               num_partitions)
        # all tasks read through ONE reducer-side manager so both plans
        # fetch every byte remotely under identical machinery; the
        # compute shim (bytes x rate — the sort/merge cost that makes a
        # hot reducer the straggler) is what the makespan measures
        reducer = execs[0]
        compute_rate = compute_s_per_mb / (1 << 20)
        hist = driver.driver.size_histogram(handle.shuffle_id)

        def est_bytes(task):
            return sum(hist.map_bytes(m, task.start_partition,
                                      task.end_partition)
                       for m in range(task.map_start, task.map_end))

        def run_stage(tasks):
            # longest-task-first dispatch for BOTH plans (what any
            # size-aware scheduler does); the histogram supplies the
            # estimates either way, so the comparison stays fair
            tasks = sorted(tasks, key=lambda t: (-est_bytes(t),
                                                 t.task_id))
            rows = {}
            task_bytes = {}

            def one(task):
                reader = reducer.get_reader(
                    handle, task.start_partition, task.end_partition,
                    map_range=(task.map_start, task.map_end))
                keys, payload = reader.read_all()
                nbytes = len(keys) * row_bytes
                time.sleep(nbytes * compute_rate)
                return task.task_id, keys, payload, nbytes

            pool = ThreadPoolExecutor(max_workers=workers)
            t0 = time.perf_counter()
            try:
                for tid, keys, payload, nbytes in pool.map(one, tasks):
                    rows[tid] = (keys, payload)
                    task_bytes[tid] = nbytes
            finally:
                pool.shutdown(wait=True)
            wall = time.perf_counter() - t0
            order = sorted(tasks, key=lambda t: (t.start_partition,
                                                 t.map_start))
            keys = np.concatenate([rows[t.task_id][0] for t in order])
            payload = np.concatenate([rows[t.task_id][1] for t in order])
            return wall, keys, payload, list(task_bytes.values())

        # warmup: one untimed pass resolves metadata into the warm
        # caches and dials every connection, so neither measured mode
        # pays cold-start costs the other skipped (the same reason
        # dense_exchange_guard warms before timing)
        for t in static.tasks:
            r = reducer.get_reader(handle, t.start_partition,
                                   t.end_partition)
            r.read_all()
        # best-of-``reps`` per mode (the fetch bench's convention): the
        # makespan model is deterministic, the best rep sheds scheduler
        # noise the same way for both modes
        results = {}
        for mode, p in (("static", static),
                        ("adaptive", plan if plan is not None else static)):
            best = None
            for _ in range(max(1, reps)):
                run = run_stage(p.tasks)
                if best is None or run[0] < best[0]:
                    best = run
            results[mode] = best

        def canonical(keys, payload):
            order = np.lexsort(
                tuple(payload[:, c] for c in
                      range(payload.shape[1] - 1, -1, -1)) + (keys,))
            return keys[order], payload[order]

        ks, ps = canonical(results["static"][1], results["static"][2])
        ka, pa = canonical(results["adaptive"][1], results["adaptive"][2])
        identical = bool(np.array_equal(ks, ka)
                         and np.array_equal(ps, pa))
        wall = {m: results[m][0] for m in results}
        return {
            "workload": workload,
            "wall_s": {m: round(t, 4) for m, t in wall.items()},
            "skew_speedup": (round(wall["static"] / wall["adaptive"], 3)
                             if wall["adaptive"] else 0.0),
            "identical": identical,
            "plan": plan.counts() if plan is not None else None,
            "is_identity": plan.is_identity if plan is not None else True,
            "reduce_balance": {
                m: round(reduce_balance(results[m][3]), 3)
                for m in results},
            "bytes": int(sum(results["static"][3])),
            "workers": workers,
        }
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
