"""Push-merge shuffle dataplane: background per-partition segment merge.

The reduce side's remaining fan-in problem: PR 3's coalescing batches the
REQUESTS per peer, but the bytes themselves stay scattered across M map
files — a reducer still drives M small server-side reads per partition,
and a lost executor still re-executes every map it owned (ROADMAP item
5). This module is the Magnet-style fix, one mechanism for both:

* **Push** (:class:`SegmentPusher`): after a map commits, a bounded
  background pusher streams its per-partition blocks — fence attached,
  sizes already in hand from the commit's partition lengths — to
  ``merge_replicas`` peer executors chosen by partition-range
  (:func:`merge_targets`). Pushes start at map COMMIT, overlapping the
  rest of the map stage, and are backpressured through
  :class:`~sparkrdma_tpu.runtime.pool.BufferPool` leases so they can
  never starve foreground writes; a push older than
  ``push_deadline_ms`` is dropped (the straggler stays per-map-fetched,
  never blocks the stage).
* **Merge** (:class:`MergeStore`): each target appends pushed blocks
  into a per-(shuffle, partition) segment file with a per-block
  CRC+fence LEDGER — a stale attempt's push is rejected, a newer fence
  supersedes the stale bytes (excluded from the finalized ranges).
  Finalize (driver broadcast at map-stage completion, or the
  ``push_deadline_ms`` idle backstop) seals each segment, registers it
  with the ordinary block resolver/server, and publishes a
  :class:`MergedEntry` into the driver's :class:`MergedDirectory` —
  ONE-SIDED, under the existing epoch machinery, per "RPC Considered
  Harmful" (PAPERS.md): the serving path stays the existing block
  server with no extra server CPU per read.
* **Serve**: reducers resolve merged-segment-FIRST
  (shuffle/fetcher.py): one sequential vectored read per partition
  instead of an M-way per-map fan-in, entry-CRC verified reducer-side;
  a CRC-bad or unreachable segment degrades to the per-map dataplane
  for exactly that partition, riding PR 3's sub-block healing.
* **Recover**: executor loss becomes a location-table flip — maps every
  live replica covers are RE-POINTED (shuffle/recovery.py), only what
  no replica covers re-executes.
* **Overflow**: tiered spill may overflow to a merge peer on ENOSPC
  (:class:`MergeClient.overflow_spill`) instead of failing the attempt;
  the writer fetches the blob back at merge time over the ordinary data
  plane.
"""

from __future__ import annotations

import logging
import os
import queue
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.transport import TransportError

log = logging.getLogger(__name__)


# -- coverage bitmaps ------------------------------------------------------

def bitmap_set(bitmap: bytearray, i: int) -> None:
    bitmap[i >> 3] |= 1 << (i & 7)


def bitmap_get(bitmap: bytes, i: int) -> bool:
    byte = i >> 3
    return byte < len(bitmap) and bool(bitmap[byte] & (1 << (i & 7)))


def bitmap_new(nbits: int) -> bytearray:
    return bytearray((nbits + 7) >> 3)


def bitmap_members(bitmap: bytes, nbits: int) -> List[int]:
    return [m for m in range(nbits) if bitmap_get(bitmap, m)]


# -- target assignment -----------------------------------------------------

def merge_targets(num_partitions: int, live_slots: Sequence[int],
                  my_slot: int, replicas: int
                  ) -> Dict[int, List[Tuple[int, int]]]:
    """``{target_slot: [(p_lo, p_hi), ...]}`` — which peer hosts which
    contiguous partition ranges, for ``replicas`` copies.

    Partition-range assignment over the candidate slots (live, excluding
    the pusher itself so a replica always survives its producer):
    partition ``p``'s primary candidate is ``p * C // P`` and replica
    ``r`` the next candidate round-robin. Deterministic per membership
    snapshot; pushers with briefly divergent views scatter segments over
    MORE targets, which the driver directory absorbs (coverage is
    whatever actually published — assignment needs no global agreement).
    """
    candidates = sorted(s for s in live_slots if s != my_slot)
    if not candidates and live_slots:
        candidates = sorted(live_slots)  # single-executor degenerate case
    if not candidates or replicas <= 0 or num_partitions <= 0:
        return {}
    k = min(replicas, len(candidates))
    out: Dict[int, List[Tuple[int, int]]] = {}
    for r in range(k):
        run_slot = None
        run_lo = 0
        for p in range(num_partitions):
            idx = (p * len(candidates) // num_partitions + r) \
                % len(candidates)
            slot = candidates[idx]
            if slot != run_slot:
                if run_slot is not None:
                    out.setdefault(run_slot, []).append((run_lo, p))
                run_slot, run_lo = slot, p
        if run_slot is not None:
            out.setdefault(run_slot, []).append((run_lo, num_partitions))
    return out


# -- the driver's merged directory ----------------------------------------

_ENTRY_HEAD = struct.Struct("<iiqqIII")  # partition, slot, token, nbytes,
#                                          crc32, ncovered, nranges
_RANGE = struct.Struct("<QI")


class MergedEntry:
    """One finalized merged segment: partition ``partition_id``'s bytes
    from the maps in ``covered``, held by executor ``slot`` as the byte
    ``ranges`` of serving token ``token`` (``crc32`` over their
    concatenation, checked reducer-side)."""

    __slots__ = ("partition_id", "slot", "token", "nbytes", "crc32",
                 "covered", "ranges")

    def __init__(self, partition_id: int, slot: int, token: int,
                 nbytes: int, crc32: int, covered: bytes,
                 ranges: Sequence[Tuple[int, int]]):
        self.partition_id = partition_id
        self.slot = slot
        self.token = token
        self.nbytes = nbytes
        self.crc32 = crc32
        self.covered = bytes(covered)
        self.ranges = tuple((int(o), int(ln)) for o, ln in ranges)

    def covers(self, map_id: int) -> bool:
        return bitmap_get(self.covered, map_id)

    def covered_maps(self, num_maps: int) -> List[int]:
        return bitmap_members(self.covered, num_maps)

    def to_bytes(self) -> bytes:
        head = _ENTRY_HEAD.pack(self.partition_id, self.slot, self.token,
                                self.nbytes, self.crc32,
                                len(self.covered), len(self.ranges))
        return head + self.covered + b"".join(
            _RANGE.pack(o, ln) for o, ln in self.ranges)

    @staticmethod
    def from_bytes(payload: bytes, off: int = 0
                   ) -> Tuple["MergedEntry", int]:
        (partition, slot, token, nbytes, crc, ncov,
         nranges) = _ENTRY_HEAD.unpack_from(payload, off)
        off += _ENTRY_HEAD.size
        covered = payload[off:off + ncov]
        off += ncov
        ranges = []
        for _ in range(nranges):
            o, ln = _RANGE.unpack_from(payload, off)
            ranges.append((o, ln))
            off += _RANGE.size
        return MergedEntry(partition, slot, token, nbytes, crc, covered,
                           ranges), off


class MergedDirectory:
    """Per-shuffle ``partition -> [MergedEntry, ...]`` view.

    Driver-side it is the authoritative aggregation of one-sided
    ``MergedPublishMsg`` applies; reducer-side a decoded, epoch-cached
    snapshot. One entry per (partition, slot): a re-finalize from the
    same slot overwrites (newest token wins, exactly like a repair
    publish overwrites a driver-table entry)."""

    def __init__(self):
        self._parts: Dict[int, Dict[int, MergedEntry]] = {}

    def apply(self, entry: MergedEntry) -> None:
        self._parts.setdefault(entry.partition_id, {})[entry.slot] = entry

    def entries(self, partition: int) -> List[MergedEntry]:
        """Entries for one partition, widest coverage first (slot index
        breaks ties, deterministically)."""
        per = self._parts.get(partition, {})
        return sorted(per.values(),
                      key=lambda e: (-sum(bin(b).count("1")
                                          for b in e.covered), e.slot))

    def partitions(self) -> List[int]:
        return sorted(self._parts)

    def __len__(self) -> int:
        return sum(len(p) for p in self._parts.values())

    def drop_map(self, map_id: int) -> int:
        """Remove entries covering ``map_id`` (a repair publish replaced
        the map's output — deterministic re-execution writes identical
        bytes, but a corrupt-output repair may not, so the directory
        stays conservative). Returns the number dropped."""
        dropped = 0
        for partition in list(self._parts):
            per = self._parts[partition]
            for slot in [s for s, e in per.items() if e.covers(map_id)]:
                del per[slot]
                dropped += 1
            if not per:
                del self._parts[partition]
        return dropped

    def drop_slot(self, slot: int) -> int:
        """Remove entries hosted by a tombstoned executor."""
        dropped = 0
        for partition in list(self._parts):
            per = self._parts[partition]
            if per.pop(slot, None) is not None:
                dropped += 1
            if not per:
                del self._parts[partition]
        return dropped

    def covering_slots(self, map_id: int, partition: int) -> List[int]:
        return [s for s, e in self._parts.get(partition, {}).items()
                if e.covers(map_id)]

    def to_bytes(self) -> bytes:
        entries = [e for p in sorted(self._parts)
                   for _, e in sorted(self._parts[p].items())]
        return struct.pack("<I", len(entries)) + b"".join(
            e.to_bytes() for e in entries)

    @staticmethod
    def from_bytes(payload: bytes) -> "MergedDirectory":
        d = MergedDirectory()
        if not payload:
            return d
        (n,) = struct.unpack_from("<I", payload, 0)
        off = 4
        for _ in range(n):
            entry, off = MergedEntry.from_bytes(payload, off)
            d.apply(entry)
        return d


# -- the merge target ------------------------------------------------------

class _Ledger:
    """One segment's append ledger: (map, fence, offset, length, crc32)
    rows in arrival order. Fence supersession is resolved at finalize:
    for each map the NEWEST fence's row serves, older rows' byte ranges
    are excluded from the finalized range list. ``fd`` is the segment
    file's cached write descriptor (positional pwrites are offset-
    explicit and thread-safe, so one fd serves concurrent pushes);
    opened at first reservation, closed at finalize/drop."""

    __slots__ = ("path", "size", "rows", "fd")

    def __init__(self, path: str):
        self.path = path
        self.size = 0
        self.rows: List[Tuple[int, int, int, int, int]] = []
        self.fd: Optional[int] = None

    def close_fd(self) -> None:
        if self.fd is not None:
            try:
                os.close(self.fd)
            except OSError:
                pass
            self.fd = None

    def newest_fence(self, map_id: int) -> Optional[int]:
        fences = [f for m, f, _, _, _ in self.rows if m == map_id]
        return max(fences) if fences else None

    def final_rows(self) -> List[Tuple[int, int, int, int, int]]:
        newest = {}
        for row in self.rows:
            m, f = row[0], row[1]
            if m not in newest or f >= newest[m][1]:
                newest[m] = row
        return sorted(newest.values(), key=lambda r: r[2])  # offset order


class _ShuffleSegments:
    """One shuffle's state on a merge target."""

    __slots__ = ("ledgers", "num_maps", "finalized", "last_push",
                 "overflow_tokens", "writing", "charged")

    def __init__(self):
        self.ledgers: Dict[int, _Ledger] = {}  # partition -> ledger
        self.num_maps = 0
        self.finalized = False
        self.last_push = time.monotonic()
        self.overflow_tokens: List[int] = []
        self.writing = 0  # reserved-but-unwritten segment appends
        # disk-ledger charges BY TENANT: early pushes can land before
        # the TenantMapMsg teaches this target's resolver (charged to
        # DEFAULT_TENANT), later ones after — the release at drop must
        # repay each ledger exactly what was charged to it, or one
        # tenant retains phantom bytes while another's quota erases
        self.charged: Dict[int, int] = {}


class MergeStore:
    """Executor-side merge target: accepts pushes, owns segment files +
    ledgers, finalizes into the resolver's serving token space.

    Segment files live under ``<spill_dir>/merge/`` so they share the
    executor's storage-health machinery's namespace without colliding
    with the resolver's committed-output naming (``recover()`` ignores
    them; cleanup rides ``drop_shuffle``, driven by unregister/epoch
    death)."""

    def __init__(self, resolver, conf):
        self.resolver = resolver
        self.conf = conf
        self.dir = os.path.join(resolver.spill_dir, "merge")
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._shuffles: Dict[int, _ShuffleSegments] = {}
        # shuffles already dropped here (unregister processed): a push
        # racing the unregister broadcast used to re-create state and
        # charge disk bytes NOTHING would ever release (drop_shuffle had
        # already run; reap_orphans deletes files, not ledger charges) —
        # the modelcheck finalize_vs_push ledger-conserve invariant.
        # Count- and time-bounded (utils/tombstones.py): zombie pushes
        # are bounded by push deadlines, and engine shuffle ids are
        # reused — an expiring marker restores push-merge for the new
        # incarnation even in deployments with no registration push.
        # A push-delivered registration signal re-arms immediately
        # (note_registered: TenantMapMsg / ShardMapMsg / pushed plan).
        from sparkrdma_tpu.utils.tombstones import TombstoneCache
        self._dropped = TombstoneCache(ttl_s=30.0, cap=1024)
        self.max_segment = int(conf.merge_segment_max_bytes)
        self._ovf_seq = 0  # uniquifies overflow blob names (one map
        # attempt may overflow several spills — they must not collide)
        # audit counters
        self.pushes_accepted = 0
        self.pushes_rejected = 0
        self.segments_finalized = 0
        self.reopens = 0  # drain re-pushes that reopened a sealed shuffle

    # -- push side -------------------------------------------------------

    def _segment_path(self, shuffle_id: int, partition: int) -> str:
        return os.path.join(self.dir, f"seg_{shuffle_id}_{partition}.bin")

    def push(self, shuffle_id: int, map_id: int, fence: int,
             start_partition: int, sizes: Sequence[int],
             data: bytes, reopen: bool = False) -> Tuple[int, bytes]:
        """Append one map's blocks for partitions [start, start+len);
        returns ``(status, accepted)`` — one byte per pushed partition.

        ``reopen`` is the graceful-drain path (``PUSH_KIND_DRAIN``): a
        drain re-push may land AFTER this target sealed the shuffle —
        instead of the STATUS_FINALIZED rejection the segment REOPENS
        (the driver re-broadcasts finalize once the drainee's pass
        completes, so the new rows still publish). Ledger fences dedupe
        as always, so re-pushing what background replication already
        delivered appends nothing.

        Disk never happens under the store lock: the lock covers ledger
        bookkeeping only (fence checks, byte-range RESERVATION, row
        append), then each segment writes positionally (``pwrite``) at
        its reserved offset — concurrent pushes to one segment cannot
        interleave bytes, and a push to shuffle A never stalls behind
        shuffle B's disk (the serve pool shares these threads with
        foreground reads)."""
        accepted = bytearray(len(sizes))
        # (ledger, offset, segment view, result index, row) to write
        writes: List[tuple] = []
        segs = []
        pos = 0
        view = memoryview(data)
        for size in sizes:
            segs.append(view[pos:pos + size])
            pos += size
        with self._lock:
            if shuffle_id in self._dropped:
                # the unregister broadcast already dropped this shuffle
                # here: accepting would charge disk bytes no drop will
                # ever release. FINALIZED stops the pusher for good.
                self.pushes_rejected += len(sizes)
                return M.STATUS_FINALIZED, bytes(accepted)
            state = self._shuffles.get(shuffle_id)
            if state is None:
                state = _ShuffleSegments()
                self._shuffles[shuffle_id] = state
            if state.finalized:
                if not reopen:
                    self.pushes_rejected += len(sizes)
                    return M.STATUS_FINALIZED, bytes(accepted)
                state.finalized = False
                self.reopens += 1
            state.last_push = time.monotonic()
            state.num_maps = max(state.num_maps, map_id + 1)
            for i, size in enumerate(sizes):
                p = start_partition + i
                ledger = state.ledgers.get(p)
                if ledger is None:
                    ledger = _Ledger(self._segment_path(shuffle_id, p))
                    state.ledgers[p] = ledger
                newest = ledger.newest_fence(map_id)
                if newest is not None and fence <= newest:
                    self.pushes_rejected += 1
                    continue  # duplicate or stale attempt's push
                if ledger.size + size > self.max_segment:
                    self.pushes_rejected += 1
                    continue  # segment full: this map stays per-map here
                # tenancy: merged-segment disk charges the OWNING tenant
                # (resolver.disk_ledger); past its spill quota the push
                # is shed exactly like a full segment — the map stays
                # per-map-fetched, nothing breaks
                tenant = self.resolver.tenant_of(shuffle_id)
                try:
                    # analysis: leak-ok(accepted rows transfer to state.charged; drop_shuffle repays per tenant)
                    self.resolver.disk_ledger.charge(tenant, size)
                except Exception:
                    self.pushes_rejected += 1
                    continue
                state.charged[tenant] = state.charged.get(tenant, 0) + size
                if ledger.fd is None:
                    try:
                        ledger.fd = os.open(
                            ledger.path, os.O_WRONLY | os.O_CREAT, 0o644)
                    except OSError as e:
                        log.warning("merge segment open %s failed: %s",
                                    ledger.path, e)
                        self.pushes_rejected += 1
                        # un-charge: no bytes will land for this push
                        state.charged[tenant] -= size
                        self.resolver.disk_ledger.release(tenant, size)
                        continue
                row = (map_id, fence, ledger.size, size,
                       zlib.crc32(segs[i]))
                ledger.rows.append(row)
                ledger.size += size
                writes.append((ledger, row[2], segs[i], i, row, tenant))
            state.writing += len(writes)
        ok = 0
        for ledger, off, seg, i, row, row_tenant in writes:
            try:
                os.pwrite(ledger.fd, seg, off)
                accepted[i] = 1
                ok += 1
            except OSError as e:
                log.warning("merge push append to %s failed: %s",
                            ledger.path, e)
                with self._lock:
                    # un-reserve: a row without bytes must never reach a
                    # finalized range list (the hole it leaves in the
                    # file is excluded with it)
                    try:
                        ledger.rows.remove(row)
                    except ValueError:
                        pass
                    self.pushes_rejected += 1
                    state.charged[row_tenant] = \
                        state.charged.get(row_tenant, 0) - row[3]
                self.resolver.disk_ledger.release(row_tenant, row[3])
        with self._lock:
            self.pushes_accepted += ok
            state.writing -= len(writes)
        return M.STATUS_OK, bytes(accepted)

    def push_overflow(self, shuffle_id: int, map_id: int, fence: int,
                      data: bytes) -> Tuple[int, int]:
        """Store one spill-overflow blob; returns (status, serving
        token). The blob is registered with the resolver so the writer
        fetches it back over the ordinary block dataplane."""
        with self._lock:
            if shuffle_id in self._dropped:
                return M.STATUS_FINALIZED, 0  # unregistered: no parking
            seq = self._ovf_seq
            self._ovf_seq += 1
        # tenancy: overflow blobs are disk the owning tenant parks here
        tenant = self.resolver.tenant_of(shuffle_id)
        try:
            # analysis: leak-ok(stored blobs transfer to state.charged; drop_shuffle repays per tenant)
            self.resolver.disk_ledger.charge(tenant, len(data))
        except Exception:
            return M.STATUS_ERROR, 0
        path = os.path.join(
            self.dir, f"ovf_{shuffle_id}_{map_id}_{fence}.{seq}.bin")
        try:
            with open(path, "wb") as f:
                f.write(data)
            token = self.resolver.register_external(shuffle_id, path,
                                                    len(data))
        except OSError as e:
            log.warning("overflow blob store failed: %s", e)
            self.resolver.disk_ledger.release(tenant, len(data))
            return M.STATUS_ERROR, 0
        with self._lock:
            if shuffle_id in self._dropped:
                # the unregister broadcast landed in the window between
                # the entry check and here (disk + registration happen
                # OUTSIDE the lock): unwind everything this call did —
                # recording the charge in a re-created state would park
                # bytes no drop will ever repay (push() is immune: its
                # check, charge, and record share one lock block)
                unwind = True
            else:
                unwind = False
                state = self._shuffles.get(shuffle_id)
                if state is None:
                    state = _ShuffleSegments()
                    self._shuffles[shuffle_id] = state
                state.overflow_tokens.append(token)
                state.charged[tenant] = state.charged.get(tenant, 0) \
                    + len(data)
        if unwind:
            self.resolver.disk_ledger.release(tenant, len(data))
            # the dropped shuffle's other externals are already gone;
            # this releases (and deletes) only the blob just parked
            self.resolver.release_externals(shuffle_id)
            return M.STATUS_FINALIZED, 0
        return M.STATUS_OK, token

    def hosted_shuffles(self) -> List[int]:
        """Shuffle ids with at least one non-empty ledger here — the
        cheap metadata pass a drain uses to prefetch directories before
        streaming :meth:`export_rows` (which reads file payloads and
        must stay lazy)."""
        with self._lock:
            return sorted(sid for sid, state in self._shuffles.items()
                          if any(ledger.rows
                                 for ledger in state.ledgers.values()))

    def export_rows(self):
        """Yield every surviving ledger row as ``(shuffle_id, partition,
        map_id, fence, bytes)`` — the graceful-drain HANDOFF source: a
        retiring target re-pushes the rows it hosts for other
        executors' maps to surviving peers, so replicas this fleet
        already paid for don't silently die with the slot. Fence
        supersession is resolved (``final_rows``), bookkeeping is
        snapshotted under the lock, file reads happen outside it."""
        with self._lock:
            items = [(sid, p, ledger.path, ledger.final_rows())
                     for sid, state in self._shuffles.items()
                     for p, ledger in state.ledgers.items()]
        for sid, partition, path, rows in sorted(
                items, key=lambda it: (it[0], it[1])):
            if not rows:
                continue
            try:
                f = open(path, "rb")
            except OSError as e:
                log.warning("drain export of %s failed: %s", path, e)
                continue
            with f:
                for map_id, fence, off, ln, _crc in rows:
                    try:
                        f.seek(off)
                        data = f.read(ln)
                    except OSError:
                        continue
                    if len(data) == ln:
                        yield sid, partition, map_id, fence, data

    # -- finalize --------------------------------------------------------

    def idle_for(self, shuffle_id: int) -> float:
        with self._lock:
            state = self._shuffles.get(shuffle_id)
            return (time.monotonic() - state.last_push
                    if state is not None else float("inf"))

    def finalize(self, shuffle_id: int, exec_index: int,
                 publish: Callable[[M.MergedPublishMsg], None],
                 tracer=None) -> int:
        """Seal every segment of the shuffle: resolve fence supersession
        into the final range list, CRC the surviving bytes, register the
        file for serving, and publish one :class:`MergedPublishMsg` per
        partition. Idempotent — a second finalize is a no-op."""
        with self._lock:
            state = self._shuffles.get(shuffle_id)
            if state is None:
                # the broadcast beat every push to this target: leave a
                # FINALIZED tombstone so later pushes answer
                # STATUS_FINALIZED (the pusher stops) instead of being
                # accepted into segments nothing will ever seal
                state = _ShuffleSegments()
                state.finalized = True
                self._shuffles[shuffle_id] = state
                return 0
            if state.finalized:
                return 0
            state.finalized = True
        # reserved rows whose pwrite is still in flight must land before
        # the seal reads the file, or the published CRC would cover a
        # hole (harmless — the reducer's CRC check degrades it — but a
        # needless coverage loss); new pushes are already barred
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with self._lock:
                if state.writing == 0:
                    break
            time.sleep(0.005)
        with self._lock:
            ledgers = dict(state.ledgers)
            num_maps = state.num_maps
        published = 0
        for partition, ledger in sorted(ledgers.items()):
            ledger.close_fd()  # writes quiesced above; seal the file
            rows = ledger.final_rows()
            if not rows:
                continue
            covered = bitmap_new(num_maps)
            ranges: List[Tuple[int, int]] = []
            range_crcs: List[int] = []  # one per coalesced range
            crc = 0
            try:
                with open(ledger.path, "rb") as f:
                    for m, _fence, off, ln, _row_crc in rows:
                        bitmap_set(covered, m)
                        f.seek(off)
                        seg = f.read(ln)
                        crc = zlib.crc32(seg, crc)
                        if ranges and ranges[-1][0] + ranges[-1][1] == off:
                            ranges[-1] = (ranges[-1][0],
                                          ranges[-1][1] + ln)
                            range_crcs[-1] = zlib.crc32(seg, range_crcs[-1])
                        else:
                            ranges.append((off, ln))
                            range_crcs.append(zlib.crc32(seg))
                # the reducer's merged read requests EXACTLY these
                # coalesced ranges, so attesting them here lets the
                # serving side reuse the CRCs (zero-copy with trailers
                # on) instead of re-hashing the segment every serve
                token = self.resolver.register_external(
                    shuffle_id, ledger.path, ledger.size,
                    crc_ranges=[(o, ln, c) for (o, ln), c
                                in zip(ranges, range_crcs)])
            except OSError as e:
                log.warning("finalize of %s failed: %s", ledger.path, e)
                continue
            nbytes = sum(ln for _, ln in ranges)
            try:
                publish(M.MergedPublishMsg(shuffle_id, partition,
                                           exec_index, token, nbytes, crc,
                                           bytes(covered), ranges))
            except TransportError as e:
                # one-sided like every publish: a lost one costs coverage
                log.debug("merged publish for shuffle %d partition %d "
                          "lost: %s", shuffle_id, partition, e)
            published += 1
            if tracer is not None:
                tracer.instant("merge.finalize", "merge",
                               shuffle=shuffle_id, partition=partition,
                               maps=len(rows), bytes=nbytes)
        with self._lock:
            self.segments_finalized += published
        return published

    # -- lifecycle -------------------------------------------------------

    def note_registered(self, shuffle_id: int) -> None:
        """Re-arm a dropped id: the driver's registration pushes
        (TenantMapMsg, ShardMapMsg, a pushed ReducePlanMsg) ride the
        same broadcast channel as the unregister that dropped it, so
        their arrival is authoritative evidence the id was reused for
        a NEW shuffle."""
        with self._lock:
            self._dropped.discard(shuffle_id)

    def drop_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            state = self._shuffles.pop(shuffle_id, None)
            self._dropped.add(shuffle_id)
        if state is None:
            return
        for tenant, nbytes in state.charged.items():
            if nbytes > 0:
                self.resolver.disk_ledger.release(tenant, nbytes)
        for ledger in state.ledgers.values():
            ledger.close_fd()
            try:
                os.unlink(ledger.path)
            except OSError:
                pass
        # finalized segments + overflow blobs were registered with the
        # resolver; external release unregisters serving and deletes
        self.resolver.release_externals(shuffle_id)

    def reap_orphans(self, live_shuffle_ids, min_age_s: float = 60.0
                     ) -> int:
        """GC sweep of ``<spill>/merge/``: delete segment files and
        overflow blobs whose shuffle is neither registered at the driver
        (``live_shuffle_ids``) nor known to this store — leftovers of a
        crashed process no unregister push will ever name. ``min_age_s``
        guards the snapshot race (a push landing for a shuffle
        registered after the live set was taken); only files older than
        it are eligible. Returns the number of files reaped."""
        import re
        live = set(int(s) for s in live_shuffle_ids)
        with self._lock:
            local = set(self._shuffles)
        pat = re.compile(r"^(?:seg|ovf)_(\d+)_")
        cutoff = time.time() - min_age_s
        reaped = 0
        try:
            names = os.listdir(self.dir)
        except OSError:
            return 0
        for name in names:
            m = pat.match(name)
            if m is None or int(m.group(1)) in live \
                    or int(m.group(1)) in local:
                continue
            path = os.path.join(self.dir, name)
            try:
                if os.stat(path).st_mtime > cutoff:
                    continue  # too fresh: may be a racing push
                os.unlink(path)
                reaped += 1
            except OSError:
                pass
        return reaped

    def stop(self) -> None:
        with self._lock:
            sids = list(self._shuffles)
        for sid in sids:
            self.drop_shuffle(sid)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "shuffles": len(self._shuffles),
                "pushes_accepted": self.pushes_accepted,
                "pushes_rejected": self.pushes_rejected,
                "segments_finalized": self.segments_finalized,
            }


# -- the pusher ------------------------------------------------------------

class _PushTask:
    __slots__ = ("shuffle_id", "map_id", "fence", "partition_lengths",
                 "num_partitions", "submitted", "planned_only")

    def __init__(self, shuffle_id: int, map_id: int, fence: int,
                 partition_lengths: Sequence[int],
                 planned_only: bool = False):
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.fence = fence
        self.partition_lengths = [int(n) for n in partition_lengths]
        self.num_partitions = len(self.partition_lengths)
        self.submitted = time.monotonic()
        # replay entries (a plan landed after the map committed, or a
        # re-plan re-routed it) redo ONLY the planned push — the merge
        # push already happened at commit time
        self.planned_only = planned_only


class SegmentPusher:
    """The bounded background pusher: one worker drains a queue of
    committed maps, reading each map's partition-range bytes out of the
    LOCAL resolver (serve-path reads, so at-rest spot checks apply — a
    rotted local file is never replicated) staged through a
    :class:`BufferPool` lease (foreground writes hold pool priority: an
    exhausted pool makes the PUSHER wait, bounded, then degrade to an
    unleased copy), and sending one ``PushBlocksReq`` per (target,
    partition-range). Queue entries are descriptors, not bytes — memory
    is bounded by one staged range at a time."""

    def __init__(self, endpoint, resolver, conf, pool=None, tracer=None,
                 pushed_store=None):
        from sparkrdma_tpu.utils import trace as trace_mod
        self.endpoint = endpoint
        self.resolver = resolver
        self.conf = conf
        self.pool = pool
        self.tracer = tracer or trace_mod.NULL
        # the LOCAL PushedInputStore: a planned range whose destination
        # is this executor lands directly (no RPC, no wire copy)
        self.pushed_store = pushed_store
        self._q: "queue.Queue[Optional[_PushTask]]" = queue.Queue()
        self._idle = threading.Condition()
        self._inflight = 0
        self._stopped = False
        self._worker: Optional[threading.Thread] = None
        # planned push: submitted maps logged per shuffle so a plan that
        # lands (or re-plans) AFTER the commit replays them against the
        # fresh placements; (sid, map) -> plan epoch already pushed at,
        # so the eager path and the replay never double-push one epoch
        self._planned_log: Dict[int, List[Tuple[int, int, List[int]]]] = {}
        self._planned_done: Dict[Tuple[int, int], int] = {}
        # native raw-frame sender (csrc/fetchclient.cpp, fc_submit_raw):
        # planned-push frames batch per doorbell on persistent raw-mode
        # connections — created lazily ON the worker thread (one engine
        # per thread), torn down when the worker exits
        self._push_engine = None
        self._push_conns: Dict[int, int] = {}  # slot -> conn id
        self._push_req_id = 0
        # audit counters
        self.pushes_sent = 0
        self.push_bytes = 0
        self.pushes_dropped = 0
        self.push_failures = 0
        self.planned_sent = 0
        self.planned_bytes = 0
        self.planned_local = 0
        self.planned_failures = 0
        self.planned_native = 0  # planned sends carried by the raw engine

    def _planned_on(self) -> bool:
        # planned routing needs a ReducePlan, which needs adaptive_plan
        return bool(self.conf.planned_push) and bool(self.conf.adaptive_plan)

    def _merge_on(self) -> bool:
        return bool(self.conf.push_merge) \
            and int(self.conf.merge_replicas) > 0

    def submit(self, shuffle_id: int, map_id: int, fence: int,
               partition_lengths: Sequence[int]) -> None:
        if not self._merge_on() and not self._planned_on():
            return
        task = _PushTask(shuffle_id, map_id, fence, partition_lengths)
        with self._idle:
            if self._stopped:
                return
            if self._planned_on():
                self._planned_log.setdefault(shuffle_id, []).append(
                    (map_id, fence, task.partition_lengths))
            self._inflight += 1
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._run, daemon=True, name="merge-pusher")
                self._worker.start()
        self._q.put(task)

    def on_plan(self, shuffle_id: int) -> None:
        """A ReducePlan landed for ``shuffle_id`` (initial publish or
        re-plan): replay every committed map's PLANNED push against the
        fresh placements. Replay entries carry a fresh deadline clock —
        the plan's arrival, not the original commit, started their
        usefulness window — and the per-epoch dedupe in
        :meth:`_push_planned` makes an already-eager-pushed epoch a
        no-op."""
        if not self._planned_on():
            return
        with self._idle:
            if self._stopped:
                return
            entries = list(self._planned_log.get(shuffle_id, ()))
            if not entries:
                return
            self._inflight += len(entries)
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._run, daemon=True, name="merge-pusher")
                self._worker.start()
        for map_id, fence, lengths in entries:
            self._q.put(_PushTask(shuffle_id, map_id, fence, lengths,
                                  planned_only=True))

    def forget(self, shuffle_id: int) -> None:
        """Drop the shuffle's replay log (unregister / epoch death)."""
        with self._idle:
            self._planned_log.pop(shuffle_id, None)
            for key in [k for k in self._planned_done
                        if k[0] == shuffle_id]:
                del self._planned_done[key]

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until every submitted push has been sent or dropped
        (test/bench determinism hook). True = drained."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(timeout=min(0.05, remaining))
        return True

    def stop(self) -> None:
        with self._idle:
            self._stopped = True
        self._q.put(None)

    def _run(self) -> None:
        try:
            while True:
                task = self._q.get()
                if task is None:
                    return
                try:
                    self._push_map(task)
                except Exception:  # noqa: BLE001 — a push must never
                    # kill the worker; the map stays per-map-fetched
                    self.push_failures += 1
                    log.exception("push of shuffle %d map %d failed",
                                  task.shuffle_id, task.map_id)
                finally:
                    with self._idle:
                        self._inflight -= 1
                        self._idle.notify_all()
        finally:
            self._close_push_engine()

    def _targets(self, task: _PushTask) -> Dict[int, List[Tuple[int, int]]]:
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        members = self.endpoint.members()
        # live AND not draining: a slot the membership plane marked
        # DRAINING is about to leave — replicas parked there would need
        # an immediate handoff, so stop choosing it now (the drainee
        # itself is excluded by my_slot as always). Pre-elastic drivers
        # never push states, so slot_draining is uniformly False.
        draining = getattr(self.endpoint, "slot_draining", None)
        live = [i for i, m in enumerate(members) if m != TOMBSTONE
                and not (draining is not None and draining(i))]
        try:
            my = self.endpoint.exec_index()
        except KeyError:
            my = -1
        return merge_targets(task.num_partitions, live, my,
                             int(self.conf.merge_replicas))

    def _stage(self, nbytes: int, tenant: int = 0):
        """A staging lease for one partition-range: pool-leased when the
        pool admits it within a short bounded wait (foreground writers
        win contention), else a plain buffer — the pusher degrades,
        never blocks the write path. A tenant over its lease quota
        degrades the same way (the push still happens, unleased)."""
        if self.pool is None or nbytes == 0:
            return None
        from sparkrdma_tpu.shuffle.tenancy import TenantQuotaError
        for _ in range(3):
            try:
                return self.pool.get(nbytes, tenant=tenant)
            except TenantQuotaError:
                return None
            except MemoryError:
                time.sleep(0.005)
        return None

    def _push_map(self, task: _PushTask) -> None:
        if self._planned_on():
            self._push_planned(task)
        if task.planned_only or not self._merge_on():
            return
        deadline_s = self.conf.push_deadline_ms / 1000
        targets = self._targets(task)
        for slot, p_ranges in sorted(targets.items()):
            for lo, hi in p_ranges:
                if time.monotonic() - task.submitted > deadline_s:
                    self.pushes_dropped += 1
                    self.tracer.instant("push.drop", "merge",
                                        shuffle=task.shuffle_id,
                                        map=task.map_id, target=slot)
                    return
                # NOTE: all-empty ranges still push — the ledger must
                # record the map as covered even where it wrote nothing,
                # or coverage checks would treat empty maps as stragglers
                sizes = task.partition_lengths[lo:hi]
                try:
                    data = self.resolver.local_blocks(
                        task.shuffle_id, task.map_id, lo, hi)
                except Exception as e:  # noqa: BLE001 — corrupt/EIO
                    # local outputs must not replicate rot; the map
                    # stays per-map-fetched and the serve path's own
                    # verdict machinery owns the escalation
                    self.push_failures += 1
                    log.warning("push read of shuffle %d map %d [%d,%d) "
                                "failed: %s", task.shuffle_id,
                                task.map_id, lo, hi, e)
                    return
                if data is None:
                    return  # output gone (unregistered/superseded)
                # the lease is a pure BACKPRESSURE token: it charges the
                # push's in-flight bytes against the pool gauge (so the
                # pusher waits when foreground writers hold the pool)
                # without copying — `data` itself rides the wire
                lease = self._stage(len(data),
                                    tenant=self.resolver.tenant_of(
                                        task.shuffle_id))
                try:
                    ok = self._send(slot, task, lo, sizes, data)
                finally:
                    if lease is not None:
                        lease.free()
                if not ok:
                    break  # next replica target still gets its copy

    def _send(self, slot: int, task: _PushTask, lo: int,
              sizes: List[int], data: bytes) -> bool:
        try:
            peer = self.endpoint.member_at(slot)
        except Exception:  # noqa: BLE001 — tombstoned mid-push
            return False
        try:
            with self.tracer.span("push.map", "merge",
                                  shuffle=task.shuffle_id,
                                  map=task.map_id, target=slot,
                                  bytes=len(data)):
                resp = self.endpoint.push_blocks(
                    peer, task.shuffle_id, task.map_id, task.fence,
                    M.PUSH_KIND_MERGE, lo, sizes, data)
        except (TransportError, TimeoutError) as e:
            self.push_failures += 1
            log.debug("push to slot %d failed: %s", slot, e)
            return False
        if resp.status == M.STATUS_FINALIZED:
            return False
        self.pushes_sent += 1
        self.push_bytes += len(data)
        return True

    # -- planned push (shuffle/pushed_store.py receive path) -------------

    def _push_planned(self, task: _PushTask) -> None:
        """Push this committed map's bytes to the PLANNED reducer slot
        of every plan task whose map range covers it (split tasks
        included — their map slices tile the map space). Cache-only plan
        resolution: no plan yet means no push now — :meth:`on_plan`
        replays this map when the broadcast lands. One epoch pushes at
        most once per map (the receive-side fence dedupe backstops the
        race between the eager path and a replay)."""
        plane = getattr(self.endpoint, "location_plane", None)
        plan = plane.plan(task.shuffle_id) if plane is not None else None
        if plan is None:
            return
        done_key = (task.shuffle_id, task.map_id)
        with self._idle:
            if self._planned_done.get(done_key, 0) >= plan.plan_epoch:
                return
            self._planned_done[done_key] = plan.plan_epoch
        try:
            my = self.endpoint.exec_index()
        except Exception:  # noqa: BLE001 — not yet joined
            my = -1
        deadline_s = self.conf.push_deadline_ms / 1000
        # remote sends collect here and go out as ONE doorbell batch on
        # the native raw engine (falling back per-send to the Python
        # RPC); leases stay staged until the batch settles
        sends: List[tuple] = []
        for t in plan.tasks:
            if t.placement < 0:
                continue  # no planned destination: stays pull-fetched
            if not (t.map_start <= task.map_id < t.map_end):
                continue  # a split sibling owns this map's slice
            if time.monotonic() - task.submitted > deadline_s:
                self.pushes_dropped += 1
                self.tracer.instant("push.drop", "merge",
                                    shuffle=task.shuffle_id,
                                    map=task.map_id, target=t.placement)
                break  # already-collected sends still go out
            lo, hi = t.start_partition, t.end_partition
            sizes = task.partition_lengths[lo:hi]
            try:
                data = self.resolver.local_blocks(
                    task.shuffle_id, task.map_id, lo, hi)
            except Exception as e:  # noqa: BLE001 — corrupt/EIO: local
                # rot must not replicate; the range stays pull-fetched
                self.planned_failures += 1
                log.warning("planned push read of shuffle %d map %d "
                            "[%d,%d) failed: %s", task.shuffle_id,
                            task.map_id, lo, hi, e)
                break
            if data is None:
                break  # output gone (unregistered/superseded)
            if t.placement == my:
                # destination is THIS executor: land directly in the
                # local store — zero RPCs, zero wire copies
                if self.pushed_store is not None:
                    self.pushed_store.push(
                        task.shuffle_id, task.map_id, task.fence,
                        plan.plan_epoch, lo, sizes, data)
                    self.planned_local += 1
                continue
            lease = self._stage(len(data),
                                tenant=self.resolver.tenant_of(
                                    task.shuffle_id))
            sends.append((t.placement, task, plan.plan_epoch, lo, sizes,
                          data, lease))
        self._send_planned_batch(sends)

    def _send_planned(self, slot: int, task: _PushTask, plan_epoch: int,
                      lo: int, sizes: List[int], data: bytes) -> bool:
        try:
            peer = self.endpoint.member_at(slot)
        except Exception:  # noqa: BLE001 — tombstoned mid-push: the
            # range stays a hole the reducer pull-fills
            return False
        try:
            with self.tracer.span("push.planned", "push",
                                  shuffle=task.shuffle_id,
                                  map=task.map_id, target=slot,
                                  epoch=plan_epoch, bytes=len(data)):
                resp = self.endpoint.push_planned(
                    peer, task.shuffle_id, task.map_id, task.fence,
                    plan_epoch, lo, sizes, data)
        except (TransportError, TimeoutError) as e:
            self.planned_failures += 1
            log.debug("planned push to slot %d failed: %s", slot, e)
            return False
        if resp.status != M.STATUS_OK:
            return False
        self.planned_sent += 1
        self.planned_bytes += len(data)
        return True

    # -- native raw-frame sender (shared fc engine, fc_submit_raw) -------

    def _native_push_engine(self):
        """The worker thread's raw-frame engine, created lazily; None
        when the native client isn't built or the wire isn't plain
        (compression/codec transform frames the C side won't)."""
        if self._push_engine is not None:
            return self._push_engine
        if (not self.conf.native_fetch or self.conf.wire_compress
                or getattr(self.endpoint, "_codec", None) is not None):
            return None
        from sparkrdma_tpu.shuffle.native_fetch import NativeFetchEngine
        if not NativeFetchEngine.available():
            return None
        try:
            self._push_engine = NativeFetchEngine()
        except RuntimeError:
            return None
        return self._push_engine

    def _close_push_engine(self) -> None:
        eng, self._push_engine = self._push_engine, None
        self._push_conns.clear()
        if eng is not None:
            eng.close()

    def _native_push_conn(self, eng, slot: int) -> int:
        """A cached raw-mode connection to ``slot``'s control port (the
        Python server speaks the same frames; replies are FIFO per
        connection). 0 = unreachable."""
        conn = self._push_conns.get(slot)
        if conn and eng.alive(conn):
            return conn
        self._push_conns.pop(slot, None)
        try:
            peer = self.endpoint.member_at(slot)
        except Exception:  # noqa: BLE001 — tombstoned mid-push
            return 0
        conn = eng.connect(peer.rpc_host, peer.rpc_port, raw=True,
                           timeout_ms=self.conf.connect_timeout_ms)
        if conn:
            self._push_conns[slot] = conn
        return conn

    def _send_planned_batch(self, sends: List[tuple]) -> None:
        """Send one map's collected planned pushes: doorbell-batched
        raw frames on the native engine where possible, the per-send
        Python RPC for the rest. Any native anomaly (dead connection,
        undecodable reply, deadline) re-sends that item over the Python
        path — the receive-side fence/epoch dedupe makes the replay
        idempotent. Staging leases free only after the batch settles."""
        try:
            eng = self._native_push_engine() if sends else None
            fallback = []
            if eng is None:
                fallback = sends
            else:
                pending = {}  # req_id -> (item, resp_buf)
                batch = max(1, self.conf.fetch_doorbell_batch)
                unsent = 0
                for item in sends:
                    slot, task, plan_epoch, lo, sizes, data, _lease = item
                    conn = self._native_push_conn(eng, slot)
                    if not conn:
                        fallback.append(item)
                        continue
                    self._push_req_id += 1
                    req = M.PushPlannedReq(self._push_req_id,
                                           task.shuffle_id, task.map_id,
                                           task.fence, plan_epoch, lo,
                                           list(sizes), data)
                    # reply: _QI head + one verdict byte per partition
                    resp_buf = bytearray(64 + len(sizes))
                    if eng.submit_raw(conn, req.req_id, req.encode(),
                                      resp_buf) != 0:
                        fallback.append(item)
                        continue
                    pending[req.req_id] = (item, resp_buf)
                    unsent += 1
                    if unsent >= batch:
                        eng.flush()
                        unsent = 0
                if unsent:
                    eng.flush()
                deadline = (time.monotonic()
                            + self.conf.resolved_request_deadline_s())
                while pending and time.monotonic() < deadline:
                    for c in eng.poll(50):
                        ent = pending.pop(c.req_id, None)
                        if ent is None:
                            continue
                        item, buf = ent
                        if not self._settle_native_push(item, c, buf):
                            fallback.append(item)
                if pending:
                    # server stalled under the batch: redial next map,
                    # replay these over the Python path
                    self._close_push_engine()
                    fallback.extend(item for item, _ in pending.values())
            for item in fallback:
                slot, task, plan_epoch, lo, sizes, data, _lease = item
                self._send_planned(slot, task, plan_epoch, lo, sizes,
                                   data)
        finally:
            for *_rest, lease in sends:
                if lease is not None:
                    lease.free()

    def _settle_native_push(self, item: tuple, comp, buf: bytearray
                            ) -> bool:
        """One raw completion -> the Python sender's verdict accounting.
        False = replay this item over the Python RPC."""
        from sparkrdma_tpu.shuffle.native_fetch import NativeFetchEngine
        slot, task, plan_epoch, _lo, _sizes, data, _lease = item
        if comp.status != 0 or comp.nbytes <= 0:
            return False  # connection died under the request
        try:
            resp = NativeFetchEngine.decode_reply(
                comp.frame_type, bytes(buf[:comp.nbytes]))
        except Exception:  # noqa: BLE001 — undecodable reply
            return False
        if not isinstance(resp, M.PushPlannedResp):
            return False
        self.planned_native += 1
        self.tracer.instant("push.planned_native", "push",
                            shuffle=task.shuffle_id, map=task.map_id,
                            target=slot, epoch=plan_epoch,
                            bytes=len(data))
        if resp.status != M.STATUS_OK:
            return True  # an authoritative rejection, not a failure
        self.planned_sent += 1
        self.planned_bytes += len(data)
        return True


def wait_for_coverage(driver_endpoint, shuffle_id: int, num_maps: int,
                      num_partitions: int, timeout: float = 10.0) -> bool:
    """Poll the driver's merged directory until every (map, partition)
    is covered by some entry (tests/benches need a deterministic point
    past the asynchronous push+finalize pipeline). True = full
    coverage."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        directory = driver_endpoint.merged_directory(shuffle_id)
        if directory is not None:
            full = all(
                set(range(num_maps)) == set().union(
                    set(), *[set(e.covered_maps(num_maps))
                             for e in directory.entries(p)])
                for p in range(num_partitions))
            if full:
                return True
        time.sleep(0.02)
    return False


# -- writer-side overflow client ------------------------------------------

class RemoteSpillHandle:
    """One spill-overflow blob parked on a merge peer: fetched back at
    merge time over the ordinary data plane."""

    __slots__ = ("endpoint", "peer", "shuffle_id", "token", "size")

    def __init__(self, endpoint, peer, shuffle_id: int, token: int,
                 size: int):
        self.endpoint = endpoint
        self.peer = peer
        self.shuffle_id = shuffle_id
        self.token = token
        self.size = size

    def fetch(self) -> bytes:
        return self.endpoint.fetch_blocks(
            self.peer, self.shuffle_id, [(self.token, 0, self.size)])


class MergeClient:
    """The writer-facing half of push-merge on one executor: overflow
    spills to a merge peer when local disks are exhausted. Installed by
    the manager as the writer's ``overflow_spill`` hook."""

    def __init__(self, endpoint, conf):
        self.endpoint = endpoint
        self.conf = conf
        self.overflow_spills = 0  # audit

    def overflow_spill(self, shuffle_id: int, map_id: int, fence: int,
                       data: bytes) -> Optional[RemoteSpillHandle]:
        """Park one rendered spill on a live peer; None = no peer could
        take it (the caller falls back to failing the attempt)."""
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        members = self.endpoint.members()
        try:
            my = self.endpoint.exec_index()
        except KeyError:
            my = -1
        draining = getattr(self.endpoint, "slot_draining", None)
        candidates = [i for i, m in enumerate(members)
                      if m != TOMBSTONE and i != my
                      and not (draining is not None and draining(i))]
        for slot in candidates:
            try:
                peer = self.endpoint.member_at(slot)
                resp = self.endpoint.push_blocks(
                    peer, shuffle_id, map_id, fence, M.PUSH_KIND_OVERFLOW,
                    0, [len(data)], data)
            except (TransportError, TimeoutError) as e:
                log.debug("overflow push to slot %d failed: %s", slot, e)
                continue
            if resp.status == M.STATUS_OK:
                self.overflow_spills += 1
                return RemoteSpillHandle(self.endpoint, peer, shuffle_id,
                                         resp.token, len(data))
        return None
