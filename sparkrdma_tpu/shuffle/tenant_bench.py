"""Multi-tenant isolation + sustained-traffic bench harness.

Two measurements back the tenancy tentpole (ROADMAP item 1):

* :func:`run_isolation_microbench` — the fairness A/B the
  ``tenant_isolation_speedup`` secondary and the tier-1 acceptance gate
  share. An ANTAGONIST tenant saturates one executor's serve path with
  a sustained backlog of wide fan-in reads while a VICTIM tenant issues
  small latency-sensitive fetches; the victim's per-read p99 is
  measured under FIFO serving vs deficit-round-robin fair share, same
  process, same data, byte-identical results. On CPU loopback the
  per-request service time is invisible, so — the fetch_bench/
  merge_bench precedent — a serve-side delay shim charges each
  dispatched request a deterministic cost proportional to its bytes
  (the stand-in for the disk/NIC time a real server pays). Fairness
  changes ONLY dispatch order, so the shim prices exactly what DRR
  schedules.

* :func:`run_sustained_bench` — the "millions of users" harness the
  repo lacked: N tenants submit terasort-, pagerank-, and join-shaped
  jobs at a target arrival rate through the admission-controlled
  driver for a fixed duration. Reported as aggregate rows/s and
  per-tenant job p99, with every completed job verified byte-identical
  to its own input, admission accounting closed (accepted + rejected
  == submitted), and ZERO cross-tenant cache evictions.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle import dist_cache
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.tenancy import AdmissionRejected

VICTIM, ANTAGONIST = 1, 2


def _canon_rows(keys: np.ndarray, payload: np.ndarray) -> np.ndarray:
    rows = np.concatenate(
        [keys[:, None].view(np.uint8).reshape(len(keys), 8),
         payload.reshape(len(keys), -1)], axis=1)
    return rows[np.lexsort(rows.T[::-1])]


def _write(driver, owner, sid, tenant, num_maps, rows, payload_w, parts,
           seed):
    handle = driver.register_shuffle(sid, num_maps, parts,
                                     PartitionerSpec("modulo"),
                                     row_payload_bytes=payload_w,
                                     tenant=tenant)
    rng = np.random.default_rng(seed)
    for m in range(num_maps):
        w = owner.get_writer(handle, m)
        w.write_batch(rng.integers(0, 1 << 32, rows).astype(np.uint64),
                      rng.integers(0, 255, (rows, payload_w)
                                   ).astype(np.uint8))
        w.close()
    return handle


def run_isolation_microbench(spill_root: str,
                             victim_reads: int = 30,
                             victim_maps: int = 2,
                             victim_rows: int = 256,
                             antag_maps: int = 16,
                             antag_rows: int = 8192,
                             antag_threads: int = 3,
                             serve_delay_s_per_kb: float = 8e-5,
                             seed: int = 0) -> Dict:
    """Victim-tenant p99 under an antagonist: FIFO vs fair share.

    Returns::

        {"p99_ms": {"fifo": x, "fair": x}, "speedup": fifo/fair,
         "mean_ms": {...}, "identical": bool, "solo_identical": bool,
         "cross_tenant_evictions": 0, "fair_served": {tenant: n},
         "antag_reads": {"fifo": n, "fair": n}, ...}
    """
    conf_kw = dict(connect_timeout_ms=20000, use_cpp_runtime=False,
                   pre_warm_connections=True, serve_threads=1,
                   shuffle_read_block_size="64k",
                   max_vectored_bytes="64k", read_ahead_depth=8,
                   fair_share_serving=True,
                   fair_share_quantum_bytes="64k")
    driver = TpuShuffleManager(TpuShuffleConf(**conf_kw), is_driver=True)
    server = TpuShuffleManager(
        TpuShuffleConf(**conf_kw), driver_addr=driver.driver_addr,
        executor_id="srv", spill_dir=os.path.join(spill_root, "tsrv"))
    client = TpuShuffleManager(
        TpuShuffleConf(**conf_kw), driver_addr=driver.driver_addr,
        executor_id="cli", spill_dir=os.path.join(spill_root, "tcli"))
    try:
        for ex in (server, client):
            ex.executor.wait_for_members(2)
        payload_w = 8
        h_victim = _write(driver, server, 1, VICTIM, victim_maps,
                          victim_rows, payload_w, 4, seed)
        h_antag = _write(driver, server, 2, ANTAGONIST, antag_maps,
                         antag_rows, payload_w, 4, seed + 1)

        # serve-cost shim on the SERVING executor: every dispatched data
        # request pays its byte-proportional service time. Installed on
        # _serve_blocks, i.e. AFTER scheduling (FIFO pool order or DRR
        # dispatch), so both modes price identical work in the order
        # they actually chose.
        ep = server.executor
        orig_serve = ep._serve_blocks

        def shim(conn, msg):
            nbytes = sum(length for _, _, length in msg.blocks)
            time.sleep(serve_delay_s_per_kb * (nbytes / 1024.0))
            return orig_serve(conn, msg)

        ep._serve_blocks = shim

        def victim_read():
            return client.get_reader(h_victim, 0, 4).read_all()

        def antag_read():
            return client.get_reader(h_antag, 0, 4).read_all()

        # solo baseline: the victim's bytes with a quiet serve path
        solo_k, solo_p = victim_read()
        solo = _canon_rows(solo_k, solo_p)
        antag_solo_k, antag_solo_p = antag_read()
        antag_solo = _canon_rows(antag_solo_k, antag_solo_p)

        stop = threading.Event()
        antag_reads: Dict[str, int] = {}
        antag_canon: Dict[str, Optional[np.ndarray]] = {}

        def antagonist(mode: str):
            # sustained wide fan-in: full re-reads back to back keep
            # read_ahead_depth requests queued on the serve path
            while not stop.is_set():
                k, p = antag_read()
                antag_reads[mode] = antag_reads.get(mode, 0) + 1
                if antag_canon.get(mode) is None:
                    antag_canon[mode] = _canon_rows(k, p)

        lat_ms: Dict[str, List[float]] = {}
        canon: Dict[str, np.ndarray] = {}
        for mode in ("fifo", "fair"):
            # flip ONLY the serving discipline, same cluster, same data
            ep.conf.fair_share_serving = (mode == "fair")
            stop.clear()
            antag_reads[mode] = 0
            antag_canon[mode] = None
            threads = [threading.Thread(target=antagonist, args=(mode,),
                                        daemon=True)
                       for _ in range(antag_threads)]
            for t in threads:
                t.start()
            time.sleep(0.3)  # let the backlog build
            lat = []
            ks, ps = [], []
            for _ in range(victim_reads):
                t0 = time.perf_counter()
                k, p = victim_read()
                lat.append((time.perf_counter() - t0) * 1000)
                ks.append(k)
                ps.append(p)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            lat_ms[mode] = lat
            # every victim read in this mode must return the solo bytes
            canon[mode] = _canon_rows(ks[-1], ps[-1])
            for k, p in zip(ks, ps):
                if not np.array_equal(_canon_rows(k, p), solo):
                    canon[mode] = np.zeros((0, 16), dtype=np.uint8)
                    break

        p99 = {m: float(np.percentile(v, 99)) for m, v in lat_ms.items()}
        mean = {m: float(np.mean(v)) for m, v in lat_ms.items()}
        identical = (np.array_equal(canon["fifo"], solo)
                     and np.array_equal(canon["fair"], solo)
                     and all(antag_canon[m] is not None
                             and np.array_equal(antag_canon[m], antag_solo)
                             for m in ("fifo", "fair")))
        return {
            "p99_ms": {m: round(v, 3) for m, v in p99.items()},
            "mean_ms": {m: round(v, 3) for m, v in mean.items()},
            "speedup": (round(p99["fifo"] / p99["fair"], 2)
                        if p99["fair"] else 0.0),
            "identical": bool(identical),
            "antag_reads": dict(antag_reads),
            "victim_reads": victim_reads,
            "fair_served": dict(ep.fair_served),
            "drr_reordered": (ep._serve_drr.reordered
                              if ep._serve_drr is not None else 0),
            "cross_tenant_evictions": dist_cache.cross_tenant_evictions,
            "serve_delay_s_per_kb": serve_delay_s_per_kb,
        }
    finally:
        client.stop()
        server.stop()
        driver.stop()


# -- sustained-traffic driver --------------------------------------------


class _TenantStats:
    def __init__(self):
        self.latencies_ms: List[float] = []
        self.rows = 0
        self.completed = 0
        self.shed = 0
        self.mismatches = 0
        self.lock = threading.Lock()


def _job_rows(kind: str, rows: int) -> int:
    return rows * (2 if kind == "join" else 1)


def run_sustained_bench(spill_root: str,
                        tenants: int = 3,
                        duration_s: float = 3.0,
                        arrival_hz: float = 6.0,
                        rows_per_map: int = 512,
                        num_maps: int = 2,
                        max_outstanding: int = 4,
                        metadata_shards: int = 2,
                        shard_ownership: bool = True,
                        seed: int = 0) -> Dict:
    """N tenants submit terasort/pagerank/join jobs at ``arrival_hz``
    each through the admission-controlled driver for ``duration_s``.

    Registrations flow through the SHARDED control plane by default
    (``metadata_shards``/``shard_ownership``): every register assigns a
    shard map and every publish takes the direct-to-owner path, so this
    bench doubles as the sustained-traffic soak for partitioned
    metadata ownership (``shard_batches`` in the result records the
    owner->driver convergence actually happening).

    Returns aggregate rows/s, per-tenant p99 job latency, admission
    accounting, and the zero-cross-tenant-eviction gate."""
    conf_kw = dict(connect_timeout_ms=20000, use_cpp_runtime=False,
                   pre_warm_connections=True,
                   admission_max_inflight=2, admission_queue_depth=1,
                   admission_retry_after_ms=200,
                   warm_read_cache=True, dist_cache_budget="64k",
                   metadata_shards=metadata_shards,
                   shard_ownership=shard_ownership)
    driver = TpuShuffleManager(TpuShuffleConf(**conf_kw), is_driver=True)
    execs = [TpuShuffleManager(
        TpuShuffleConf(**conf_kw), driver_addr=driver.driver_addr,
        executor_id=str(i), spill_dir=os.path.join(spill_root, f"s{i}"))
        for i in range(2)]
    try:
        for ex in execs:
            ex.executor.wait_for_members(2)
        parts = 4
        payload_w = 8
        stats = {t: _TenantStats() for t in range(1, tenants + 1)}
        submitted = {t: 0 for t in stats}
        sid_counter = {t: 0 for t in stats}
        kinds = ("terasort", "pagerank", "join")

        def run_job(tenant: int, kind: str, job_seed: int):
            st = stats[tenant]
            sid_counter[tenant] += 1
            sid = tenant * 100_000 + sid_counter[tenant]
            t0 = time.perf_counter()
            rng = np.random.default_rng(job_seed)
            handles = []
            try:
                n_shuffles = 2 if kind == "join" else 1
                written = []
                for j in range(n_shuffles):
                    h = driver.register_shuffle(
                        sid + j * 50_000, num_maps, parts,
                        PartitionerSpec("modulo"),
                        row_payload_bytes=payload_w, tenant=tenant)
                    handles.append(h)
                    keys = rng.integers(0, 1 << 20,
                                        num_maps * rows_per_map
                                        ).astype(np.uint64)
                    payload = rng.integers(
                        0, 255, (len(keys), payload_w)).astype(np.uint8)
                    written.append((keys, payload))
                    for m in range(num_maps):
                        w = execs[m % 2].get_writer(h, m)
                        s = slice(m * rows_per_map, (m + 1) * rows_per_map)
                        w.write_batch(keys[s], payload[s])
                        w.close()
                got_rows = 0
                ok = True
                supersteps = 2 if kind == "pagerank" else 1
                for h, (keys, payload) in zip(handles, written):
                    for _ in range(supersteps):
                        reader = execs[(tenant + 1) % 2].get_reader(h, 0,
                                                                    parts)
                        if kind == "terasort":
                            k, p = reader.read_sorted()
                            ok &= bool((np.diff(k.astype(np.int64))
                                        >= 0).all())
                        else:
                            k, p = reader.read_all()
                        got_rows += len(k)
                    ok &= np.array_equal(_canon_rows(k, p),
                                         _canon_rows(keys, payload))
                for j, h in enumerate(handles):
                    driver.unregister_shuffle(h.shuffle_id)
                with st.lock:
                    st.completed += 1
                    st.rows += got_rows
                    st.latencies_ms.append(
                        (time.perf_counter() - t0) * 1000)
                    if not ok:
                        st.mismatches += 1
            except AdmissionRejected:
                # a join job's SECOND register can reject after its
                # first was admitted: shed cleanly, nothing leaks
                for h in handles:
                    driver.unregister_shuffle(h.shuffle_id)
                with st.lock:
                    st.shed += 1

        job_threads: List[threading.Thread] = []

        def tenant_loop(tenant: int):
            # a Poisson-ish open-loop arrival process: one job every
            # 1/arrival_hz regardless of completions, up to the local
            # outstanding bound (beyond it the submission itself sheds)
            period = 1.0 / arrival_hz
            deadline = time.monotonic() + duration_s
            i = 0
            while time.monotonic() < deadline:
                live = [t for t in job_threads
                        if t.is_alive() and t.name == f"job-{tenant}"]
                submitted[tenant] += 1
                if len(live) >= max_outstanding:
                    with stats[tenant].lock:
                        stats[tenant].shed += 1
                else:
                    kind = kinds[i % len(kinds)]
                    t = threading.Thread(
                        target=run_job,
                        args=(tenant, kind,
                              seed * 1000 + tenant * 100 + i),
                        name=f"job-{tenant}", daemon=True)
                    job_threads.append(t)
                    t.start()
                i += 1
                time.sleep(period)

        t_start = time.perf_counter()
        loops = [threading.Thread(target=tenant_loop, args=(t,),
                                  daemon=True) for t in stats]
        for t in loops:
            t.start()
        for t in loops:
            t.join()
        for t in job_threads:
            t.join(timeout=60)
        wall_s = time.perf_counter() - t_start

        total_rows = sum(st.rows for st in stats.values())
        completed = sum(st.completed for st in stats.values())
        shed = sum(st.shed for st in stats.values())
        adm = driver.driver.admission.snapshot()
        return {
            "aggregate_rows_per_s": round(total_rows / wall_s, 0),
            "per_tenant_p99_ms": {
                t: (round(float(np.percentile(st.latencies_ms, 99)), 2)
                    if st.latencies_ms else None)
                for t, st in stats.items()},
            "jobs": {"submitted": sum(submitted.values()),
                     "completed": completed, "shed": shed},
            "identical": all(st.mismatches == 0 for st in stats.values()),
            "admission": adm,
            "cross_tenant_evictions": dist_cache.cross_tenant_evictions,
            "cache_evicted": dist_cache.stats()["evicted"],
            "wall_s": round(wall_s, 2),
            "tenants": tenants,
            "arrival_hz": arrival_hz,
            "metadata_shards": metadata_shards,
            "shard_batches": driver.driver.shard_batches,
            "shard_handoffs": driver.driver.shard_handoffs,
        }
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
