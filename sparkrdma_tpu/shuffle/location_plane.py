"""One-sided metadata plane: epoch-versioned location tables.

The reference's defining property is that the remote CPU never sits on
the serving path — locations are READ one-sided out of published tables
(scala/RdmaShuffleManager.scala:341-376). Our control plane carried that
flow as request/reply RPCs (``FetchTableReq``/``FetchOutputsReq``) on
EVERY stage, and iterative workloads (PageRank/ALS/TPC-DS supersteps
re-reading an unchanged parent shuffle) re-paid the full metadata cost
each superstep. Per "RPC Considered Harmful: Fast Distributed Deep
Learning on RDMA" (PAPERS.md), this module replaces the request/reply
metadata plane with one-sided publication of VERSIONED state:

* Every shuffle's location state carries an **epoch** (monotone,
  driver-allocated, starting at 1). Executors publish into the driver
  table once per map commit exactly as before — the epoch only moves
  when the state is REPAIRED: a re-execution overwrites an entry, an
  executor is tombstoned, or the shuffle unregisters (``EPOCH_DEAD``).
* Reducers keep a **local epoch-validated cache** (:class:`LocationPlane`)
  of the driver table and the per-map block-location entries. The warm
  path — superstep N over unchanged inputs — resolves every location
  from the cache: **zero metadata RPCs on the wire**. The cold path pays
  one driver-table sync plus one batched location read per (peer, epoch)
  and caches both under the epoch.
* Invalidation is **pushed**, not polled: the driver broadcasts
  ``EpochBumpMsg`` on the same channel as membership announces. A lost
  push is backstopped by the fetch path itself — a stale location fails
  its fetch, and the failure handler invalidates the cache the hard way
  (``invalidate``), so staleness can cost latency, never correctness.
* The driver table is **sharded by map-range across executors**
  (:class:`ShardMap`, ``metadata_shards``): the driver keeps ownership
  of shard assignment and commit fencing (only fence-surviving publishes
  are forwarded, as ``ShardEntryMsg``), while shard hosts serve
  cold-path table reads (``FetchShardReq`` long-poll) out of their
  replica (:class:`ShardStore`) — thousand-reducer fan-in spreads over
  shard hosts instead of serializing on one driver endpoint. The driver
  remains authoritative: any shard failure falls back to the driver
  long-poll.

"Memory-efficient array redistribution through portable collective
communication" (PAPERS.md) motivates the other half: redistribution
state stays RESIDENT across iterations instead of rebuilt per stage —
connections (already pre-warmed + cached), pool registrations, and this
module's location views all survive supersteps keyed by epoch, and
``shuffle/dist_cache.py`` extends the same idea to the reduced bytes
themselves (epoch-keyed cross-stage shuffle-output reuse).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from sparkrdma_tpu.shuffle.map_output import (
    MAP_ENTRY_SIZE,
    UNPUBLISHED,
    _MAP_ENTRY,
    DriverTable,
)

# epoch sentinel mirrored from messages.EPOCH_DEAD (kept here too so the
# plane has no wire dependency; tests assert they stay equal)
EPOCH_DEAD = -1


class ShardMap:
    """Map-range -> shard-host assignment for one shuffle, driver-owned.

    Maps are divided into ``len(shard_slots)`` contiguous ranges;
    ``shard_slots[i]`` is the executor slot hosting shard ``i``'s
    replica. Contiguity keeps one shard read one contiguous table slice
    (the same reason the reference's table is positional: range reads
    stay O(1) request, O(range) bytes).
    """

    def __init__(self, num_maps: int, shard_slots: List[int]):
        if num_maps <= 0 or not shard_slots:
            raise ValueError("need maps and at least one shard slot")
        self.num_maps = num_maps
        # ceil-divided contiguous spans; the last shard may run short.
        # Shards whose range would start past the map space are DROPPED
        # (5 maps over 4 slots = span 2 = 3 real shards): an empty shard
        # would own no maps, receive no forwards, and fail every sharded
        # sync into the driver fallback. The truncation is stable across
        # the wire: ceil(m / ceil(m / span)) == span for any span this
        # constructor produces, so sender and receiver derive identical
        # ranges from the truncated slot list.
        self._span = -(-num_maps // len(shard_slots))
        self.shard_slots = list(shard_slots[:-(-num_maps // self._span)])

    @property
    def num_shards(self) -> int:
        return len(self.shard_slots)

    def shard_of(self, map_id: int) -> int:
        if not 0 <= map_id < self.num_maps:
            raise IndexError(map_id)
        return map_id // self._span

    def range_of(self, shard: int) -> Tuple[int, int]:
        """[map_lo, map_hi) of one shard (never empty for valid shards)."""
        lo = shard * self._span
        return lo, min(self.num_maps, lo + self._span)

    def slot_of_map(self, map_id: int) -> int:
        return self.shard_slots[self.shard_of(map_id)]

    @staticmethod
    def assign(num_maps: int, membership, max_shards: int,
               avoid=()) -> Optional["ShardMap"]:
        """The driver's assignment policy: up to ``max_shards`` shards
        over the live executor slots, round-robin; None when sharding is
        off (``max_shards`` < 1) or there is nobody to host.

        ``membership`` is the driver's MembershipPlane (anything with a
        ``live_slots()`` method) — consulted directly so a DRAINING slot
        is never assigned as a shard owner: its writes are being walked
        off the host, handing it a fence-CAS range would re-pin it. A
        raw slot list is still accepted (tests, the model checker), in
        which case the caller vouches for liveness. ``avoid`` excludes
        slots mid-removal: membership tombstoning and shard handoff are
        not atomic, so reassignment must not re-pick the slot whose
        death triggered it."""
        if max_shards < 1 or num_maps <= 0:
            return None
        if hasattr(membership, "live_slots"):
            slots = list(membership.live_slots())  # excludes DRAINING
        else:
            slots = list(membership)
        if avoid:
            slots = [s for s in slots if s not in set(avoid)]
        if not slots:
            return None
        n = min(max_shards, len(slots), num_maps)
        return ShardMap(num_maps, [slots[i % len(slots)]
                                   for i in range(n)])


class _ShardState:
    """One shuffle's replica on a shard host: applied entries by map id.

    A plain dict rather than a positional buffer: the host may receive
    forwards for any subset of the map space (the driver only forwards
    the ranges this host owns, but the store doesn't need to know the
    shard map — ``FetchShardReq`` carries its range explicitly, so the
    replica serves whatever it holds and reports the in-range count)."""

    __slots__ = ("entries", "epoch", "num_maps")

    def __init__(self, num_maps: int):
        self.entries: Dict[int, bytes] = {}
        self.epoch = 0
        self.num_maps = num_maps


class ShardStore:
    """Executor-side driver-table shard replicas (the serve half of the
    sharded metadata plane). Fed one-sided by the driver's
    ``ShardEntryMsg`` forwards; read by peers' ``FetchShardReq``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._shuffles: Dict[int, _ShardState] = {}
        self.entries_applied = 0  # audit

    def apply(self, shuffle_id: int, epoch: int, map_id: int,
              num_maps: int, entry: bytes) -> None:
        """Apply one forwarded entry (idempotent positional overwrite;
        the driver already fenced it). The replica's epoch follows the
        newest forward — a repair forward carries the bumped epoch."""
        if len(entry) != MAP_ENTRY_SIZE:
            return
        with self._lock:
            state = self._shuffles.get(shuffle_id)
            if state is None:
                state = _ShardState(num_maps)
                self._shuffles[shuffle_id] = state
            state.entries[map_id] = bytes(entry)
            state.epoch = max(state.epoch, epoch)
            state.num_maps = max(state.num_maps, num_maps)
            self.entries_applied += 1

    def drop(self, shuffle_id: int) -> None:
        with self._lock:
            self._shuffles.pop(shuffle_id, None)

    def count_in(self, shuffle_id: int, map_lo: int,
                 map_hi: int) -> Optional[int]:
        """Published entries within [map_lo, map_hi), or None when the
        host holds no replica for the shuffle."""
        with self._lock:
            state = self._shuffles.get(shuffle_id)
            if state is None:
                return None
            return sum(1 for m in state.entries if map_lo <= m < map_hi)

    def read_range(self, shuffle_id: int, map_lo: int, map_hi: int
                   ) -> Optional[Tuple[int, int, bytes]]:
        """(num_published_in_range, epoch, entry bytes) for [map_lo,
        map_hi), UNPUBLISHED-filled holes; None = no replica here."""
        if map_hi < map_lo or map_lo < 0:
            return None
        with self._lock:
            state = self._shuffles.get(shuffle_id)
            if state is None:
                return None
            out = bytearray()
            n = 0
            for m in range(map_lo, map_hi):
                e = state.entries.get(m)
                if e is None:
                    out += _MAP_ENTRY.pack(0, UNPUBLISHED)
                else:
                    out += e
                    n += 1
            return n, state.epoch, bytes(out)


class LocationPlane:
    """One executor's epoch-validated cache of location metadata.

    Three layers, all keyed by (shuffle, epoch):

    * the driver table (complete tables only — partial tables are never
      memoized, same rule the endpoint's old memo kept),
    * per-(map, partition-range) block-location entries (what
      ``FetchOutputsReq`` returns on the cold path),
    * the shuffle's :class:`ShardMap`, when the driver pushed one.

    Validity rule: a cached item serves iff its epoch equals the newest
    epoch this executor has OBSERVED for the shuffle (pushes and table
    responses both advance the observation; observations are monotone).
    An ``EPOCH_DEAD`` push drops everything for the shuffle.

    Bounded: location ranges evict FIFO past ``max_ranges`` so a
    long-lived executor reading thousands of shuffles can't grow the
    plane without bound (complete tables are one entry per shuffle and
    dropped on unregister, so they need no separate cap).
    """

    def __init__(self, enabled: bool = True, max_ranges: int = 8192):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._epochs: Dict[int, int] = {}
        # shuffles observed DEAD: an EPOCH_DEAD push pops the epoch
        # record, so without this marker a LATE response stamped with
        # the pre-death epoch re-cached views for a dead shuffle (the
        # modelcheck ttl_vs_late_fetch schedule). put_* paths drop for
        # marked shuffles; a POSITIVE pushed bump or a push-delivered
        # registration signal (note_registered) re-arms the id — both
        # ride the driver's FIFO broadcast channel, so their arrival
        # postdates the death. Count- and time-bounded (see
        # utils/tombstones.py): zombie responses are bounded by request
        # deadlines, so an aged marker has nothing left to reject and
        # expires rather than keeping a reused id cold forever.
        from sparkrdma_tpu.utils.tombstones import TombstoneCache
        self._dead = TombstoneCache(ttl_s=60.0, cap=4096)
        self._tables: Dict[int, Tuple[DriverTable, int]] = {}
        self._locations: "OrderedDict[Tuple[int, int, int, int], Tuple[list, int]]" = OrderedDict()
        self._shard_maps: Dict[int, Tuple[ShardMap, int]] = {}
        # reduce plans (shuffle/planner.py): versioned by their OWN
        # plan_epoch, independent of the location epoch — a location
        # repair moves bytes, not the carve-up of reduce work. Newest
        # plan_epoch wins; EPOCH_DEAD drops the plan with the rest.
        self._plans: Dict[int, object] = {}
        # merged-segment directories (shuffle/push_merge.py): cached
        # under the LOCATION epoch like tables — a repair/tombstone bump
        # invalidates, so a re-pointed reducer re-pulls a directory the
        # driver has already pruned. Only non-empty directories are
        # cached (endpoint policy), so pre-finalize stages keep pulling.
        self._merged: Dict[int, Tuple[object, int]] = {}
        self._max_ranges = max_ranges
        # elastic membership (parallel/membership.py): the pushed
        # slot-state vector under ITS epoch — highest epoch wins, same
        # rule as announces. Empty until the first MembershipBumpMsg
        # (pre-elastic drivers never send one): every slot then reads
        # LIVE, the static-membership behavior.
        self._member_epoch = -1
        self._member_states: Tuple[int, ...] = ()
        # audit counters (surfaced via snapshot(); the warm-path test and
        # the iterative bench read these)
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.stale_drops = 0

    # -- membership states (parallel/membership.py) -----------------------

    def note_membership(self, epoch: int, states) -> List[int]:
        """Apply one pushed slot-state vector; stale (lower-epoch)
        pushes are ignored. Returns the slots that BECAME live with this
        bump (mid-job joiners — the health monitor registers them)."""
        with self._lock:
            if epoch <= self._member_epoch:
                return []
            old = self._member_states
            new = tuple(int(s) for s in states)
            self._member_epoch = epoch
            self._member_states = new
        joined = []
        for i, s in enumerate(new):
            was = old[i] if i < len(old) else None
            if s == 0 and was != 0:  # SLOT_LIVE
                joined.append(i)
        return joined

    def membership(self) -> Tuple[int, Tuple[int, ...]]:
        """``(epoch, states)`` — ``(-1, ())`` before any bump."""
        with self._lock:
            return self._member_epoch, self._member_states

    def slot_draining(self, slot: int) -> bool:
        """True when the pushed state vector marks the slot DRAINING —
        pushers stop choosing it as a merge target and planners stop
        placing work there. Unknown slots (no bump yet, or a joiner
        newer than the vector) read False = LIVE."""
        with self._lock:
            if not 0 <= slot < len(self._member_states):
                return False
            return self._member_states[slot] == 1  # SLOT_DRAINING

    # -- epoch observation ------------------------------------------------

    def note_registered(self, shuffle_id: int) -> None:
        """Re-arm a DEAD id: called on push-delivered registration
        signals (TenantMapMsg, ShardMapMsg, pushed ReducePlanMsg) —
        they ride the same FIFO broadcast channel as the EPOCH_DEAD
        that killed the id, so their arrival postdates the death and
        names a NEW incarnation. Response-path put_* calls never clear
        the marker (a late response is exactly what the marker exists
        to reject). Residual window: a response from the OLD
        incarnation still in flight when the id is re-registered and
        re-armed can cache once — epochs restart per registration, so
        without a wire-level registration generation no local guard
        can tell the incarnations apart; the fetch-failure
        invalidation backstop (module docstring) keeps that a latency
        cost, never a correctness one."""
        with self._lock:
            self._dead.discard(shuffle_id)

    def known_epoch(self, shuffle_id: int) -> Optional[int]:
        with self._lock:
            return self._epochs.get(shuffle_id)

    def note_epoch(self, shuffle_id: int, epoch: int) -> bool:
        """Observe ``epoch`` for ``shuffle_id``; returns True when the
        observation invalidated cached state (the push-invalidation
        path). ``EPOCH_DEAD`` drops the shuffle entirely."""
        with self._lock:
            if epoch == EPOCH_DEAD:
                had = (self._tables.pop(shuffle_id, None) is not None)
                self._epochs.pop(shuffle_id, None)
                self._shard_maps.pop(shuffle_id, None)
                self._plans.pop(shuffle_id, None)
                self._merged.pop(shuffle_id, None)
                self._dead.add(shuffle_id)
                dropped = self._drop_locations_locked(shuffle_id)
                if had or dropped:
                    self.invalidations += 1
                return had or dropped
            # a positive PUSHED epoch re-arms a dead id: the broadcast
            # channel is FIFO, so this bump postdates the death — the
            # id was re-registered (engine shuffle ids are reused)
            self._dead.discard(shuffle_id)
            prev = self._epochs.get(shuffle_id)
            if prev is not None and epoch <= prev:
                return False
            self._epochs[shuffle_id] = epoch
            stale = False
            cached = self._tables.get(shuffle_id)
            # analysis: epoch-eq-ok(validity is exact-epoch match; the monotone guard above ordered the observation)
            if cached is not None and cached[1] != epoch:
                del self._tables[shuffle_id]
                stale = True
            merged = self._merged.get(shuffle_id)
            # analysis: epoch-eq-ok(validity is exact-epoch match; the monotone guard above ordered the observation)
            if merged is not None and merged[1] != epoch:
                del self._merged[shuffle_id]
                stale = True
            for key in [k for k in self._locations if k[0] == shuffle_id]:
                # analysis: epoch-eq-ok(validity is exact-epoch match; the monotone guard above ordered the observation)
                if self._locations[key][1] != epoch:
                    del self._locations[key]
                    stale = True
            if stale:
                self.invalidations += 1
                self.stale_drops += 1
            return stale

    # -- driver table -----------------------------------------------------

    def put_table(self, shuffle_id: int, table: DriverTable,
                  epoch: int) -> None:
        """Memoize a COMPLETE table under its epoch (and observe the
        epoch). Partial tables never memoize — later readers with higher
        expectations must go back to the source."""
        if not self.enabled or table.num_published < table.num_maps:
            return
        with self._lock:
            if shuffle_id in self._dead:
                # late response for a DEAD shuffle: the epoch record is
                # gone, only the marker knows this would resurrect it
                self.stale_drops += 1
                return
            prev = self._epochs.get(shuffle_id)
            if prev is not None and epoch < prev:
                # the response predates a pushed invalidation: stale
                self.stale_drops += 1
                return
            self._epochs[shuffle_id] = max(prev or 0, epoch)
            self._tables[shuffle_id] = (table, epoch)

    def table(self, shuffle_id: int) -> Optional[Tuple[DriverTable, int]]:
        """The cached complete table iff epoch-current, else None."""
        if not self.enabled:
            return None
        with self._lock:
            cached = self._tables.get(shuffle_id)
            if cached is None:
                self.misses += 1
                return None
            known = self._epochs.get(shuffle_id)
            # analysis: epoch-eq-ok(a cached view serves only at exactly the newest observed epoch; != means stale)
            if known is not None and cached[1] != known:
                del self._tables[shuffle_id]
                self.stale_drops += 1
                self.misses += 1
                return None
            self.hits += 1
            return cached

    # -- block-location entries -------------------------------------------

    def put_locations(self, shuffle_id: int, map_id: int, start: int,
                      end: int, locations: list, epoch: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            if shuffle_id in self._dead:
                self.stale_drops += 1
                return
            prev = self._epochs.get(shuffle_id)
            if prev is not None and epoch < prev:
                self.stale_drops += 1
                return
            self._epochs[shuffle_id] = max(prev or 0, epoch)
            key = (shuffle_id, map_id, start, end)
            self._locations[key] = (locations, epoch)
            self._locations.move_to_end(key)
            while len(self._locations) > self._max_ranges:
                self._locations.popitem(last=False)

    def locations(self, shuffle_id: int, map_id: int, start: int,
                  end: int) -> Optional[list]:
        if not self.enabled:
            return None
        with self._lock:
            key = (shuffle_id, map_id, start, end)
            cached = self._locations.get(key)
            if cached is None:
                self.misses += 1
                return None
            known = self._epochs.get(shuffle_id)
            # analysis: epoch-eq-ok(a cached view serves only at exactly the newest observed epoch; != means stale)
            if known is not None and cached[1] != known:
                del self._locations[key]
                self.stale_drops += 1
                self.misses += 1
                return None
            self.hits += 1
            return cached[0]

    # -- shard map --------------------------------------------------------

    def put_shard_map(self, shuffle_id: int, shard_map: ShardMap,
                      epoch: int) -> bool:
        """Cache a pushed shard assignment; highest generation wins
        (``epoch`` carries the composed ownership generation in
        shard_ownership mode, a constant 1 in replica mode — either
        way a reordered stale push must not roll a handoff back).
        Returns True when the assignment was accepted."""
        with self._lock:
            prev = self._shard_maps.get(shuffle_id)
            if prev is not None and epoch < prev[1]:
                return False
            self._shard_maps[shuffle_id] = (shard_map, epoch)
            return True

    def shard_map(self, shuffle_id: int) -> Optional[ShardMap]:
        with self._lock:
            cached = self._shard_maps.get(shuffle_id)
            return cached[0] if cached is not None else None

    def shard_map_v(self, shuffle_id: int):
        """(shard_map, generation) — the ownership write path needs the
        generation to stamp direct publishes."""
        with self._lock:
            return self._shard_maps.get(shuffle_id)

    # -- reduce plan ------------------------------------------------------

    def put_plan(self, shuffle_id: int, plan) -> bool:
        """Cache one shuffle's ReducePlan; newest ``plan_epoch`` wins
        (pushes may reorder — a stale re-delivery must never roll a
        re-plan back). Returns True when the plan was ACCEPTED (first
        plan or a newer epoch) — plan-keyed warm invalidation gates on
        this, so a rejected stale push can't wipe warm state either."""
        with self._lock:
            if shuffle_id in self._dead:
                return False  # a late plan response for a DEAD shuffle
            prev = self._plans.get(shuffle_id)
            if prev is not None and plan.plan_epoch <= prev.plan_epoch:
                return False
            self._plans[shuffle_id] = plan
            return True

    def plan(self, shuffle_id: int):
        """The cached ReducePlan (cache-first resolution; validity is by
        plan_epoch monotonicity, not the location epoch)."""
        with self._lock:
            return self._plans.get(shuffle_id)

    # -- merged-segment directory (push-merge) ----------------------------

    def put_merged(self, shuffle_id: int, directory, epoch: int) -> None:
        """Cache one shuffle's merged directory under its epoch (same
        staleness rule as tables: a response predating a pushed
        invalidation is dropped, never served)."""
        if not self.enabled:
            return
        with self._lock:
            if shuffle_id in self._dead:
                self.stale_drops += 1
                return
            prev = self._epochs.get(shuffle_id)
            if prev is not None and epoch < prev:
                self.stale_drops += 1
                return
            self._epochs[shuffle_id] = max(prev or 0, epoch)
            self._merged[shuffle_id] = (directory, epoch)

    def merged(self, shuffle_id: int):
        """The cached merged directory iff epoch-current, else None."""
        if not self.enabled:
            return None
        with self._lock:
            cached = self._merged.get(shuffle_id)
            if cached is None:
                self.misses += 1
                return None
            known = self._epochs.get(shuffle_id)
            # analysis: epoch-eq-ok(a cached view serves only at exactly the newest observed epoch; != means stale)
            if known is not None and cached[1] != known:
                del self._merged[shuffle_id]
                self.stale_drops += 1
                self.misses += 1
                return None
            self.hits += 1
            return cached[0]

    # -- invalidation -----------------------------------------------------

    def _drop_locations_locked(self, shuffle_id: int) -> bool:
        keys = [k for k in self._locations if k[0] == shuffle_id]
        for k in keys:
            del self._locations[k]
        return bool(keys)

    def invalidate(self, shuffle_id: int) -> None:
        """Hard invalidation (fetch failure / recovery / unregister):
        drop every cached view of the shuffle but KEEP the observed
        epoch — a re-read must come from the source, and a racing
        response stamped with the old epoch must still be recognized as
        stale."""
        with self._lock:
            dropped = (self._tables.pop(shuffle_id, None) is not None)
            dropped |= self._drop_locations_locked(shuffle_id)
            self._shard_maps.pop(shuffle_id, None)
            self._merged.pop(shuffle_id, None)
            # the plan drops too: invalidate() is also the unregister
            # backstop, and engine shuffle ids are reused — a re-read
            # refetches the plan from the driver for the price of one RPC
            self._plans.pop(shuffle_id, None)
            if dropped:
                self.invalidations += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "tables": len(self._tables),
                "ranges": len(self._locations),
                "shard_maps": len(self._shard_maps),
                "plans": len(self._plans),
                "merged": len(self._merged),
                "member_epoch": self._member_epoch,
                "member_states": list(self._member_states),
                "dead": len(self._dead),
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "stale_drops": self.stale_drops,
            }
