"""Bounded-memory sort/merge: the ExternalSorter role.

The reference leans on Spark's ExternalSorter for beyond-memory reduces
(scala/RdmaShuffleReader.scala:100-114: sort runs, spill to disk, k-way
merge). A standalone framework needs that half in-tree:

* ``merge_two`` / ``merge_runs`` — vectorized positional merges of sorted
  row arrays (O(N log R) tournament over R runs; numpy has no merge
  primitive, but two sorted arrays interleave with two ``searchsorted``
  calls and two scatters — no per-row Python).
* ``ExternalMerger`` — the spill path: batches accumulate to a memory
  budget, spill as sorted runs to disk, then stream back globally sorted
  via a k-way buffered merge whose resident set is bounded by
  ``runs x run_buffer_rows`` rows regardless of dataset size. Plain
  ``file.read`` (not mmap) so an address-space rlimit genuinely bounds
  the process.

Merge scheme (vectorized k-way): each live run keeps a small sorted
buffer; every round emits all rows with key <= the minimum over runs of
"my buffer's last key" — any unread row in any run is >= that threshold,
so the emitted prefix is globally final. The threshold run drains its
whole buffer, guaranteeing progress.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

Batch = Tuple[np.ndarray, np.ndarray]  # (keys u64[N], payload u8[N, W])


def merge_two(a_keys: np.ndarray, a_rows: np.ndarray,
              b_keys: np.ndarray, b_rows: np.ndarray) -> Batch:
    """Merge two key-sorted row sets, stable with ``a`` first on ties."""
    pos_a = np.arange(len(a_keys)) + np.searchsorted(b_keys, a_keys, "left")
    pos_b = np.arange(len(b_keys)) + np.searchsorted(a_keys, b_keys, "right")
    keys = np.empty(len(a_keys) + len(b_keys), a_keys.dtype)
    rows = np.empty((len(keys),) + a_rows.shape[1:], a_rows.dtype)
    keys[pos_a], keys[pos_b] = a_keys, b_keys
    rows[pos_a], rows[pos_b] = a_rows, b_rows
    return keys, rows


def merge_runs(runs: Sequence[Batch]) -> Batch:
    """Tournament-merge R key-sorted runs in O(N log R) — the in-memory
    replacement for the full re-sort (models/terasort.py streamed merge)."""
    runs = list(runs)
    nonempty = [r for r in runs if len(r[0])]
    if not nonempty:
        if runs:  # preserve the caller's dtypes/row shape, just empty
            k0, r0 = runs[0]
            return k0[:0], r0[:0]
        return np.zeros(0, np.uint64), np.zeros((0, 0), np.uint8)
    runs = nonempty
    while len(runs) > 1:
        nxt = []
        for i in range(0, len(runs) - 1, 2):
            nxt.append(merge_two(*runs[i], *runs[i + 1]))
        if len(runs) % 2:
            nxt.append(runs[-1])
        runs = nxt
    return runs[0]


class ExternalMerger:
    """Spill-to-disk sorted merge with a bounded memory footprint.

    ``add_batch`` buffers rows; when buffered bytes exceed
    ``memory_budget_bytes`` the buffer is sorted and written out as one
    run. ``sorted_batches()`` then streams the global sort order, holding
    only ``num_runs x run_buffer_rows`` rows resident. Track
    ``peak_buffer_bytes`` to audit the bound.
    """

    def __init__(self, row_payload_bytes: int,
                 spill_dir: Optional[str] = None,
                 memory_budget_bytes: int = 64 << 20,
                 run_buffer_rows: int = 8192):
        self.row_payload_bytes = row_payload_bytes
        self.row_bytes = 8 + row_payload_bytes
        self.memory_budget_bytes = memory_budget_bytes
        self.run_buffer_rows = run_buffer_rows
        self._own_dir = spill_dir is None
        self.spill_dir = spill_dir or tempfile.mkdtemp(prefix="extsort_")
        os.makedirs(self.spill_dir, exist_ok=True)
        self._pending: List[Batch] = []
        self._pending_bytes = 0
        self._runs: List[Tuple[str, int]] = []  # (path, num_rows)
        self.spilled_bytes = 0
        self.peak_buffer_bytes = 0
        self._closed = False

    # -- feeding ---------------------------------------------------------

    def add_batch(self, keys: np.ndarray, payload: np.ndarray) -> None:
        assert not self._closed
        if len(keys) == 0:
            return
        self._pending.append((np.asarray(keys, np.uint64),
                              np.asarray(payload, np.uint8)))
        self._pending_bytes += len(keys) * self.row_bytes
        self.peak_buffer_bytes = max(self.peak_buffer_bytes,
                                     self._pending_bytes)
        if self._pending_bytes >= self.memory_budget_bytes:
            self._spill()

    def _spill(self) -> None:
        if not self._pending:
            return
        keys = np.concatenate([k for k, _ in self._pending])
        payload = np.concatenate([p for _, p in self._pending])
        self._pending, self._pending_bytes = [], 0
        order = np.argsort(keys, kind="stable")
        rows = np.empty((len(keys), self.row_bytes), np.uint8)
        rows[:, :8] = keys[order, None].view(np.uint8).reshape(-1, 8)
        rows[:, 8:] = payload[order]
        path = os.path.join(self.spill_dir, f"run{len(self._runs)}.bin")
        with open(path, "wb") as f:
            f.write(rows.tobytes())
        self._runs.append((path, len(keys)))
        self.spilled_bytes += rows.nbytes

    # -- draining --------------------------------------------------------

    def sorted_batches(self) -> Iterator[Batch]:
        """Stream the global sort order; bounded resident set."""
        assert not self._closed
        if not self._runs:
            # everything fit in the budget: sort in memory, skip the disk
            # round-trip entirely
            if not self._pending:
                return
            keys = np.concatenate([k for k, _ in self._pending])
            payload = np.concatenate([p for _, p in self._pending])
            self._pending, self._pending_bytes = [], 0
            order = np.argsort(keys, kind="stable")
            yield keys[order], payload[order]
            return
        self._spill()  # flush the tail as the final run
        cursors = [_RunCursor(path, rows, self.row_bytes,
                              self.run_buffer_rows)
                   for path, rows in self._runs]
        try:
            live = [c for c in cursors if c.refill()]
            while live:
                # all rows <= the minimum of the buffers' last keys are
                # globally final this round
                threshold = min(c.last_key() for c in live)
                ks, ps = [], []
                for c in live:
                    k, p = c.take_upto(threshold)
                    if len(k):
                        ks.append(k)
                        ps.append(p)
                keys = np.concatenate(ks)
                payload = np.concatenate(ps)
                order = np.argsort(keys, kind="stable")
                yield keys[order], payload[order]
                live = [c for c in live if c.ensure()]
        finally:
            for c in cursors:
                c.close()

    def sorted_all(self) -> Batch:
        """Materialize the merge (small datasets / tests)."""
        parts = list(self.sorted_batches())
        if not parts:
            return (np.zeros(0, np.uint64),
                    np.zeros((0, self.row_payload_bytes), np.uint8))
        return (np.concatenate([k for k, _ in parts]),
                np.concatenate([p for _, p in parts]))

    @property
    def num_runs(self) -> int:
        return len(self._runs) + (1 if self._pending else 0)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._pending = []
        for path, _ in self._runs:
            try:
                os.unlink(path)
            except OSError:
                pass
        if self._own_dir:
            shutil.rmtree(self.spill_dir, ignore_errors=True)

    def __enter__(self) -> "ExternalMerger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _RunCursor:
    """Buffered reader over one sorted run file."""

    def __init__(self, path: str, total_rows: int, row_bytes: int,
                 buffer_rows: int):
        self._f = open(path, "rb")
        self._remaining = total_rows
        self._row_bytes = row_bytes
        self._buffer_rows = buffer_rows
        self._keys = np.zeros(0, np.uint64)
        self._payload = np.zeros((0, row_bytes - 8), np.uint8)

    def refill(self) -> bool:
        """Read the next chunk into the (empty) buffer; False when the
        run is exhausted. Only called with an empty buffer, which is what
        keeps the resident bound at exactly buffer_rows per run."""
        assert not len(self._keys)
        if self._remaining == 0:
            return False
        take = min(self._buffer_rows, self._remaining)
        data = self._f.read(take * self._row_bytes)
        self._remaining -= take
        rows = np.frombuffer(data, np.uint8).reshape(take, self._row_bytes)
        self._keys = rows[:, :8].copy().view(np.uint64).ravel()
        self._payload = rows[:, 8:].copy()
        return True

    def ensure(self) -> bool:
        """Make sure the buffer is non-empty; False when fully drained."""
        if len(self._keys):
            return True
        return self.refill() if self._remaining else False

    def last_key(self) -> int:
        return int(self._keys[-1])

    def take_upto(self, threshold: int) -> Batch:
        cut = int(np.searchsorted(self._keys, np.uint64(threshold), "right"))
        k, p = self._keys[:cut], self._payload[:cut]
        self._keys, self._payload = self._keys[cut:], self._payload[cut:]
        return k, p

    def close(self) -> None:
        self._f.close()
