"""TpuShuffleManager: the engine-facing plugin hub.

Re-design of ``scala/RdmaShuffleManager.scala`` keeping its API shape —
``register_shuffle / get_writer / get_reader / unregister_shuffle / stop``
(:143-310) — so an engine swaps shuffle implementations with one config line
(README.md:69-71 analogue).

Role split matches the reference: the driver allocates per-shuffle tables
and runs membership (:38-140, 155-183); executors lazily boot their
endpoint + hello on first writer/reader (:186-232) — here the boot happens
in ``__init__`` since there's no engine-imposed laziness to preserve, and a
single process may host the driver role, an executor role, or both (the
reference forbids local mode, :154, because in-process RDMA is pointless;
an in-process multi-executor TPU cluster is, by contrast, the primary
single-host deployment, so it is supported, not rejected).

The shuffle **handle** carries everything a task needs — ids, sizes, row
width, partitioner spec — the way the reference's handles piggyback the
driver table's (address, length, rkey) through task serialization
(scala/RdmaUtils.scala:145-159).
"""

from __future__ import annotations

import os
import tempfile
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel.endpoints import DriverEndpoint, ExecutorEndpoint
from sparkrdma_tpu.runtime.pool import BufferPool
from sparkrdma_tpu.shuffle.reader import TpuShuffleReader
from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver
from sparkrdma_tpu.shuffle.writer import Partitioner, TpuShuffleWriter
from sparkrdma_tpu.utils.stats import MemStats, ShuffleReaderStats
from sparkrdma_tpu.utils import trace as trace_mod

import logging

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class PartitionerSpec:
    """Serializable partitioner description (handles cross process
    boundaries; callables don't)."""

    kind: str  # "hash" | "range" | "modulo"
    splitters: Optional[Tuple[int, ...]] = None

    def build(self, num_partitions: int) -> Partitioner:
        if self.kind == "hash":
            # host-side numpy mirror of ops.partition.hash_partition (same
            # murmur finalizer, bit-identical) — the writer partitions on
            # the host, and routing through jnp would dispatch to the
            # default accelerator for no benefit
            def hash_part(keys):
                k = np.asarray(keys, dtype=np.uint64) & 0xFFFFFFFF
                k = ((k ^ (k >> 16)) * 0x85EBCA6B) & 0xFFFFFFFF
                k = ((k ^ (k >> 13)) * 0xC2B2AE35) & 0xFFFFFFFF
                k = k ^ (k >> 16)
                return (k % num_partitions).astype(np.int64)
            return hash_part
        if self.kind == "range":
            splitters = np.asarray(self.splitters, dtype=np.uint64)
            return lambda keys: np.searchsorted(
                splitters, np.asarray(keys), side="right").astype(np.int64)
        if self.kind == "modulo":
            return lambda keys: (np.asarray(keys) % num_partitions).astype(np.int64)
        raise ValueError(f"unknown partitioner kind {self.kind!r}")


@dataclass(frozen=True)
class ShuffleHandle:
    """(scala/RdmaUtils.scala:145-159 analogue). ``combiner`` is the
    map-side aggregator registered with the shuffle (Spark carries it on
    the handle's dependency): every writer of this shuffle applies it —
    including stage-retry recomputes and shipped tasks, whose handles
    travel by cloudpickle. None = no map-side combine."""

    shuffle_id: int
    num_maps: int
    num_partitions: int
    row_payload_bytes: int
    partitioner: PartitionerSpec
    combiner: Optional[Callable] = None
    # tenancy: the tenant id minted at registerShuffle rides the handle
    # through task serialization, so every writer/reader/pool lease on
    # every executor charges the right owner even if the one-sided
    # TenantMapMsg push was lost (shuffle/tenancy.py)
    tenant: int = 0


class TpuShuffleManager:
    """One per process; ``is_driver`` and/or executor role."""

    def __init__(self, conf: Optional[TpuShuffleConf] = None,
                 is_driver: bool = False,
                 driver_addr: Optional[Tuple[str, int]] = None,
                 host: str = "127.0.0.1", executor_id: str = "driver",
                 spill_dir: Optional[str] = None,
                 num_executors_hint: int = 0,
                 lease_store=None, lease_holder: Optional[str] = None):
        self.conf = conf or TpuShuffleConf()
        self.is_driver = is_driver
        self.driver: Optional[DriverEndpoint] = None
        self.executor: Optional[ExecutorEndpoint] = None
        self.resolver: Optional[TpuShuffleBlockResolver] = None
        self._handles: Dict[int, ShuffleHandle] = {}
        self._lock = threading.Lock()
        self.pool = BufferPool(self.conf)
        # worker-process shuffle cache budget (mesh results + warm
        # iterative ranges, shuffle/dist_cache.py) — process-global, so
        # co-hosted managers share one bound like they share the process
        from sparkrdma_tpu.shuffle import dist_cache
        dist_cache.configure(self.conf.dist_cache_budget,
                             tenant_quota=self.conf.tenant_cache_quota)
        self.reader_stats = (ShuffleReaderStats(self.conf)
                             if self.conf.collect_shuffle_reader_stats else None)
        self.tracer = trace_mod.get(self.conf)
        self._role_name = executor_id  # "driver" for the driver role
        self._mem_stats = MemStats()

        if is_driver:
            # HA deployments hand the driver role a shared lease store
            # (shuffle/ha.py): the endpoint renews the lease and mutes
            # itself the instant a standby wins the next term
            self.driver = DriverEndpoint(self.conf, host=host,
                                         lease_store=lease_store,
                                         lease_holder=lease_holder)
            driver_addr = self.driver.address
        if driver_addr is None:
            raise ValueError("executor role needs driver_addr")
        self.driver_addr = driver_addr

        self.block_server = None
        self.pusher = None
        self.merge_client = None
        if executor_id != "driver":
            from sparkrdma_tpu.runtime.blockserver import maybe_create
            self.block_server = maybe_create(self.conf, host=host,
                                             tracer=self.tracer)
            spill_dir = spill_dir or tempfile.mkdtemp(prefix="tpushuffle_")
            self.resolver = TpuShuffleBlockResolver(
                spill_dir, block_server=self.block_server, conf=self.conf)
            self.executor = ExecutorEndpoint(
                host, executor_id, driver_addr, data_source=self.resolver,
                conf=self.conf,
                block_port=self.block_server.port if self.block_server else 0,
                tracer=self.tracer)
            planned = (self.conf.planned_push and self.conf.adaptive_plan)
            if self.conf.push_merge:
                # push-merge dataplane (shuffle/push_merge.py): this
                # executor is a merge TARGET (store served through the
                # endpoint) and an overflow client for the writer's
                # ENOSPC ladder
                from sparkrdma_tpu.shuffle.push_merge import (
                    MergeClient, MergeStore)
                self.executor.merge_store = MergeStore(self.resolver,
                                                       self.conf)
                self.merge_client = MergeClient(self.executor, self.conf)
                if self.conf.cold_tier:
                    # cold tier (shuffle/cold_tier.py): finalized merged
                    # segments tier to the blob store in the background;
                    # the publish callback rides the one-sided driver
                    # channel like every other publish
                    from sparkrdma_tpu.shuffle.cold_tier import (
                        TieringService, open_store)
                    store = open_store(self.conf)
                    if store is not None:
                        self.executor.tiering = TieringService(
                            store, self.resolver, self.conf,
                            publish=self.executor._publish_tiered,
                            tracer=self.tracer)
            if planned:
                # planned push (shuffle/pushed_store.py): this executor
                # is a planned-push TARGET — staged reduce inputs the
                # fetcher resolves first
                from sparkrdma_tpu.shuffle.pushed_store import (
                    PushedInputStore)
                self.executor.pushed_store = PushedInputStore(
                    self.resolver, self.conf, pool=self.pool,
                    tracer=self.tracer)
            if self.conf.push_merge or planned:
                # one background pusher serves both dataplanes: merge
                # replicas at commit, planned reducer slots once the
                # plan is in hand (replayed via on_plan when it lands
                # after the commit)
                from sparkrdma_tpu.shuffle.push_merge import SegmentPusher
                self.pusher = SegmentPusher(
                    self.executor, self.resolver, self.conf,
                    pool=self.pool, tracer=self.tracer,
                    pushed_store=self.executor.pushed_store)
                self.executor.on_plan_cb = self.pusher.on_plan
            self.executor.start()
            if num_executors_hint:
                self.executor.wait_for_members(num_executors_hint)

    # -- engine SPI ------------------------------------------------------

    def register_shuffle(self, shuffle_id: int, num_maps: int,
                         num_partitions: int,
                         partitioner: PartitionerSpec,
                         row_payload_bytes: int = 0,
                         combiner=None, tenant: int = 0) -> ShuffleHandle:
        """Driver-side (scala/RdmaShuffleManager.scala:143-183).

        ``tenant`` is the owning tenant id minted here and threaded
        through every layer (quotas, fair-share serving, admission).
        With ``admission_max_inflight`` configured, a tenant at its
        in-flight cap parks in the admission queue and — past the queue
        depth or the park deadline — gets
        :class:`~sparkrdma_tpu.shuffle.tenancy.AdmissionRejected` with
        a retry-after hint instead of a registration."""
        if self.driver is None:
            raise RuntimeError("register_shuffle is a driver-role call")
        self.driver.register_shuffle(shuffle_id, num_maps, num_partitions,
                                     tenant=tenant)
        handle = ShuffleHandle(shuffle_id, num_maps, num_partitions,
                               row_payload_bytes, partitioner, combiner,
                               tenant=tenant)
        with self._lock:
            self._handles[shuffle_id] = handle
        return handle

    def get_writer(self, handle: ShuffleHandle, map_id: int,
                   combiner=None) -> "_PublishingWriter":
        """(scala/RdmaShuffleManager.scala:263-291). Map-side combine
        comes from the handle's registered combiner (every writer of the
        shuffle, on every path — recomputes included); the ``combiner``
        kwarg overrides per-writer (writer.make_sum_combiner or a custom
        ``(keys_sorted, payload_sorted) -> (keys', payload')``)."""
        if self.executor is None or self.resolver is None:
            raise RuntimeError("get_writer is an executor-role call")
        self._teach_tenant(handle)
        overflow = (self.merge_client.overflow_spill
                    if self.merge_client is not None else None)
        inner = TpuShuffleWriter(
            self.resolver, handle.shuffle_id, map_id, handle.num_partitions,
            handle.partitioner.build(handle.num_partitions),
            handle.row_payload_bytes,
            combiner=combiner if combiner is not None else handle.combiner,
            conf=self.conf, pool=self.pool, tracer=self.tracer,
            overflow_spill=overflow)
        return _PublishingWriter(inner, self.executor, tracer=self.tracer,
                                 pusher=self.pusher)

    def get_reader(self, handle: ShuffleHandle, start_partition: int,
                   end_partition: int, map_range=None) -> TpuShuffleReader:
        """(scala/RdmaShuffleManager.scala:234-261). ``map_range`` is the
        adaptive plan's split-task map slice — ``(map_lo, map_hi)`` reads
        the partition range from just those maps; None reads all."""
        if self.executor is None:
            raise RuntimeError("get_reader is an executor-role call")
        self._teach_tenant(handle)
        return TpuShuffleReader(self.executor, self.resolver, self.conf,
                                handle.shuffle_id, handle.num_maps,
                                start_partition, end_partition,
                                handle.row_payload_bytes,
                                reader_stats=self.reader_stats,
                                tracer=self.tracer, pool=self.pool,
                                map_range=map_range)

    def _teach_tenant(self, handle: ShuffleHandle) -> None:
        """Teach local components the handle's tenant (the backstop for
        a lost TenantMapMsg push — handles travel with tasks, so the
        local path always knows the owner)."""
        tenant = getattr(handle, "tenant", 0)
        if self.resolver is not None:
            self.resolver.note_tenant(handle.shuffle_id, tenant)
        if self.executor is not None:
            self.executor.note_tenant(handle.shuffle_id, tenant)
        from sparkrdma_tpu.shuffle import dist_cache
        dist_cache.set_tenant(handle.shuffle_id, tenant)

    def gc_orphans(self, live_shuffle_ids, min_age_s: float = 60.0) -> int:
        """Executor-role GC sweep: reap committed outputs, merged
        segments and overflow blobs of shuffles absent from the
        driver's live set (``live_shuffle_ids``) and unknown locally —
        debris of dead processes that no unregister push will ever
        name. ``min_age_s`` skips files fresh enough to be a commit or
        push racing the live-set snapshot. Returns files reaped."""
        if self.resolver is None:
            raise RuntimeError("gc_orphans is an executor-role call")
        n = self.resolver.reap_orphans(live_shuffle_ids, min_age_s)
        if self.executor is not None and self.executor.merge_store is not None:
            n += self.executor.merge_store.reap_orphans(live_shuffle_ids,
                                                        min_age_s)
        if self.executor is not None and self.executor.tiering is not None:
            n += self.executor.tiering.reap_orphans(live_shuffle_ids,
                                                    min_age_s)
        return n

    def plan_reduce(self, handle: ShuffleHandle):
        """Driver-role: build + publish the shuffle's adaptive
        ReducePlan at map-stage completion (shuffle/planner.py). Returns
        the plan, or None when ``adaptive_plan`` is off or no sizes were
        collected — callers fall back to the identity plan."""
        if self.driver is None:
            raise RuntimeError("plan_reduce is a driver-role call")
        return self.driver.build_reduce_plan(handle.shuffle_id,
                                             tracer=self.tracer)

    def decommission_slot(self, slot: int,
                          deadline_ms: Optional[int] = None) -> dict:
        """Driver-role: gracefully drain + retire one executor slot
        (parallel/membership.py) — push-merge replicates the drainee's
        committed outputs, location entries re-point under a bumped
        epoch, and the slot retires with zero re-executions; a drainee
        death mid-drain falls back to ordinary tombstone recovery."""
        if self.driver is None:
            raise RuntimeError("decommission_slot is a driver-role call")
        return self.driver.decommission_slot(slot, deadline_ms=deadline_ms)

    def join_cluster(self) -> None:
        """Executor-role: announce an explicit mid-job JOIN (the elastic
        scale-up path; the startup hello already made this executor a
        member — this names the intent so the driver traces it)."""
        if self.executor is None:
            raise RuntimeError("join_cluster is an executor-role call")
        self.executor.join_cluster()

    def recover_and_republish(self) -> dict:
        """Elastic rejoin: recover committed spills from disk and
        re-publish them under this executor's (new) slot. The positional
        publish overwrite atomically repairs each driver-table entry."""
        if self.resolver is None or self.executor is None:
            raise RuntimeError("executor-role call")
        recovered = self.resolver.recover()
        for shuffle_id, entries in recovered.items():
            for m, token in entries:
                lengths = None
                if self.conf.adaptive_plan:
                    # re-publishes must feed the size histogram too, or
                    # a post-rejoin plan would undercount this executor
                    table = self.resolver.get_output_table(shuffle_id, m)
                    if table is not None:
                        lengths = [table.get_block_location(p).length
                                   for p in range(table.num_partitions)]
                self.executor.publish_map_output(
                    shuffle_id, m, token,
                    fence=self.resolver.committed_fence(shuffle_id, m),
                    lengths=lengths)
        return recovered

    def unregister_shuffle(self, shuffle_id: int) -> None:
        """(scala/RdmaShuffleManager.scala:293-299)."""
        if self.driver is not None:
            self.driver.unregister_shuffle(shuffle_id)
        if self.executor is not None:
            self.executor.invalidate_shuffle(shuffle_id)
            if self.executor.merge_store is not None:
                self.executor.merge_store.drop_shuffle(shuffle_id)
            if self.executor.pushed_store is not None:
                self.executor.pushed_store.drop_shuffle(shuffle_id)
            if self.executor.tiering is not None:
                self.executor.tiering.drop_shuffle(shuffle_id)
        if self.pusher is not None:
            self.pusher.forget(shuffle_id)
        if self.resolver is not None:
            self.resolver.remove_shuffle(shuffle_id)
        with self._lock:
            self._handles.pop(shuffle_id, None)

    def stop(self) -> None:
        """Stats dump then teardown (scala/RdmaShuffleManager.scala:301-310;
        histograms at RdmaShuffleReaderStats.scala:55-81; pool stats at
        RdmaBufferManager.java:217-231)."""
        if self.reader_stats is not None:
            self.reader_stats.log_summary(log)
        if self.block_server is not None:
            # flush the registered-region pool's activity into the trace
            # (serve.pin / serve.zero_copy / serve.remap instants) BEFORE
            # the dump below writes the file
            self.block_server.trace_serve()
        if self.tracer.enabled and self.conf.trace_file:
            # one file per role so a cluster of managers sharing one conf
            # doesn't overwrite each other's dumps
            path = f"{self.conf.trace_file}.{self._role_name}.json"
            n = self.tracer.dump(path)
            log.info("wrote %d trace events to %s", n, path)
        # quiesce traffic sources before destroying the pool: outstanding
        # readers hold views into pool memory
        if self.pusher is not None:
            self.pusher.stop()
        if self.executor is not None and self.executor.merge_store is not None:
            log.info("merge store at stop: %s",
                     self.executor.merge_store.snapshot())
            self.executor.merge_store.stop()
        if self.executor is not None and self.executor.pushed_store is not None:
            log.info("pushed store at stop: %s",
                     self.executor.pushed_store.snapshot())
            self.executor.pushed_store.stop()
        if self.executor is not None and self.executor.tiering is not None:
            log.info("cold tier at stop: %s",
                     self.executor.tiering.snapshot())
            self.executor.tiering.stop()
        if self.executor is not None:
            if self.executor.suspect_events or self.executor.checksum_failures:
                log.warning("peer health at stop: %s (checksum failures: %d)",
                            self.executor.health_snapshot(),
                            self.executor.checksum_failures)
            self.executor.stop()
        if self.resolver is not None:
            self.resolver.stop()
        if self.block_server is not None:
            # second flush catches serves that landed after the trace dump
            # (in-memory instants only) and logs the final gauges
            log.info("native block server stats: %s",
                     self.block_server.trace_serve())
            self.block_server.stop()
        pool_stats = self.pool.stop()
        if pool_stats.get("bins"):
            log.info("buffer pool stats: %s", pool_stats)
        log.info("host paging over manager lifetime: %s", self._mem_stats.diff())
        if self.driver is not None:
            self.driver.stop()


class _PublishingWriter:
    """Writer wrapper that publishes the map output on successful close
    (RdmaWrapperShuffleWriter.scala:104-122)."""

    def __init__(self, inner: TpuShuffleWriter, endpoint: ExecutorEndpoint,
                 tracer=None, pusher=None):
        self._inner = inner
        self._endpoint = endpoint
        self._tracer = tracer or trace_mod.NULL
        self._pusher = pusher  # SegmentPusher | None (push-merge)

    def write_batch(self, keys, payload=None) -> None:
        self._inner.write_batch(keys, payload)

    def close(self, success: bool = True):
        with self._tracer.span("writer.commit", "write",
                               shuffle=self._inner.shuffle_id,
                               map=self._inner.map_id):
            result = self._inner.close(success)
        if result is None:
            return None
        token, partition_lengths = result
        if self._pusher is not None:
            # push-merge: queue the committed output's background push
            # BEFORE the publish can complete the map stage at the
            # driver — the finalize broadcast then provably trails this
            # submit, so targets' idle-grace wait sees the push coming
            self._pusher.submit(self._inner.shuffle_id,
                                self._inner.map_id, self._inner.fence,
                                partition_lengths)
        with self._tracer.span("writer.publish", "write",
                               shuffle=self._inner.shuffle_id,
                               map=self._inner.map_id):
            # the publish carries the attempt's fencing token: a stale
            # (zombie) attempt can't even get here — its commit already
            # raised StaleAttemptError — and the driver's fence check
            # rejects lateness the resolver couldn't see. With adaptive
            # planning the partition lengths (already in hand from the
            # commit) ride along so the driver's size histogram needs no
            # extra round trip.
            lengths = ([int(n) for n in partition_lengths]
                       if self._endpoint.conf.adaptive_plan else None)
            self._endpoint.publish_map_output(self._inner.shuffle_id,
                                              self._inner.map_id, token,
                                              fence=self._inner.fence,
                                              lengths=lengths)
        return token, partition_lengths

    @property
    def closed(self) -> bool:
        return self._inner.closed

    @property
    def fence(self) -> int:
        return self._inner.fence

    @property
    def metrics(self):
        out = {"bytes_written": self._inner.bytes_written,
               "records_written": self._inner.records_written}
        write_metrics = getattr(self._inner, "metrics", None)
        if write_metrics is not None:
            out["write"] = write_metrics.snapshot()
        return out

    @property
    def write_metrics(self):
        """The streaming writer's :class:`WriteMetrics` (scatter/spill/
        merge timing, spill count/bytes, peak buffered bytes)."""
        return self._inner.metrics
