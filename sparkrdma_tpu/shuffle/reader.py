"""Shuffle reader: drain the fetcher into record batches.

Re-design of ``scala/RdmaShuffleReader.scala``: builds the fetcher iterator,
decodes streams into records, and optionally aggregates / sorts the combined
output (:43-115 — deserialize, aggregate, ExternalSorter when keyOrdering).
Compression/encryption stream wrapping (:54-69) has no analogue: rows are
fixed-width binary already.
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel.endpoints import ExecutorEndpoint
from sparkrdma_tpu.shuffle.fetcher import ReadMetrics, ShuffleFetcher
from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver
from sparkrdma_tpu.shuffle.writer import decode_rows

Batch = Tuple[np.ndarray, np.ndarray]  # (keys u64[N], payload u8[N, W])


class TpuShuffleReader:
    """One reducer's reader over partitions [start, end)."""

    def __init__(self, endpoint: ExecutorEndpoint,
                 resolver: Optional[TpuShuffleBlockResolver],
                 conf: TpuShuffleConf, shuffle_id: int, num_maps: int,
                 start_partition: int, end_partition: int,
                 row_payload_bytes: int, reader_stats=None, tracer=None,
                 pool=None, map_range=None):
        self.row_payload_bytes = row_payload_bytes
        # adaptive reduce planning: a plan-SPLIT task reads its partition
        # from a [map_lo, map_hi) slice of the map space; None = all maps
        self.map_range = tuple(map_range) if map_range is not None else None
        self.fetcher = ShuffleFetcher(endpoint, resolver, conf, shuffle_id,
                                      num_maps, start_partition, end_partition,
                                      reader_stats=reader_stats, tracer=tracer,
                                      pool=pool, map_range=map_range)

    @property
    def metrics(self) -> ReadMetrics:
        return self.fetcher.metrics

    def read(self) -> Iterator[Batch]:
        """Record batches in arrival order (one per grouped fetch).

        Batches may be READ-ONLY zero-copy views (blocks that arrived as
        owned bytes decode without any copy); copy before mutating in
        place. ``read_all``/``read_sorted`` return fresh writable arrays.
        """
        self.fetcher.start()
        try:
            for result in self.fetcher:
                # len(), not truthiness: lease-backed results are numpy
                # views (multi-element truthiness raises). Lease-backed
                # bytes are materialized ONCE by the decode (the pool
                # lease releases immediately after); results whose bytes
                # the fetch already handed us outright decode zero-copy.
                try:
                    if len(result.data):
                        owned = (result.lease is None
                                 and isinstance(result.data,
                                                (bytes, bytearray)))
                        yield decode_rows(result.data,
                                          self.row_payload_bytes,
                                          copy=not owned)
                finally:
                    result.free()
        finally:
            # releases budget waiters + peer threads if the consumer stops
            # early (GeneratorExit) or a fetch failed
            self.fetcher.close()

    def read_all(self) -> Batch:
        """Materialize every record of the partition range.

        With ``warm_read_cache`` on, the materialized range is kept in
        the worker-process cache keyed by the location EPOCH it was read
        under (shuffle/dist_cache.py): iteration N+1 over the unchanged
        shuffle serves it locally — zero RPCs, zero bytes moved — and an
        epoch bump (re-execution, executor loss) invalidates. Cached
        round trips copy on both sides so callers may mutate freely.
        """
        f = self.fetcher
        warm = f.conf.warm_read_cache
        if warm:
            from sparkrdma_tpu.shuffle import dist_cache

            known = f.endpoint.location_plane.known_epoch(f.shuffle_id)
            if known is not None and known > 0:
                cached = dist_cache.get_range(f.shuffle_id, known,
                                              f.start_partition,
                                              f.end_partition,
                                              map_range=self.map_range)
                if cached is not None:
                    f.metrics.warm_range_hits += 1
                    return cached[0].copy(), cached[1].copy()
        keys_parts, payload_parts = [], []
        for keys, payload in self.read():
            keys_parts.append(keys)
            payload_parts.append(payload)
        if not keys_parts:
            keys = np.zeros(0, dtype=np.uint64)
            payload = np.zeros((0, self.row_payload_bytes), dtype=np.uint8)
        else:
            keys = np.concatenate(keys_parts)
            payload = np.concatenate(payload_parts)
        if warm and f.epoch > 0:
            from sparkrdma_tpu.shuffle import dist_cache

            dist_cache.put_range(f.shuffle_id, f.epoch, f.start_partition,
                                 f.end_partition, keys.copy(),
                                 payload.copy(), map_range=self.map_range)
        return keys, payload

    def read_sorted(self) -> Batch:
        """Full sort by key (the ExternalSorter role,
        scala/RdmaShuffleReader.scala:100-114)."""
        keys, payload = self.read_all()
        order = np.argsort(keys, kind="stable")
        return keys[order], payload[order]

    def read_sorted_spilled(self, memory_budget_bytes: int = 64 << 20,
                            spill_dir: Optional[str] = None,
                            ) -> Iterator[Batch]:
        """Globally key-sorted batches with a bounded resident set: fetched
        batches spill as sorted runs once ``memory_budget_bytes`` is
        buffered, then stream back through a k-way disk merge — the
        ExternalSorter delegation of scala/RdmaShuffleReader.scala:100-114
        for reduces that exceed host memory (``read_sorted`` materializes
        everything)."""
        from sparkrdma_tpu.shuffle.external import ExternalMerger

        with ExternalMerger(self.row_payload_bytes, spill_dir=spill_dir,
                            memory_budget_bytes=memory_budget_bytes) as m:
            for keys, payload in self.read():
                m.add_batch(keys, payload)
            yield from m.sorted_batches()

    def read_aggregated(self, combine: Callable[[np.ndarray, np.ndarray], Batch]
                        ) -> Batch:
        """Aggregate with a vectorized combiner (sorted-run reduction).
        Combiners never see zero rows — the same contract the writer's
        map-side combine keeps (an empty partition short-circuits)."""
        keys, payload = self.read_sorted()
        if not len(keys):
            return keys, payload
        return combine(keys, payload)

    def read_to_device(self, pool, device=None):
        """Stage the partition range into one pool buffer, then one
        host->device transfer. Returns ``(keys: u32[N, 2], payload:
        u8[N, W])`` device arrays — keys as (lo, hi) uint32 words, since
        uint64 silently narrows under jit without x64.

        This is the host->HBM on-ramp the staging pool exists for
        (RdmaMappedFile's mmap+register in the reference becomes: gather
        fetched bytes into page-aligned host staging, single DMA up).
        """
        import jax

        self.fetcher.start()
        chunks = []
        try:
            total = 0
            for result in self.fetcher:
                if len(result.data):
                    # the result (and its pool lease, if any) is held
                    # until the staging copy below, then freed
                    chunks.append(result)
                    total += len(result.data)
                else:
                    result.free()
            row_bytes = 8 + self.row_payload_bytes
            if total == 0:
                keys = jax.device_put(np.zeros((0, 2), dtype=np.uint32), device)
                payload = jax.device_put(
                    np.zeros((0, self.row_payload_bytes), dtype=np.uint8), device)
                return keys, payload
            # wire->device donation: when every chunk already lives in
            # lease memory (the native fetch engine landed the response
            # payloads there) and tiles whole rows, the lease views go to
            # the device directly — the staging gather below would be the
            # one copy the zero-copy receive path exists to delete. The
            # leases stay referenced until the transfer completes (the
            # finally block frees them after block_until_ready).
            if (self.fetcher.conf.native_fetch
                    and all(r.lease is not None for r in chunks)
                    and all(len(r.data) % row_bytes == 0 for r in chunks)):
                import jax.numpy as jnp

                parts = [jax.device_put(r.data, device) for r in chunks]
                flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
                rows_d = flat.reshape(-1, row_bytes)
                payload_dev = rows_d[:, 8:]
                keys_dev = jax.lax.bitcast_convert_type(
                    rows_d[:, :8].reshape(-1, 2, 4), jnp.uint32)
                jax.block_until_ready((keys_dev, payload_dev))
                return keys_dev, payload_dev
            with pool.get(total, tenant=self.fetcher.tenant) as buf:
                pos = 0
                for r in chunks:
                    n = len(r.data)
                    buf.view[pos:pos + n] = np.frombuffer(r.data,
                                                          dtype=np.uint8)
                    pos += n
                    r.free()
                rows = buf.view[:total].reshape(-1, row_bytes)
                # device_put straight from the staging buffer's key/payload
                # views — the staging gather IS the one materialization;
                # the old host-side .copy() pair was a redundant hop
                try:
                    keys_host = rows[:, :8].view(np.uint32)
                except ValueError:  # numpy < 1.23: strided view unsupported
                    keys_host = rows[:, :8].copy().view(np.uint32)
                keys_dev = jax.device_put(keys_host, device)
                payload_dev = jax.device_put(rows[:, 8:], device)
                jax.block_until_ready((keys_dev, payload_dev))
            return keys_dev, payload_dev
        finally:
            # free() is idempotent: chunks already freed by the staging
            # copy are no-ops; an exception mid-fetch frees the rest
            for r in chunks:
                r.free()
            self.fetcher.close()
