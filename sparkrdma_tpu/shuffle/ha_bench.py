"""Driver-HA microbench: what one primary crash costs the job.

The A/B the replicated control plane exists for (shuffle/ha.py): a
lease-armed primary with a warm standby shadowing its op log CRASHES
after the map stage has fully replicated, and the bench measures

* ``failover_downtime_ms`` — crash to the FIRST successful publish
  against the promoted standby: the whole control-plane outage as an
  executor sees it (lease expiry + takeover + TakeoverMsg re-point),
  probed by an idempotent republish loop riding the DriverClient retry
  envelope.
* ``replay_ops`` — the standby's op-log tail length at the crash: the
  replay bill the promotion paid (the ``oplog_lag_entries`` gauge).

Gates: the post-failover reduce is byte-identical to the ground truth
and re-executes ZERO maps — the outputs live on the executors, so
losing the driver may cost a wait, never a recompute (bench.py
secondary, scripts/run_ha_bench.sh).
"""

from __future__ import annotations

import threading
import time
from typing import Dict

import numpy as np

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.driver_client import DriverUnreachableError
from sparkrdma_tpu.shuffle.ha import DriverStandby, InMemoryLeaseStore
from sparkrdma_tpu.shuffle.manager import (PartitionerSpec, ShuffleHandle,
                                           TpuShuffleManager)
from sparkrdma_tpu.shuffle.map_output import DriverTable
from sparkrdma_tpu.shuffle.recovery import run_map_stage

NUM_EXECUTORS = 2
NUM_MAPS = 4
NUM_PARTITIONS = 4
ROWS_PER_MAP = 500
PROBE_SID = 99  # the probe shuffle the downtime loop republishes into


def _conf(lease_ms: int) -> TpuShuffleConf:
    return TpuShuffleConf(connect_timeout_ms=2000,
                          max_connection_attempts=1,
                          retry_backoff_base_ms=10,
                          retry_backoff_cap_ms=60,
                          pre_warm_connections=False,
                          use_cpp_runtime=False,
                          ha_standbys=1, driver_lease_ms=lease_ms,
                          request_deadline_ms=20_000)


def _expected(seed: int) -> np.ndarray:
    return np.sort(np.concatenate(
        [np.random.default_rng(seed * 1_000_003 + m)
         .integers(0, 50_000, ROWS_PER_MAP)
         for m in range(NUM_MAPS)]).astype(np.uint64))


def run_ha_microbench(tmpdir: str, seed: int = 0,
                      lease_ms: int = 500) -> Dict:
    conf = _conf(lease_ms)
    primary = TpuShuffleManager(conf, is_driver=True,
                                lease_store=InMemoryLeaseStore(),
                                lease_holder="primary")
    standby = DriverStandby(conf, primary.driver.lease_store, "standby",
                            primary_addr=primary.driver.address).start()
    execs = [TpuShuffleManager(conf, driver_addr=primary.driver_addr,
                               executor_id=str(i),
                               spill_dir=f"{tmpdir}/e{i}")
             for i in range(NUM_EXECUTORS)]
    counter: Dict[int, int] = {}
    lock = threading.Lock()
    try:
        for ex in execs:
            ex.executor.wait_for_members(NUM_EXECUTORS)
        handle = ShuffleHandle(7, NUM_MAPS, NUM_PARTITIONS, 0,
                               PartitionerSpec("modulo"))
        primary.driver.register_shuffle(7, num_maps=NUM_MAPS,
                                        num_partitions=NUM_PARTITIONS)
        # the probe shuffle: one slot the downtime loop republishes
        # into — the fence makes every duplicate a no-op, so the probe
        # never perturbs the state it is measuring
        primary.driver.register_shuffle(PROBE_SID, num_maps=1,
                                        num_partitions=1)
        probe = M.PublishMsg(PROBE_SID, 0,
                             DriverTable.pack_entry(1, 0), fence=1)
        execs[0].executor.driver.send(probe)

        def map_fn(writer, map_id):
            with lock:
                counter[map_id] = counter.get(map_id, 0) + 1
            rng = np.random.default_rng(seed * 1_000_003 + map_id)
            writer.write_batch(
                rng.integers(0, 50_000, ROWS_PER_MAP).astype(np.uint64))

        run_map_stage(execs, handle, map_fn)
        table, _ = execs[0].executor.get_driver_table_v(
            7, expect_published=NUM_MAPS, timeout=10)
        assert table.num_published == NUM_MAPS

        # wait for the async replication stream to drain: nothing
        # mutates driver state now, so a stable ingest seq means a
        # crash at any later instant loses no op
        stable_since, last_seen = time.monotonic(), standby._last
        deadline = time.monotonic() + 15
        while time.monotonic() - stable_since < 0.4:
            if time.monotonic() > deadline:
                raise TimeoutError("standby never caught up")
            time.sleep(0.03)
            if standby._last != last_seen:
                stable_since, last_seen = time.monotonic(), standby._last
        replay_ops = standby.lag()

        # CRASH: server down, lease renewals stop — the in-process
        # stand-in for SIGKILL (the subprocess kill -9 variant is the
        # chaos acceptance scenario)
        t_kill = time.monotonic()
        primary.driver.stop()

        # downtime probe: idempotent republish until one lands on the
        # PROMOTED primary — dials of the dead one fail fast, the
        # TakeoverMsg re-point makes the first post-takeover attempt
        # succeed
        client = execs[0].executor.driver
        while True:
            if time.monotonic() - t_kill > 30:
                raise TimeoutError("no successful publish after failover")
            try:
                client.send(probe, deadline_s=0.2)
                if client.incarnation > 0:
                    break
            except DriverUnreachableError:
                pass
        downtime_ms = (time.monotonic() - t_kill) * 1000.0

        # the acceptance gates: byte-identical reduce, zero recomputes
        reader = execs[1].get_reader(handle, 0, NUM_PARTITIONS)
        keys, _ = reader.read_all()
        identical = bool(np.array_equal(np.sort(keys), _expected(seed)))
        reexec = sum(counter.values()) - NUM_MAPS
        new_primary = standby.endpoint
        return {
            "failover_downtime_ms": round(downtime_ms, 3),
            "lease_ms": lease_ms,
            "replay_ops": replay_ops,
            "identical": identical,
            "reexec": reexec,
            "incarnation": new_primary.incarnation if new_primary else 0,
            "seed": seed,
        }
    finally:
        for ex in execs:
            ex.stop()
        standby.stop()
        primary.stop()
