"""TPC-DS-shaped multi-join: the q64/q95-class shuffle-heavy SQL workload.

BASELINE.md config #4's missing half: where ``models/join.py`` is one
equi-join, real TPC-DS plans chain shuffles — q64/q95 join a skewed fact
table against several dimension tables and aggregate (the reference's
published workloads are shuffle-bound Spark jobs of exactly this class,
/root/reference/README.md:7-31). This model runs the canonical star shape

    fact  ⋈(key1) dim1  ⋈(key2) dim2  -> GROUP BY g -> (count, sum)

as FOUR chained ragged exchanges inside ONE jitted SPMD step (fact and
dim1 by hash(key1); the join-1 survivors and dim2 by hash(key2); the
joined rows by group owner), stressing multiple concurrent shuffles per
job the way a TPC-DS stage graph does. Fact keys are Zipf-skewed
(realistic key popularity); dimension keys are unique with partial
coverage, so both joins are selective inner joins implemented as static-
shape sorted lookups (no data-dependent output sizes — validity masks
carry selectivity).

The same logical plan is also expressed as a DAG-engine job
(``build_tpcds_job``) driving the drop-in SPI — source stages for the
three tables, two join MapStages, one aggregating ResultStage — so the
workload exercises both the on-mesh collective path and the host/DCN
engine path against one oracle (``numpy_tpcds``).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.utils.compat import shard_map

from sparkrdma_tpu.ops.partition import hash_partition
from sparkrdma_tpu.parallel.exchange import resolve_impl, shuffle_shard

PAD = np.uint32(0xFFFFFFFF)


@dataclass(frozen=True)
class TpcdsConfig:
    fact_rows_per_device: int
    dim1_size: int              # global; keys in [0, dim1_size)
    dim2_size: int
    num_groups: int = 256
    zipf_a: float = 1.2         # fact key1 skew exponent
    out_factor: int = 3         # receive headroom for the skewed exchange
    dim_coverage_mod: int = 10  # dim keeps keys with k % mod != 0 (90%)


def _mix_group(key1, key2, num_groups):
    """Group key from both join keys (u32 wrap, same in numpy and jnp)."""
    return (key1 * 31 + key2) % num_groups


def generate_star(cfg: TpcdsConfig, num_devices: int, seed: int = 0,
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(fact u32[D*F, 3], dim1 u32[M1', 2], dim2 u32[M2', 2]).

    fact columns: (key1 zipf-skewed, key2 uniform, measure). Dim tables
    have unique keys with ``(mod-1)/mod`` coverage; attrs are small so
    i32 per-group partial sums cannot wrap at bench sizes.
    """
    rng = np.random.default_rng(seed)
    n = num_devices * cfg.fact_rows_per_device
    key1 = (rng.zipf(cfg.zipf_a, size=n) - 1) % cfg.dim1_size
    key2 = rng.integers(0, cfg.dim2_size, size=n)
    measure = rng.integers(0, 97, size=n)
    fact = np.stack([key1, key2, measure], axis=1).astype(np.uint32)

    def dim(size, attr_mod, salt):
        keys = np.arange(size, dtype=np.uint32)
        keys = keys[keys % cfg.dim_coverage_mod != 0]
        attr = ((keys * 2654435761 + salt) % attr_mod).astype(np.uint32)
        return np.stack([keys, attr], axis=1)

    return fact, dim(cfg.dim1_size, 89, 7), dim(cfg.dim2_size, 83, 13)


def pad_to_devices(rows: np.ndarray, num_devices: int) -> np.ndarray:
    """Pad (with PAD-key rows) so the leading axis splits evenly; at least
    one row per device so an empty table still exchanges/probes cleanly
    (static shapes: a zero-capacity buffer can't be gathered from)."""
    per = max(1, -(-len(rows) // num_devices))
    out = np.full((per * num_devices, rows.shape[1]), PAD, rows.dtype)
    out[:len(rows)] = rows
    return out


def make_tpcds_step(mesh: Mesh, axis_name: str, cfg: TpcdsConfig,
                    impl: str = "auto"):
    """Jitted star-join + aggregate over ``mesh``.

    Inputs sharded on the leading axis: ``fact u32[D*F, 3]``,
    ``dim1 u32[D*M1, 2]``, ``dim2 u32[D*M2, 2]`` (PAD-key rows ignored).
    Returns ``(counts i32[D, G], sums i32[D, G], overflowed bool[D])`` —
    device d's rows hold exact totals for the groups it owns
    (``g % D == d``) and zeros elsewhere, so a plain host sum over
    devices is the full GROUP BY result.
    """
    n = mesh.shape[axis_name]
    impl = resolve_impl(mesh, impl, axis_name)
    spec = P(axis_name)
    G = cfg.num_groups
    pad = jnp.uint32(PAD)

    def exchange(rows, dest, capacity):
        output = jnp.zeros((capacity, rows.shape[1]), rows.dtype)
        received, recv_counts, _, overflowed = shuffle_shard(
            rows, dest, axis_name, n, output=output, impl=impl)
        total = recv_counts.sum()
        valid = jnp.arange(capacity, dtype=jnp.int32) < total
        return received, valid, overflowed

    def dim_lookup(dim_rows, dim_valid, query_keys):
        """Unique-key join: sorted dim + one searchsorted per probe."""
        dkeys = jnp.where(dim_valid, dim_rows[:, 0], pad)
        order = jnp.argsort(dkeys, stable=True)
        dkeys_s = jnp.take(dkeys, order)
        dattr_s = jnp.take(dim_rows[:, 1], order)
        idx = jnp.clip(jnp.searchsorted(dkeys_s, query_keys),
                       0, dkeys_s.shape[0] - 1)
        found = (jnp.take(dkeys_s, idx) == query_keys) & (query_keys != pad)
        return jnp.take(dattr_s, idx), found

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec, spec, spec),
                       out_specs=(spec, spec, spec))
    def step(fact, dim1, dim2):
        F = fact.shape[0]

        def route(rows, key_col):
            keys = rows[:, key_col]
            return jnp.where(keys != pad,
                             hash_partition(keys, n), -1)

        # shuffles 1+2: fact and dim1 to hash(key1) owners
        d1, d1_valid, of1 = exchange(dim1, route(dim1, 0),
                                     dim1.shape[0] * cfg.out_factor)
        f1, f1_valid, of2 = exchange(fact, route(fact, 0),
                                     F * cfg.out_factor)
        attr1, found1 = dim_lookup(d1, d1_valid, f1[:, 0])
        live1 = f1_valid & found1
        value1 = (f1[:, 2] * attr1) % jnp.uint32(10007)
        # join-1 survivors: (key2, key1, value1), PAD-keyed when dead
        mid = jnp.stack([jnp.where(live1, f1[:, 1], pad),
                         f1[:, 0], value1], axis=1)

        # shuffles 3+4: survivors and dim2 to hash(key2) owners
        d2, d2_valid, of3 = exchange(dim2, route(dim2, 0),
                                     dim2.shape[0] * cfg.out_factor)
        m2, m2_valid, of4 = exchange(mid, route(mid, 0),
                                     F * cfg.out_factor)
        attr2, found2 = dim_lookup(d2, d2_valid, m2[:, 0])
        live2 = m2_valid & found2
        value = (m2[:, 2] + attr2) % jnp.uint32(10007)
        group = _mix_group(m2[:, 1], m2[:, 0], jnp.uint32(G))

        # shuffle 5: joined rows to their group's owner (g % D)
        rows3 = jnp.stack([jnp.where(live2, group, pad), value], axis=1)
        dest3 = jnp.where(live2, (group % n).astype(jnp.int32), -1)
        agg_cap = F * cfg.out_factor
        out3 = jnp.zeros((agg_cap, 2), rows3.dtype)
        recv3, rc3, _, of5 = shuffle_shard(rows3, dest3, axis_name, n,
                                           output=out3, impl=impl)
        total3 = rc3.sum()
        v3 = jnp.arange(agg_cap, dtype=jnp.int32) < total3
        g3 = jnp.where(v3 & (recv3[:, 0] != pad), recv3[:, 0], jnp.uint32(G))
        counts = jnp.bincount(g3, length=G + 1)[:G].astype(jnp.int32)
        sums = jnp.bincount(
            g3, weights=jnp.where(g3 < G, recv3[:, 1], 0).astype(jnp.int32),
            length=G + 1)[:G].astype(jnp.int32)
        overflowed = of1 | of2 | of3 | of4 | of5
        return counts[None], sums[None], overflowed[None]

    return step


def run_tpcds(mesh: Mesh, cfg: TpcdsConfig, axis_name: str = "shuffle",
              seed: int = 0, impl: str = "auto",
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Host driver: returns exact global (counts[G], sums[G])."""
    n = mesh.shape[axis_name]
    fact, dim1, dim2 = generate_star(cfg, n, seed)
    step = make_tpcds_step(mesh, axis_name, cfg, impl)
    shard = NamedSharding(mesh, P(axis_name))
    counts, sums, overflowed = jax.block_until_ready(step(
        jax.device_put(fact, shard),
        jax.device_put(pad_to_devices(dim1, n), shard),
        jax.device_put(pad_to_devices(dim2, n), shard)))
    if np.asarray(overflowed).any():
        raise OverflowError("tpcds shuffle overflowed receive headroom; "
                            "raise TpcdsConfig.out_factor")
    return (np.asarray(counts).sum(axis=0).astype(np.int64),
            np.asarray(sums).sum(axis=0).astype(np.int64))


def numpy_tpcds(fact: np.ndarray, dim1: np.ndarray, dim2: np.ndarray,
                num_groups: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host oracle: exact star-join + GROUP BY with the same arithmetic."""
    a1 = dict(zip(dim1[:, 0].tolist(), dim1[:, 1].tolist()))
    a2 = dict(zip(dim2[:, 0].tolist(), dim2[:, 1].tolist()))
    counts = np.zeros(num_groups, np.int64)
    sums = np.zeros(num_groups, np.int64)
    for k1, k2, m in fact.tolist():
        v1 = a1.get(k1)
        v2 = a2.get(k2)
        if v1 is None or v2 is None:
            continue
        value = (np.uint32(m) * np.uint32(v1) % np.uint32(10007)
                 + np.uint32(v2)) % np.uint32(10007)
        g = int(_mix_group(np.uint32(k1), np.uint32(k2),
                           np.uint32(num_groups)))
        counts[g] += 1
        sums[g] += int(value)
    return counts, sums


# -- the same plan through the DAG engine (drop-in SPI path) --------------

def build_tpcds_job(cfg: TpcdsConfig, num_maps: int, num_partitions: int,
                    seed: int = 0):
    """The star query as a stage DAG for ``engine.DAGEngine.run``.

    Returns ``(result_stage, finish)`` where ``finish(results)`` folds the
    per-partition dicts into global ``(counts[G], sums[G])``. Stage graph:
    three sources (fact/dim1/dim2, modulo-partitioned on their join key),
    join-1 (reads fact+dim1, writes by key2), join-2 (reads join-1+dim2,
    writes by group), aggregate ResultStage — five shuffles, the SPI
    sequence a TPC-DS stage graph drives through Spark.
    """
    from sparkrdma_tpu.engine import MapStage, ResultStage
    from sparkrdma_tpu.shuffle.manager import PartitionerSpec
    from sparkrdma_tpu.shuffle.spark_compat import ShuffleDependency

    G = cfg.num_groups
    fact_all, dim1_all, dim2_all = generate_star(cfg, 1, seed)

    def dep(payload_bytes):
        return ShuffleDependency(num_partitions, PartitionerSpec("modulo"),
                                 row_payload_bytes=payload_bytes)

    def rows_of(table, task):  # deterministic striping across map tasks
        return table[task::num_maps]

    def src(table, key_col, payload_cols):
        width = 4 * len(payload_cols)

        def fn(ctx, writer, task):
            rows = rows_of(table, task)
            payload = np.ascontiguousarray(
                rows[:, payload_cols], dtype="<u4").view(np.uint8)
            writer.write((rows[:, key_col].astype(np.uint64),
                          payload.reshape(len(rows), width)))
        return fn

    fact_st = MapStage(num_maps, dep(8), src(fact_all, 0, [1, 2]))
    dim1_st = MapStage(num_maps, dep(4), src(dim1_all, 0, [1]))
    dim2_st = MapStage(num_maps, dep(4), src(dim2_all, 0, [1]))

    def read_u32(ctx, parent):  # -> (keys u64[N], cols u32[N, W])
        ks, vs = [], []
        for keys, payload in ctx.read(parent).readBatches():
            ks.append(keys)
            vs.append(np.ascontiguousarray(payload).view("<u4")
                      .reshape(len(keys), -1))
        if not ks:
            return np.zeros(0, np.uint64), np.zeros((0, 1), np.uint32)
        return np.concatenate(ks), np.concatenate(vs)

    def np_lookup(dkeys, dattr, probes):
        """Vectorized unique-key join: (attr[N] u32, found[N] bool)."""
        if len(dkeys) == 0:
            return (np.zeros(len(probes), np.uint32),
                    np.zeros(len(probes), bool))
        order = np.argsort(dkeys)
        ks, at = dkeys[order], dattr[order]
        idx = np.clip(np.searchsorted(ks, probes), 0, len(ks) - 1)
        return at[idx].astype(np.uint32), ks[idx] == probes

    def join1_fn(ctx, writer, task):
        fkeys, fcols = read_u32(ctx, 0)   # key1 -> (key2, measure)
        dkeys, dcols = read_u32(ctx, 1)   # key1 -> (attr1,)
        attr, found = np_lookup(dkeys, dcols[:, 0], fkeys)
        v1 = (fcols[:, 1].astype(np.uint32) * attr) % np.uint32(10007)
        keep = found
        payload = np.stack([fkeys.astype(np.uint32)[keep], v1[keep]],
                           axis=1)  # (key1, value1)
        writer.write((fcols[:, 0][keep].astype(np.uint64),
                      np.ascontiguousarray(payload, "<u4").view(np.uint8)
                      .reshape(int(keep.sum()), 8)))
        del task

    join1_st = MapStage(num_partitions, dep(8), join1_fn,
                        parents=[fact_st, dim1_st])

    def join2_fn(ctx, writer, task):
        mkeys, mcols = read_u32(ctx, 0)   # key2 -> (key1, value1)
        dkeys, dcols = read_u32(ctx, 1)   # key2 -> (attr2,)
        attr, found = np_lookup(dkeys, dcols[:, 0], mkeys)
        value = (mcols[:, 1].astype(np.uint32) + attr) % np.uint32(10007)
        group = _mix_group(mcols[:, 0].astype(np.uint32),
                           mkeys.astype(np.uint32), np.uint32(G))
        keep = found
        writer.write((group[keep].astype(np.uint64),
                      np.ascontiguousarray(value[keep], "<u4")
                      .view(np.uint8).reshape(int(keep.sum()), 4)))
        del task

    join2_st = MapStage(num_partitions, dep(4), join2_fn,
                        parents=[join1_st, dim2_st])

    def agg_fn(ctx, task):
        counts = np.zeros(G, np.int64)
        sums = np.zeros(G, np.int64)
        for keys, payload in ctx.read(0).readBatches():
            vals = np.ascontiguousarray(payload).view("<u4").ravel()
            np.add.at(counts, keys.astype(np.int64), 1)
            np.add.at(sums, keys.astype(np.int64), vals.astype(np.int64))
        del task
        return counts, sums

    result = ResultStage(num_partitions, agg_fn, parents=[join2_st])

    def finish(results):
        counts = sum(c for c, _ in results)
        sums = sum(s for _, s in results)
        return counts, sums

    return result, finish
