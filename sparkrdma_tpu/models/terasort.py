"""TeraSort: the flagship workload.

The reference's headline benchmark is TeraSort-320GB, 2.63× faster than
Spark's TCP shuffle on InfiniBand FDR (README.md:11-17; BASELINE.md). It is
the canonical shuffle stress: every byte crosses the network exactly once.

TPU-native design — the whole map/shuffle/reduce cycle is ONE jitted SPMD
step per round:

1. **partition**: analytic or sampled range splitters; ``range_partition``
   assigns each row a destination device (VPU compares, no host loop).
2. **exchange**: ``shuffle_shard`` — size pre-exchange + ragged all-to-all
   over ICI (see ``parallel.exchange``). Rows are ``[N, 1+P]`` uint32
   matrices (key word + P payload words), so the collective moves one dense
   buffer.
3. **local sort**: co-sort received rows by key (padded rows sort to the
   end via the key-max sentinel).

The result is globally sorted by (device order, local order) — the same
contract as TeraSort's output files. A numpy reference pipeline provides the
CPU baseline (the "stock local sort-shuffle" stand-in, BASELINE.json
config #1).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class TeraSortConfig:
    rows_per_device: int
    payload_words: int = 24  # 4B key word + 24*4B payload ≈ the classic 100B row
    out_factor: int = 2      # receive headroom (uniform keys -> mild skew)
    # How payload follows its key through a local sort:
    #   "gather"    — sort (key, iota) then ONE row gather. Measured on
    #                 v5e: the gather runs at ~1 word/cycle (28.8 ns/row
    #                 at width 25, ~3.4x the 8.5 ns/row key sort) — it is
    #                 the step's bottleneck.
    #   "multisort" — every payload column rides the sort network as an
    #                 extra rank-1 lax.sort operand: no gather, but the
    #                 XLA:TPU compile cost grows ~16s per operand and a
    #                 26-operand network never finished a 900s cold
    #                 compile — only usable behind a warm compilation
    #                 cache.
    #   "colsort"   — ONE variadic 2D sort along axis 0 of
    #                 (broadcast keys [N,W], rows [N,W]) with
    #                 is_stable=True: per-column comparators see identical
    #                 keys, so the stable sort applies the SAME permutation
    #                 to every lane and payload never leaves the sort
    #                 network. Carries the key column W times (2x the
    #                 multisort bytes) but compiles like a 2-operand sort
    #                 and runs lane-parallel.
    # Which wins is hardware-dependent (gather is latency-bound, the
    # sorts bandwidth-bound); bench A/Bs via BENCH_SORT_MODE.
    sort_mode: str = "gather"

    @property
    def row_bytes(self) -> int:
        return 4 * (1 + self.payload_words)


def make_terasort_step(mesh: Mesh, axis_name: str, cfg: TeraSortConfig,
                       impl: str = "auto"):
    """Build the jitted one-round TeraSort step over ``mesh``.

    Takes ``rows: u32[D*rows_per_device, 1+P]`` sharded on the leading axis
    (column 0 is the key); returns ``(sorted_rows, recv_counts[D, D],
    overflowed[D])`` with rows per device sorted by key, padding
    (key=0xFFFFFFFF) at the end. ``overflowed[d]`` flags that device d's
    receive buffer was too small for the skew (results there are truncated
    and must not be trusted — raise ``out_factor`` or chunk the round).

    The step IS the device plane's fused op (``parallel.device_plane.
    make_fused_step``) in its range-partition mode: TeraSort's uniform
    u32 key-range split makes ONE key sort double as the destination
    grouping; the generic op adds the caller-computed-destination mode
    the mesh shuffle service rides.
    """
    from sparkrdma_tpu.parallel.device_plane import make_fused_step

    return make_fused_step(mesh, axis_name, 1 + cfg.payload_words,
                           out_factor=cfg.out_factor, impl=impl,
                           sort_mode=cfg.sort_mode, key_words=1,
                           partition="range")


def generate_rows(cfg: TeraSortConfig, num_devices: int,
                  seed: int = 0) -> np.ndarray:
    """Uniform random TeraSort input: u32 keys + incompressible payload."""
    rng = np.random.default_rng(seed)
    n = num_devices * cfg.rows_per_device
    rows = rng.integers(0, 2**32, size=(n, 1 + cfg.payload_words),
                        dtype=np.uint32)
    return rows


def numpy_terasort(rows: np.ndarray, num_partitions: int) -> np.ndarray:
    """CPU baseline: the identical partition/shuffle/sort pipeline in numpy
    (the single-host stock sort-shuffle stand-in, BASELINE.json config #1)."""
    keys = rows[:, 0]
    edges = np.array([(i * (1 << 32)) // num_partitions
                      for i in range(1, num_partitions)], dtype=np.uint64)
    dest = np.searchsorted(edges, keys.astype(np.uint64), side="right")
    # "shuffle": group rows by destination partition (the data movement)
    order = np.argsort(dest, kind="stable")
    grouped = rows[order]
    counts = np.bincount(dest, minlength=num_partitions)
    # per-partition local sort
    out = np.empty_like(grouped)
    start = 0
    for c in counts:
        seg = grouped[start:start + c]
        out[start:start + c] = seg[np.argsort(seg[:, 0], kind="stable")]
        start += c
    return out


def run_terasort(mesh: Mesh, cfg: TeraSortConfig, axis_name: str = "shuffle",
                 impl: str = "auto", seed: int = 0,
                 rows: Optional[np.ndarray] = None,
                 ) -> Tuple[np.ndarray, np.ndarray, float]:
    """Host driver: generate, run one jitted round, return
    (sorted_rows_by_device, counts, step_seconds). Compile excluded."""
    n = mesh.shape[axis_name]
    if rows is None:
        rows = generate_rows(cfg, n, seed)
    step = make_terasort_step(mesh, axis_name, cfg, impl)
    sharding = NamedSharding(mesh, P(axis_name))
    rows_d = jax.device_put(rows, sharding)
    # compile + warm
    out, counts, overflowed = jax.block_until_ready(step(rows_d))
    t0 = time.perf_counter()
    out, counts, overflowed = jax.block_until_ready(step(rows_d))
    dt = time.perf_counter() - t0
    if np.asarray(overflowed).any():
        raise OverflowError(
            "receive buffer overflow: key skew exceeds out_factor headroom "
            f"(devices {np.nonzero(np.asarray(overflowed).ravel())[0].tolist()}); "
            "raise TeraSortConfig.out_factor or chunk the round")
    return np.asarray(out), np.asarray(counts), dt


def run_terasort_streamed(mesh: Mesh, cfg: TeraSortConfig, rows: np.ndarray,
                          axis_name: str = "shuffle", impl: str = "auto",
                          pipeline_rounds: bool = True,
                          phase_times: Optional[dict] = None,
                          ) -> Tuple[list, int]:
    """TeraSort a dataset LARGER than one round's device capacity.

    The 320 GB-class configuration (BASELINE.md config #2): per-device HBM
    holds only a fraction of the data, so the job runs as R rounds of the
    jitted partition/exchange/sort step — each round bounded to
    ``rows_per_device`` rows per device — and each device merges its R
    key-sorted runs host-side. Per-round memory is static; total data is
    not (the chunked-transfer discipline of the reference's grouped
    fetches, scala/RdmaShuffleFetcherIterator.scala:240-276, applied to
    the whole job).

    ``pipeline_rounds`` (default) double-buffers: round r+1's staging +
    device step overlap round r's host-side collection, at the cost of up
    to TWO rounds of device footprint resident at once. Pass False for
    the strict one-round footprint when a round is sized near HBM.

    ``phase_times``, when a dict is passed, is filled with wall seconds per
    phase — ``stage_s`` (host chunk prep + device_put + async dispatch),
    ``collect_s`` (blocking device wait + host-side run splitting) and
    ``merge_s`` (final per-device tournament merge) — the per-phase view
    BASELINE config #2 rehearsals report (with pipelining on, stage and
    collect overlap, so their sum can exceed end-to-end wall time).

    Returns ``(per_device_sorted_rows: [D] list of u32[*, 1+P], rounds)``.
    """
    n = mesh.shape[axis_name]
    if len(rows) == 0:
        return [np.zeros((0, rows.shape[1]), rows.dtype)
                for _ in range(n)], 0
    per_round = n * cfg.rows_per_device
    num_rounds = -(-len(rows) // per_round)
    step = make_terasort_step(mesh, axis_name, cfg, impl)
    sharding = NamedSharding(mesh, P(axis_name))
    # Tail-round padding: pad j is addressed to device j % n with that
    # device's range-maximum key, spreading the extra receive load evenly
    # (all-max-key padding would pile onto the last device and overflow its
    # headroom on perfectly valid input). Pads are appended LAST, so the
    # stable sort puts each device's pads at the very end of its run; the
    # strip is an exact per-device row count.
    range_max = np.array([((d + 1) << 32) // n - 1 for d in range(n)],
                         dtype=np.uint32)

    # Tail rounds reuse the SAME full-size step (one compile, static round
    # memory — the function's whole point): the tail is padded up to a full
    # round with the spread pads. With pads spread evenly, a device receives
    # at most ~rows_per_device real rows (uniform keys) + ~rows_per_device
    # pads, which fits the out_factor>=2 receive budget; genuine key skew is
    # caught by the overflow flag like any other round.
    if n > 1 and cfg.out_factor < 2 and len(rows) % per_round:
        raise ValueError("streamed terasort with a partial tail round needs "
                         "out_factor >= 2 (pad headroom)")

    runs: list = [[] for _ in range(n)]
    times = {"stage_s": 0.0, "collect_s": 0.0, "merge_s": 0.0}

    def dispatch(r: int):
        """Stage + launch round r; returns (pads_for, async device results)."""
        t0 = time.perf_counter()
        chunk = rows[r * per_round:(r + 1) * per_round]
        pads_for = np.zeros(n, dtype=np.int64)
        tail_pad = per_round - len(chunk)
        if tail_pad:
            pad = np.zeros((tail_pad, rows.shape[1]), rows.dtype)
            dests = np.arange(tail_pad) % n
            pad[:, 0] = range_max[dests]
            np.add.at(pads_for, dests, 1)
            chunk = np.concatenate([chunk, pad])
        result = pads_for, step(jax.device_put(chunk, sharding))
        times["stage_s"] += time.perf_counter() - t0
        return result

    def collect(pads_for, results):
        t0 = time.perf_counter()
        out, counts, overflowed = results
        if np.asarray(overflowed).any():
            raise OverflowError("streamed round receive overflow; raise "
                                "out_factor or shrink rows_per_device")
        out = np.asarray(out).reshape(n, -1, rows.shape[1])
        counts = np.asarray(counts)
        for d in range(n):
            total = int(counts[d].sum())
            # .copy(): a view would pin the whole padded round buffer on the
            # host across all R rounds (~out_factor x dataset RSS)
            runs[d].append(out[d][:total - int(pads_for[d])].copy())
        times["collect_s"] += time.perf_counter() - t0

    # Double-buffered rounds: round r+1's device work is dispatched (jax
    # dispatch is async) before round r's host-side collection, so staging
    # + host processing overlap the device step — the inter-round pipeline
    # the reference gets from its async fetch window
    # (scala/RdmaShuffleFetcherIterator.scala:264-276).
    if pipeline_rounds:
        pending = None
        for r in range(num_rounds):
            nxt = dispatch(r)
            if pending is not None:
                collect(*pending)
            pending = nxt
        collect(*pending)
    else:
        for r in range(num_rounds):
            collect(*dispatch(r))

    from sparkrdma_tpu.shuffle.external import merge_runs

    t0 = time.perf_counter()
    merged = []
    for d in range(n):
        if not runs[d]:
            merged.append(np.zeros((0, rows.shape[1]), rows.dtype))
            continue
        # R key-sorted runs -> one sorted output via an O(N log R)
        # pairwise tournament of vectorized positional merges (keys are a
        # zero-copy view of column 0; earlier rounds win ties, matching
        # the former stable re-sort's order exactly)
        _, out = merge_runs([(r[:, 0], r) for r in runs[d]])
        merged.append(out)
    times["merge_s"] = time.perf_counter() - t0
    if phase_times is not None:
        phase_times.update(times, rounds=num_rounds)
    return merged, num_rounds


def verify_terasort(sorted_rows: np.ndarray, counts: np.ndarray,
                    input_rows: np.ndarray, num_devices: int) -> None:
    """Check the global sort contract against the input multiset."""
    per_dev = sorted_rows.reshape(num_devices, -1, sorted_rows.shape[-1])
    got_keys = []
    prev_max = -1
    for d in range(num_devices):
        total = int(counts[d].sum())
        keys = per_dev[d][:total, 0].astype(np.int64)
        if len(keys):
            assert (np.diff(keys) >= 0).all(), f"device {d} not locally sorted"
            assert keys[0] >= prev_max, f"device {d} overlaps previous range"
            prev_max = keys[-1]
        got_keys.append(keys)
    got = np.concatenate(got_keys)
    assert len(got) == len(input_rows), "row count mismatch"
    np.testing.assert_array_equal(np.sort(got),
                                  np.sort(input_rows[:, 0].astype(np.int64)))
