"""ALS (alternating least squares): the skew stress test.

BASELINE.md config #5: MLlib ALS over 100M ratings — the workload whose
shuffle is *ragged and skewed* (item popularity is zipfian, so grouping
ratings by item hammers a few devices). The reference handles skew with
bounded in-flight windows and grouped fetches
(scala/RdmaShuffleFetcherIterator.scala:240-276); the TPU build handles it
with the **chunked multi-round exchange** (``parallel.exchange.
chunked_exchange``) so per-round receive memory stays bounded at any skew.

One ALS half-step (solving item factors from fixed user factors):

1. ratings live user-sharded; each carries ``(item, user, rating)``;
2. chunked ragged exchange groups ratings onto the item's owner device —
   the skewed shuffle;
3. per item: accumulate normal equations ``A^T A + λI`` and ``A^T r`` over
   its ratings' user factors, then a **batched Cholesky-free solve**
   (``jnp.linalg.solve``) — dense [I_local, k, k] batches on the MXU.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from sparkrdma_tpu.ops.partition import hash_partition  # noqa: F401 (API parity)
from sparkrdma_tpu.parallel.exchange import chunked_exchange


@dataclass(frozen=True)
class ALSConfig:
    num_users: int
    num_items: int
    rank: int = 8
    reg: float = 0.1
    zipf_a: float = 1.3  # item popularity skew


def generate_ratings(cfg: ALSConfig, num_devices: int, per_device: int,
                     seed: int = 0) -> np.ndarray:
    """Zipf-skewed ratings ``u32[D*per_device, 3]`` = (item, user, rating_bits),
    user-sharded (device d holds users congruent d mod D)."""
    rng = np.random.default_rng(seed)
    rows = np.zeros((num_devices * per_device, 3), dtype=np.uint32)
    for d in range(num_devices):
        lo = d * per_device
        items = (rng.zipf(cfg.zipf_a, size=per_device) - 1) % cfg.num_items
        users = rng.integers(0, cfg.num_users // num_devices,
                             size=per_device) * num_devices + d
        ratings = rng.uniform(1.0, 5.0, size=per_device).astype(np.float32)
        rows[lo:lo + per_device, 0] = items
        rows[lo:lo + per_device, 1] = users
        rows[lo:lo + per_device, 2] = ratings.view(np.uint32)
    return rows


def solve_item_factors(ratings_for_device: np.ndarray, user_factors: np.ndarray,
                       cfg: ALSConfig, items_on_device: np.ndarray) -> np.ndarray:
    """Batched normal-equation solve for this device's items (jitted).

    ``ratings_for_device``: the post-exchange (item, user, rating) rows this
    device owns. Dense accumulation via segment scatter-add, then one
    batched ``linalg.solve`` — [I, k, k] on the MXU.
    """
    k = cfg.rank
    item_index = {int(i): n for n, i in enumerate(items_on_device)}
    local_item = np.array([item_index[int(i)] for i in ratings_for_device[:, 0]],
                          dtype=np.int32)
    users = ratings_for_device[:, 1].astype(np.int64)
    vals = ratings_for_device[:, 2].view(np.float32)

    n_items = len(items_on_device)
    u = jnp.asarray(user_factors[users])              # [R, k]
    li = jnp.asarray(local_item)
    r = jnp.asarray(vals)
    solve = _cached_solve(n_items, k, float(cfg.reg))
    return np.asarray(solve(u, li, r))


@functools.lru_cache(maxsize=64)
def _cached_solve(n_items: int, k: int, reg: float):
    """One jitted solver per (n_items, k, reg) — reused across devices and
    iterations so ALS pays a handful of compiles, not D*T."""

    @jax.jit
    def solve(u, li, r):
        outer = u[:, :, None] * u[:, None, :]          # [R, k, k]
        ata = jnp.zeros((n_items, k, k)).at[li].add(outer)
        atr = jnp.zeros((n_items, k)).at[li].add(u * r[:, None])
        ata = ata + reg * jnp.eye(k)[None]
        return jnp.linalg.solve(ata, atr[..., None])[..., 0]

    return solve


def als_half_step(mesh: Mesh, cfg: ALSConfig, ratings: np.ndarray,
                  user_factors: np.ndarray, quota: int,
                  axis_name: str = "shuffle") -> Tuple[np.ndarray, int]:
    """One item-side half-step: skewed shuffle + batched solves.

    Returns (item_factors[num_items, k], rounds_used). Item i is owned by
    device ``i % D``; the chunked exchange bounds per-round memory no matter
    how zipfian the item distribution is.
    """
    n = mesh.shape[axis_name]
    per_dev = ratings.shape[0] // n

    # destination-group rows by item owner (host-side: writer-side grouping)
    grouped = np.empty_like(ratings)
    counts = np.zeros((n, n), dtype=np.int32)
    for d in range(n):
        seg = ratings[d * per_dev:(d + 1) * per_dev]
        dest = (seg[:, 0] % n).astype(np.int32)
        order = np.argsort(dest, kind="stable")
        grouped[d * per_dev:(d + 1) * per_dev] = seg[order]
        counts[d] = np.bincount(dest, minlength=n)

    received, rounds = chunked_exchange(mesh, axis_name, grouped, counts,
                                        quota=quota)

    item_factors = np.zeros((cfg.num_items, cfg.rank), dtype=np.float32)
    for d in range(n):
        rows = received[d]
        if not len(rows):
            continue
        items_here = np.unique(rows[:, 0])
        factors = solve_item_factors(rows, user_factors, cfg, items_here)
        item_factors[items_here.astype(np.int64)] = factors
    return item_factors, rounds


def numpy_als_half_step(ratings: np.ndarray, user_factors: np.ndarray,
                        cfg: ALSConfig) -> np.ndarray:
    """Host oracle: per-item normal equations, plain numpy."""
    k = cfg.rank
    item_factors = np.zeros((cfg.num_items, k), dtype=np.float32)
    items = ratings[:, 0].astype(np.int64)
    users = ratings[:, 1].astype(np.int64)
    vals = ratings[:, 2].view(np.float32)
    for i in np.unique(items):
        sel = items == i
        u = user_factors[users[sel]].astype(np.float64)
        ata = u.T @ u + cfg.reg * np.eye(k)
        atr = u.T @ vals[sel].astype(np.float64)
        item_factors[i] = np.linalg.solve(ata, atr).astype(np.float32)
    return item_factors
