"""ALS (alternating least squares): the skew stress test.

BASELINE.md config #5: MLlib ALS over 100M ratings — the workload whose
shuffle is *ragged and skewed* (item popularity is zipfian, so grouping
ratings by item hammers a few devices). The reference handles skew with
bounded in-flight windows and grouped fetches
(scala/RdmaShuffleFetcherIterator.scala:240-276); the TPU build handles it
with the **chunked multi-round exchange** (``parallel.exchange.
chunked_exchange``) so per-round receive memory stays bounded at any skew.

One ALS half-step (solving item factors from fixed user factors):

1. ratings live user-sharded; each carries ``(item, user, rating)``;
2. chunked ragged exchange groups ratings onto the item's owner device —
   the skewed shuffle;
3. per item: accumulate normal equations ``A^T A + λI`` and ``A^T r`` over
   its ratings' user factors, then a **batched Cholesky-free solve**
   (``jnp.linalg.solve``) — dense [I_local, k, k] batches on the MXU.

``run_als`` drives the FULL alternating loop — items from users, then
users from items (the same half-step with the key columns swapped), two
skewed shuffles per sweep — and reports the RMSE trajectory, matching the
MLlib ALS cadence the reference benchmarks under config #5.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from sparkrdma_tpu.ops.partition import hash_partition  # noqa: F401 (API parity)
from sparkrdma_tpu.parallel.exchange import chunked_exchange


@dataclass(frozen=True)
class ALSConfig:
    num_users: int
    num_items: int
    rank: int = 8
    reg: float = 0.1
    zipf_a: float = 1.3  # item popularity skew


def generate_ratings(cfg: ALSConfig, num_devices: int, per_device: int,
                     seed: int = 0) -> np.ndarray:
    """Zipf-skewed ratings ``u32[D*per_device, 3]`` = (item, user, rating_bits),
    user-sharded (device d holds users congruent d mod D)."""
    rng = np.random.default_rng(seed)
    rows = np.zeros((num_devices * per_device, 3), dtype=np.uint32)
    for d in range(num_devices):
        lo = d * per_device
        items = (rng.zipf(cfg.zipf_a, size=per_device) - 1) % cfg.num_items
        users = rng.integers(0, cfg.num_users // num_devices,
                             size=per_device) * num_devices + d
        ratings = rng.uniform(1.0, 5.0, size=per_device).astype(np.float32)
        rows[lo:lo + per_device, 0] = items
        rows[lo:lo + per_device, 1] = users
        rows[lo:lo + per_device, 2] = ratings.view(np.uint32)
    return rows


def solve_item_factors(ratings_for_device: np.ndarray, user_factors: np.ndarray,
                       cfg: ALSConfig, items_on_device: np.ndarray,
                       key_col: int = 0) -> np.ndarray:
    """Batched normal-equation solve for this device's entities (jitted).

    ``ratings_for_device``: the post-exchange (item, user, rating) rows this
    device owns. Dense accumulation via segment scatter-add, then one
    batched ``linalg.solve`` — [I, k, k] on the MXU.

    ``key_col`` picks the side being SOLVED (0 = items from fixed user
    factors, 1 = users from fixed item factors — the two alternating
    half-steps are the same math with the columns swapped).
    """
    k = cfg.rank
    other_col = 1 - key_col
    # np.searchsorted over the sorted owned-entity ids: the Python dict
    # per-row loop was the host bottleneck at rehearsal scale
    local_key = np.searchsorted(items_on_device,
                                ratings_for_device[:, key_col]).astype(np.int32)
    others = ratings_for_device[:, other_col].astype(np.int64)
    vals = ratings_for_device[:, 2].view(np.float32)

    # pow2 key-count bucket + fixed row chunks: a handful of compiled
    # shapes total (not one per device per sweep), and the [CH, k, k]
    # outer-product transient stays bounded no matter how many rows the
    # zipf-hot device drew (11M rows would otherwise materialize a
    # multi-GB intermediate in one op)
    n_keys = len(items_on_device)
    n_pad = 1 << max(4, (n_keys - 1).bit_length())
    accum = _cached_accum(n_pad, k)
    finish = _cached_finish(n_pad, k, float(cfg.reg))
    ata = jnp.zeros((n_pad, k, k), jnp.float32)
    atr = jnp.zeros((n_pad, k), jnp.float32)
    R = len(ratings_for_device)
    # bucket the chunk size like n_pad: tiny inputs (unit tests, sparse
    # devices) must not each run a padded 1M-row outer-product — pow2
    # bucketing keeps the compile count logarithmic while sizing the
    # [CH, k, k] transient to the data
    ch = min(_SOLVE_CHUNK, 1 << max(10, (max(R, 1) - 1).bit_length()))
    for lo in range(0, max(R, 1), ch):
        hi = min(lo + ch, R)
        pad = ch - (hi - lo)
        u = user_factors[others[lo:hi]]
        li = local_key[lo:hi]
        r = vals[lo:hi]
        if pad:
            u = np.concatenate([u, np.zeros((pad, k), np.float32)])
            # out-of-range key -> dropped by the scatter
            li = np.concatenate([li, np.full(pad, n_pad, np.int32)])
            r = np.concatenate([r, np.zeros(pad, np.float32)])
        ata, atr = accum(ata, atr, jnp.asarray(u), jnp.asarray(li),
                         jnp.asarray(r))
    return np.asarray(finish(ata, atr))[:n_keys]


_SOLVE_CHUNK = 1 << 20


@functools.lru_cache(maxsize=64)
def _cached_accum(n_pad: int, k: int):
    """Jitted normal-equation accumulator over one fixed-size row chunk;
    pow2 ``n_pad`` buckets keep the compile count logarithmic."""

    @jax.jit
    def accum(ata, atr, u, li, r):
        outer = u[:, :, None] * u[:, None, :]          # [CH, k, k]
        return (ata.at[li].add(outer, mode="drop"),
                atr.at[li].add(u * r[:, None], mode="drop"))

    return accum


@functools.lru_cache(maxsize=64)
def _cached_finish(n_pad: int, k: int, reg: float):
    """Batched regularized solve; padded keys see ``reg*I x = 0`` -> 0."""

    @jax.jit
    def finish(ata, atr):
        ata = ata + reg * jnp.eye(k)[None]
        return jnp.linalg.solve(ata, atr[..., None])[..., 0]

    return finish


def als_half_step(mesh: Mesh, cfg: ALSConfig, ratings: np.ndarray,
                  user_factors: np.ndarray, quota: int,
                  axis_name: str = "shuffle",
                  key_col: int = 0) -> Tuple[np.ndarray, int]:
    """One half-step: skewed shuffle + batched solves.

    ``key_col=0``: solve item factors from fixed user factors (the
    skew-hammered side); ``key_col=1``: solve user factors from fixed
    item factors. Returns (factors[num_entities, k], rounds_used).
    Entity e is owned by device ``e % D``; the chunked exchange bounds
    per-round memory no matter how zipfian the distribution is.
    """
    n = mesh.shape[axis_name]
    per_dev = ratings.shape[0] // n
    num_out = cfg.num_items if key_col == 0 else cfg.num_users

    # destination-group rows by entity owner (host-side: writer-side
    # grouping, the analogue of the sort-by-partition spill)
    grouped = np.empty_like(ratings)
    counts = np.zeros((n, n), dtype=np.int32)
    for d in range(n):
        seg = ratings[d * per_dev:(d + 1) * per_dev]
        dest = (seg[:, key_col] % n).astype(np.int32)
        order = np.argsort(dest, kind="stable")
        grouped[d * per_dev:(d + 1) * per_dev] = seg[order]
        counts[d] = np.bincount(dest, minlength=n)

    received, rounds = chunked_exchange(mesh, axis_name, grouped, counts,
                                        quota=quota)
    del grouped  # ~1x the dataset; the solves below only need `received`

    factors = np.zeros((num_out, cfg.rank), dtype=np.float32)
    for d in range(n):
        rows = received[d]
        if not len(rows):
            continue
        keys_here = np.unique(rows[:, key_col])
        solved = solve_item_factors(rows, user_factors, cfg, keys_here,
                                    key_col=key_col)
        factors[keys_here.astype(np.int64)] = solved
    return factors, rounds


def rmse(ratings: np.ndarray, user_factors: np.ndarray,
         item_factors: np.ndarray, sample: int = 0) -> float:
    """Root-mean-square prediction error over (a sample of) the ratings."""
    rows = ratings
    if sample and len(rows) > sample:
        rows = rows[np.random.default_rng(0).permutation(len(rows))[:sample]]
    pred = np.sum(user_factors[rows[:, 1].astype(np.int64)]
                  * item_factors[rows[:, 0].astype(np.int64)], axis=1)
    err = pred - rows[:, 2].view(np.float32)
    return float(np.sqrt(np.mean(err * err)))


def run_als(mesh: Mesh, cfg: ALSConfig, ratings: np.ndarray, quota: int,
            iterations: int = 5, axis_name: str = "shuffle", seed: int = 0,
            rmse_sample: int = 200_000,
            ) -> Tuple[np.ndarray, np.ndarray, list, int]:
    """The FULL alternating loop (BASELINE config #5's actual workload):
    each iteration solves items from users, then users from items — two
    skewed shuffles per iteration through the bounded-round exchange,
    the cadence MLlib ALS drives per sweep.

    Returns (user_factors, item_factors, rmse_history, total_rounds);
    ``rmse_history[0]`` is the pre-training error of the random init.
    """
    rng = np.random.default_rng(seed)
    user_factors = (rng.standard_normal((cfg.num_users, cfg.rank))
                    .astype(np.float32) / np.sqrt(cfg.rank))
    item_factors = np.zeros((cfg.num_items, cfg.rank), np.float32)
    total_rounds = 0
    history = [rmse(ratings, user_factors, item_factors, rmse_sample)]
    for _ in range(iterations):
        item_factors, r1 = als_half_step(mesh, cfg, ratings, user_factors,
                                         quota, axis_name, key_col=0)
        user_factors, r2 = als_half_step(mesh, cfg, ratings, item_factors,
                                         quota, axis_name, key_col=1)
        total_rounds += r1 + r2
        history.append(rmse(ratings, user_factors, item_factors,
                            rmse_sample))
    return user_factors, item_factors, history, total_rounds


def numpy_als_half_step(ratings: np.ndarray, user_factors: np.ndarray,
                        cfg: ALSConfig) -> np.ndarray:
    """Host oracle: per-item normal equations, plain numpy."""
    k = cfg.rank
    item_factors = np.zeros((cfg.num_items, k), dtype=np.float32)
    items = ratings[:, 0].astype(np.int64)
    users = ratings[:, 1].astype(np.int64)
    vals = ratings[:, 2].view(np.float32)
    for i in np.unique(items):
        sel = items == i
        u = user_factors[users[sel]].astype(np.float64)
        ata = u.T @ u + cfg.reg * np.eye(k)
        atr = u.T @ vals[sel].astype(np.float64)
        item_factors[i] = np.linalg.solve(ata, atr).astype(np.float32)
    return item_factors
