"""Shuffle join: the TPC-DS q64/q95-style workload.

BASELINE.md config #4: shuffle-heavy SQL joins. A distributed equi-join is
two shuffles (both sides hash-partitioned on the join key to the same
devices) followed by a local join per partition — exactly the traffic the
reference accelerates for Spark SQL.

TPU-native design, one jitted SPMD step:

1. both row sets are hash-partitioned on key and ragged-exchanged to the
   key's owner device (two collectives, same routing);
2. the local join is sort-merge: co-sort both sides by key, then for every
   left row count/sum its key's matches on the right via two
   ``searchsorted`` boundaries — static shapes, no data-dependent output
   (the step returns per-device aggregates: match count + sum of joined
   measures, the q95-style reduction).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.utils.compat import shard_map

from sparkrdma_tpu.ops.partition import hash_partition
from sparkrdma_tpu.parallel.exchange import resolve_impl, shuffle_shard


@dataclass(frozen=True)
class JoinConfig:
    rows_per_device_left: int
    rows_per_device_right: int
    key_space: int
    out_factor: int = 2


def make_join_step(mesh: Mesh, axis_name: str, cfg: JoinConfig,
                   impl: str = "auto"):
    """Jitted hash-shuffle join.

    Inputs (leading axis sharded): ``left: u32[D*L, 2]`` (key, measure),
    ``right: u32[D*R, 2]`` (key, measure). Padding rows use key
    0xFFFFFFFF. Returns per-device ``(match_count: i32[D, 1],
    measure_sum: i32[D, 1])`` where measure_sum adds left.measure *
    right_match_count + right measures of matches — a fixed-shape
    aggregate standing in for the materialized join. Per-device partial
    sums are i32 (x64 is off under jit); callers needing >2^31 totals
    aggregate the per-device partials host-side.
    """
    n = mesh.shape[axis_name]
    impl = resolve_impl(mesh, impl, axis_name)
    spec = P(axis_name)
    PAD = jnp.uint32(0xFFFFFFFF)

    def exchange_side(rows, capacity_factor):
        keys = rows[:, 0]
        valid = keys != PAD
        dest = jnp.where(valid, hash_partition(keys, n), -1)
        output = jnp.zeros((rows.shape[0] * capacity_factor, rows.shape[1]),
                           rows.dtype)
        received, recv_counts, _, overflowed = shuffle_shard(
            rows, dest, axis_name, n, output=output, impl=impl)
        total = recv_counts.sum()
        rvalid = jnp.arange(received.shape[0], dtype=jnp.int32) < total
        rkeys = jnp.where(rvalid, received[:, 0], PAD)
        order = jnp.argsort(rkeys, stable=True)
        return (jnp.sort(rkeys), jnp.take(received[:, 1], order),
                total, overflowed)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec, spec),
                       out_specs=(spec, spec, spec))
    def step(left, right):
        lk, lv, ln_, lof = exchange_side(left, cfg.out_factor)
        rk, rv, rn_, rof = exchange_side(right, cfg.out_factor)
        # right-side prefix sums of measures for O(1) range sums
        rv32 = rv.astype(jnp.int32)
        rpref = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(rv32)])
        lo = jnp.searchsorted(rk, lk, side="left")
        hi = jnp.searchsorted(rk, lk, side="right")
        lvalid = lk != PAD
        matches = jnp.where(lvalid, (hi - lo).astype(jnp.int32), 0)
        # sum over matched pairs of (left.measure + right.measure)
        pair_sum = jnp.where(
            lvalid,
            matches * lv.astype(jnp.int32) + (rpref[hi] - rpref[lo]),
            0)
        overflowed = lof | rof
        return (matches.sum()[None, None], pair_sum.sum()[None, None],
                overflowed[None])

    return step


def generate_tables(cfg: JoinConfig, num_devices: int, seed: int = 0,
                    ) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    left = rng.integers(0, cfg.key_space,
                        size=(num_devices * cfg.rows_per_device_left, 2),
                        dtype=np.uint32)
    right = rng.integers(0, cfg.key_space,
                         size=(num_devices * cfg.rows_per_device_right, 2),
                         dtype=np.uint32)
    left[:, 1] %= 1000
    right[:, 1] %= 1000
    return left, right


def run_join(mesh: Mesh, cfg: JoinConfig, axis_name: str = "shuffle",
             seed: int = 0, impl: str = "auto") -> Tuple[int, int]:
    """Returns (total_matches, total_pair_measure_sum)."""
    n = mesh.shape[axis_name]
    left, right = generate_tables(cfg, n, seed)
    step = make_join_step(mesh, axis_name, cfg, impl)
    shard = NamedSharding(mesh, P(axis_name))
    counts, sums, overflowed = jax.block_until_ready(
        step(jax.device_put(left, shard), jax.device_put(right, shard)))
    if np.asarray(overflowed).any():
        raise OverflowError("join shuffle overflowed receive headroom; "
                            "raise JoinConfig.out_factor")
    return int(np.asarray(counts).sum()), int(np.asarray(sums).sum())


def numpy_join(left: np.ndarray, right: np.ndarray) -> Tuple[int, int]:
    """Host oracle: exact inner-join aggregates."""
    matches = 0
    pair_sum = 0
    right_by_key: dict = {}
    for k, v in right.tolist():
        right_by_key.setdefault(k, []).append(v)
    for k, v in left.tolist():
        rs = right_by_key.get(k)
        if rs:
            matches += len(rs)
            pair_sum += len(rs) * v + sum(rs)
    return matches, pair_sum
