"""PageRank: iterative shuffle over ICI.

The reference's second headline benchmark is GraphX PageRank-19GB, 2.01×
faster over 100GbE RoCE (README.md:25-31; BASELINE.md config #3). GraphX
shuffles edge contributions to vertex owners every iteration — the workload
that stresses *repeated* exchange with stable routing.

TPU-native design: vertices are range-sharded over the mesh; edges live on
their source vertex's device. One iteration is one jitted SPMD step:

1. contribution per local edge = rank[src] / out_degree[src] (local gather
   — src is local by construction);
2. ragged exchange of ``(dst, contribution)`` rows to dst's owner device
   (the GraphX shuffle);
3. segment-sum received contributions into local ranks (one scatter-add),
   then ``rank = (1 - d)/V + d * sums``.

Ranks never leave their shard; only contributions move — the same traffic
shape GraphX produces, minus the host.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.utils.compat import shard_map

from sparkrdma_tpu.parallel.exchange import resolve_impl, shuffle_shard


@dataclass(frozen=True)
class PageRankConfig:
    num_vertices: int          # global, multiple of mesh size
    edges_per_device: int      # local edge capacity (padded)
    damping: float = 0.85
    out_factor: int = 2


def make_pagerank_step(mesh: Mesh, axis_name: str, cfg: PageRankConfig,
                       impl: str = "auto"):
    """One jitted PageRank iteration.

    Per-device inputs (leading axis sharded over ``axis_name``):
      ``edges: i32[D*E, 2]`` — (src, dst) global vertex ids; padding rows
        have src = -1;
      ``ranks: f32[V]`` — vertex ranks, range-sharded (device d owns
        ``[d*V/D, (d+1)*V/D)``);
      ``out_deg: f32[V]`` — out-degrees, sharded identically.

    Returns ``(ranks, overflowed[D])``; ``overflowed[d]`` flags a receive
    buffer too small for the contribution fan-in (results invalid — raise
    ``out_factor``), mirroring the TeraSort/join steps.
    """
    n = mesh.shape[axis_name]
    impl = resolve_impl(mesh, impl, axis_name)
    v_local = cfg.num_vertices // n
    spec = P(axis_name)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec, spec, spec),
                       out_specs=(spec, spec))
    def step(edges, ranks, out_deg):
        me = jax.lax.axis_index(axis_name)
        src, dst = edges[:, 0], edges[:, 1]
        valid = src >= 0
        # local rank lookup: src ids are local to this shard
        src_local = jnp.where(valid, src - me * v_local, 0)
        contrib = jnp.where(valid,
                            ranks[src_local] / jnp.maximum(out_deg[src_local], 1.0),
                            0.0)
        # rows: (dst, contribution bits) — one u32 matrix for the exchange
        rows = jnp.stack([dst.astype(jnp.uint32),
                          jax.lax.bitcast_convert_type(
                              contrib.astype(jnp.float32), jnp.uint32)], axis=1)
        dest_dev = jnp.where(valid, dst // v_local, -1)
        output = jnp.zeros((rows.shape[0] * cfg.out_factor, 2), jnp.uint32)
        received, recv_counts, _, overflowed = shuffle_shard(
            rows, dest_dev, axis_name, n, output=output, impl=impl)
        total = recv_counts.sum()
        rvalid = jnp.arange(received.shape[0], dtype=jnp.int32) < total
        rdst = jnp.where(rvalid,
                         received[:, 0].astype(jnp.int32) - me * v_local, 0)
        rcontrib = jnp.where(
            rvalid,
            jax.lax.bitcast_convert_type(received[:, 1], jnp.float32), 0.0)
        sums = jnp.zeros(v_local, jnp.float32).at[rdst].add(rcontrib)
        new_ranks = (1.0 - cfg.damping) / cfg.num_vertices + cfg.damping * sums
        return new_ranks, overflowed[None]

    return step


def random_graph(cfg: PageRankConfig, num_devices: int, seed: int = 0,
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Random directed graph, edges placed on their src's device.
    Returns (edges[D*E, 2], ranks[V], out_deg[V])."""
    rng = np.random.default_rng(seed)
    v_local = cfg.num_vertices // num_devices
    edges = np.full((num_devices * cfg.edges_per_device, 2), -1, dtype=np.int32)
    out_deg = np.zeros(cfg.num_vertices, dtype=np.float32)
    for d in range(num_devices):
        e = rng.integers(0, v_local, size=(cfg.edges_per_device, 2))
        e[:, 0] += d * v_local                          # src local to d
        e[:, 1] = rng.integers(0, cfg.num_vertices,     # dst anywhere
                               size=cfg.edges_per_device)
        lo = d * cfg.edges_per_device
        edges[lo:lo + cfg.edges_per_device] = e
        np.add.at(out_deg, e[:, 0], 1.0)
    ranks = np.full(cfg.num_vertices, 1.0 / cfg.num_vertices, dtype=np.float32)
    return edges, ranks, out_deg


def run_pagerank(mesh: Mesh, cfg: PageRankConfig, iterations: int,
                 axis_name: str = "shuffle", seed: int = 0,
                 impl: str = "auto") -> np.ndarray:
    """Host loop: `iterations` jitted shuffle rounds; returns final ranks."""
    n = mesh.shape[axis_name]
    edges, ranks, out_deg = random_graph(cfg, n, seed)
    step = make_pagerank_step(mesh, axis_name, cfg, impl)
    shard = NamedSharding(mesh, P(axis_name))
    edges_d = jax.device_put(edges, shard)
    ranks_d = jax.device_put(ranks, shard)
    deg_d = jax.device_put(out_deg, shard)
    overflowed = None
    for _ in range(iterations):
        ranks_d, overflowed = step(edges_d, ranks_d, deg_d)
    ranks_h = np.asarray(jax.block_until_ready(ranks_d))
    if overflowed is not None and np.asarray(overflowed).any():
        raise OverflowError(
            "pagerank receive buffer overflow: contribution fan-in exceeds "
            "out_factor headroom; raise PageRankConfig.out_factor")
    return ranks_h


def numpy_pagerank(edges: np.ndarray, num_vertices: int, damping: float,
                   iterations: int) -> np.ndarray:
    """Dense host oracle for correctness checks."""
    valid = edges[:, 0] >= 0
    src, dst = edges[valid, 0], edges[valid, 1]
    out_deg = np.zeros(num_vertices, dtype=np.float64)
    np.add.at(out_deg, src, 1.0)
    ranks = np.full(num_vertices, 1.0 / num_vertices, dtype=np.float64)
    for _ in range(iterations):
        contrib = ranks[src] / np.maximum(out_deg[src], 1.0)
        sums = np.zeros(num_vertices, dtype=np.float64)
        np.add.at(sums, dst, contrib)
        ranks = (1.0 - damping) / num_vertices + damping * sums
    return ranks.astype(np.float32)
