"""Actual TPC-DS q64 / q95 plan shapes (BASELINE.md config #4).

The reference's workload class is shuffle-heavy Spark jobs (its README
publishes TeraSort and PageRank results, /root/reference/README.md:7-31);
BASELINE.md config #4 names Spark SQL TPC-DS q64/q95 as the
multi-join shuffle stress for this build — q64 and q95 are the
standard shuffle-heavy picks in TPC-DS benchmarking literature. The
generic star in ``models/tpcds.py`` covers the *class*; this module
expresses the two *named* plans:

**q95** — web-sales shipping analysis:
  - ``ws_wh`` self-semi-join: orders shipped from MORE THAN ONE warehouse
    (web_sales ⋈ web_sales on order_number, warehouse_sk <> warehouse_sk)
  - semi-join against web_returns on order_number (returned orders only)
  - dimension filters: date_dim (60-day ship window), customer_address
    (state), web_site (company)
  - output: count(distinct order_number), sum(ext_ship_cost),
    sum(net_profit)

**q64** — cross-channel sales with both returns tables:
  - ``cs_ui``: catalog_sales ⋈ catalog_returns on (item, order), grouped
    by item, HAVING sum(sales) > 2 * sum(refund)
  - store_sales ⋈ store_returns on (item, ticket)  [inner: sold AND
    returned]
  - ⋈ date_dim on sold_date (two consecutive years)
  - semi-join against cs_ui on item
  - per (item, year) aggregation, then the aggregated CTE SELF-JOINED
    across years: items where cnt(year+1) <= cnt(year)
  - output: count(qualifying items), sum(both years' price sums)

Both run two ways against ONE numpy oracle each:
  - ``make_q95_step`` / ``make_q64_step``: every shuffle is a collective
    ragged exchange chained inside ONE jitted shard_map step (dimension
    joins are expressed as shuffle joins — heavier than Spark's broadcast
    hash joins on purpose: the exchange is the thing under test).
    Static shapes throughout: selectivity travels as flag bits on the
    rows, never as data-dependent row counts.
  - ``build_q95_job`` / ``build_q64_job``: the same logical plan as a
    stage DAG for ``engine.DAGEngine.run`` — source stages, join
    MapStages, aggregating ResultStage — driving the drop-in shuffle SPI
    exactly the way Spark SQL's stage graph drives the reference.

Key-space convention: item/order/ticket keys fit 16 bits so an exact
(item, order) pair key fits one u32 lane (pairkey = item << 16 | order);
the engine path uses the native u64 key lane instead. PAD = 0xFFFFFFFF
marks dead rows.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.utils.compat import shard_map

from sparkrdma_tpu.ops.partition import hash_partition
from sparkrdma_tpu.parallel.exchange import resolve_impl, shuffle_shard

PAD = np.uint32(0xFFFFFFFF)
_KEY_BITS = 16  # item/order/ticket key spaces (see module docstring)


def _pairkey(a, b):
    """Exact u32 composite of two 16-bit keys (same in numpy and jnp)."""
    return a * np.uint32(1 << _KEY_BITS) + b


# ---------------------------------------------------------------------------
# shared shard-side helpers (inside shard_map)
# ---------------------------------------------------------------------------


def _exchange(rows, dest, axis_name, n, capacity, impl):
    """One collective shuffle of ``rows`` to ``dest`` with a fixed receive
    capacity; returns (received, valid_mask, overflowed)."""
    output = jnp.zeros((capacity,) + rows.shape[1:], rows.dtype)
    received, recv_counts, _, overflowed = shuffle_shard(
        rows, dest, axis_name, n, output=output, impl=impl)
    total = recv_counts.sum()
    valid = jnp.arange(capacity, dtype=jnp.int32) < total
    return received, valid, overflowed


def _lookup(dim_keys, dim_valid, dim_attr, probes):
    """Sorted unique-key lookup: returns (attr, found) per probe."""
    dk = jnp.where(dim_valid, dim_keys, PAD)
    order = jnp.argsort(dk)
    ks = jnp.take(dk, order)
    at = jnp.take(dim_attr, order)
    idx = jnp.clip(jnp.searchsorted(ks, probes), 0, ks.shape[0] - 1)
    found = (jnp.take(ks, idx) == probes) & (probes != PAD)
    return jnp.take(at, idx), found


def _route(keys, valid, n):
    return jnp.where(valid, hash_partition(keys, n), -1)


def _dim_cap(rows_per_shard: int, n: int) -> int:
    """Receive capacity for a small broadcast-class table: ``rows * n``.

    One device receiving EVERYTHING fits, and under the dense transport
    each (src, dst) pair's fixed slot is ``cap // n = rows`` — a source
    only HAS ``rows`` rows, so pair overflow is impossible too. Dim
    tables are small by definition; anything where rows*n hurts should
    ride the fact-table path with an out_factor instead."""
    return rows_per_shard * n


# ===========================================================================
# q95
# ===========================================================================


@dataclass(frozen=True)
class Q95Config:
    ws_rows_per_device: int
    num_orders: int            # < 2**16
    num_warehouses: int = 8
    num_dates: int = 365
    window_start: int = 40     # d_date in [start, start + 60)
    num_states: int = 16
    target_state: int = 3
    num_sites: int = 12
    num_companies: int = 4
    target_company: int = 1
    return_fraction: float = 0.4
    out_factor: int = 3


def generate_q95(cfg: Q95Config, num_devices: int, seed: int = 0):
    """(ws[N,7], wr[R,1], date[D,2], addr[A,2], site[S,2]) as u32.

    ws columns: order, warehouse, ship_date, ship_addr, site, cost,
    profit. Orders are zipf-ish popular (several line items per order —
    the self-semi-join needs real multi-row orders)."""
    assert cfg.num_orders < (1 << _KEY_BITS)
    rng = np.random.default_rng(seed)
    n_rows = cfg.ws_rows_per_device * num_devices
    order = rng.integers(0, cfg.num_orders, n_rows)
    ws = np.stack([
        order,
        rng.integers(0, cfg.num_warehouses, n_rows),
        rng.integers(0, cfg.num_dates, n_rows),
        rng.integers(0, cfg.num_states * 50, n_rows),
        rng.integers(0, cfg.num_sites, n_rows),
        rng.integers(0, 1000, n_rows),
        rng.integers(0, 1000, n_rows),
    ], axis=1).astype(np.uint32)
    returned = rng.permutation(cfg.num_orders)[
        : int(cfg.num_orders * cfg.return_fraction)]
    wr = np.sort(returned).astype(np.uint32).reshape(-1, 1)
    date = np.stack([np.arange(cfg.num_dates),
                     np.arange(cfg.num_dates)], axis=1).astype(np.uint32)
    addr = np.stack([np.arange(cfg.num_states * 50),
                     np.arange(cfg.num_states * 50) % cfg.num_states],
                    axis=1).astype(np.uint32)
    site = np.stack([np.arange(cfg.num_sites),
                     np.arange(cfg.num_sites) % cfg.num_companies],
                    axis=1).astype(np.uint32)
    return ws, wr, date, addr, site


def numpy_q95(ws, wr, date, addr, site, cfg: Q95Config
              ) -> Tuple[int, int, int]:
    """Oracle: (distinct qualifying orders, sum cost, sum profit)."""
    d_date = dict(zip(date[:, 0].tolist(), date[:, 1].tolist()))
    a_state = dict(zip(addr[:, 0].tolist(), addr[:, 1].tolist()))
    s_comp = dict(zip(site[:, 0].tolist(), site[:, 1].tolist()))
    returned = set(wr[:, 0].tolist())
    wh_by_order: dict = {}
    for o, w in zip(ws[:, 0].tolist(), ws[:, 1].tolist()):
        wh_by_order.setdefault(o, set()).add(w)
    multi = {o for o, whs in wh_by_order.items() if len(whs) > 1}
    lo, hi = cfg.window_start, cfg.window_start + 60
    orders = set()
    cost = profit = 0
    for o, _w, dt, ad, st, c, p in ws.tolist():
        dd = d_date.get(dt)
        if dd is None or not (lo <= dd < hi):
            continue
        if a_state.get(ad) != cfg.target_state:
            continue
        if s_comp.get(st) != cfg.target_company:
            continue
        if o not in multi or o not in returned:
            continue
        orders.add(o)
        cost += c
        profit += p
    return len(orders), cost, profit


def make_q95_step(mesh: Mesh, axis_name: str, cfg: Q95Config,
                  impl: str = "auto"):
    """q95 as FOUR chained exchange rounds in one jitted SPMD step.

    Rounds 1-3 shuffle-join the three dimensions (date/addr/site),
    accumulating pass/fail as flag bits on the moving rows; round 4
    co-locates web_sales and web_returns by order_number, where the
    multi-warehouse self-semi-join and the returns semi-join become
    per-order segment reductions. Returns per-device partials
    ``(i32[D, 3], overflowed[D])``: host-sums give the exact answer
    (each order lives on exactly one device)."""
    n = mesh.shape[axis_name]
    impl = resolve_impl(mesh, impl, axis_name)
    spec = P(axis_name)
    F = cfg.ws_rows_per_device
    cap = F * cfg.out_factor
    lo = np.uint32(cfg.window_start)
    hi = np.uint32(cfg.window_start + 60)

    def dim_round(rows, valid, key_col, dim, flag_bit, pred):
        """Shuffle-join one dimension; OR ``pred(attr) & found`` into the
        flags column (col 7); returns (rows, valid, overflow)."""
        d_recv, d_valid, of_d = _exchange(
            dim, _route(dim[:, 0], jnp.ones(dim.shape[0], bool), n),
            axis_name, n, _dim_cap(dim.shape[0], n), impl)
        keys = rows[:, key_col]
        f_recv, f_valid, of_f = _exchange(
            rows, _route(keys, valid, n), axis_name, n, cap, impl)
        attr, found = _lookup(d_recv[:, 0], d_valid, d_recv[:, 1],
                              jnp.where(f_valid, f_recv[:, key_col], PAD))
        ok = found & pred(attr)
        flags = f_recv[:, 7] | jnp.where(ok, jnp.uint32(flag_bit),
                                         jnp.uint32(0))
        return (f_recv.at[:, 7].set(flags), f_valid, of_d | of_f)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec,) * 5, out_specs=(spec, spec))
    def step(ws, wr, date, addr, site):
        # working rows: [order, wh, date, addr, site, cost, profit, flags]
        rows = jnp.concatenate(
            [ws, jnp.zeros((ws.shape[0], 1), jnp.uint32)], axis=1)
        valid = jnp.ones(rows.shape[0], bool)
        rows, valid, of1 = dim_round(
            rows, valid, 2, date, 1, lambda d: (d >= lo) & (d < hi))
        rows, valid, of2 = dim_round(
            rows, valid, 3, addr, 2,
            lambda s: s == np.uint32(cfg.target_state))
        rows, valid, of3 = dim_round(
            rows, valid, 4, site, 4,
            lambda c: c == np.uint32(cfg.target_company))
        # round 4: co-locate by order_number (fact AND returns)
        rows, valid, of4 = _exchange(
            rows, _route(rows[:, 0], valid, n), axis_name, n, cap, impl)
        wr_recv, wr_valid, of5 = _exchange(
            wr, _route(wr[:, 0], jnp.ones(wr.shape[0], bool), n),
            axis_name, n, _dim_cap(wr.shape[0], n), impl)

        # per-order segment reductions over order-sorted rows
        o = jnp.where(valid, rows[:, 0], PAD)
        perm = jnp.argsort(o)
        o_s = jnp.take(o, perm)
        r_s = jnp.take(rows, perm, axis=0)
        N = o_s.shape[0]
        new_seg = jnp.concatenate(
            [jnp.ones(1, bool), o_s[1:] != o_s[:-1]])
        si = jnp.cumsum(new_seg.astype(jnp.int32)) - 1
        live = o_s != PAD
        wh = r_s[:, 1]
        min_wh = jax.ops.segment_min(
            jnp.where(live, wh, PAD), si, num_segments=N)
        max_wh = jax.ops.segment_max(
            jnp.where(live, wh, jnp.uint32(0)), si, num_segments=N)
        multi = min_wh != max_wh          # ws_wh: >1 distinct warehouse
        _, has_ret = _lookup(wr_recv[:, 0], wr_valid,
                             wr_recv[:, 0], o_s)
        qual = (live & (r_s[:, 7] == 7) & has_ret
                & jnp.take(multi, si))
        # distinct via segment_sum (identity 0 — segment_max's int32
        # identity is INT32_MIN on unoccupied segments)
        distinct = (jax.ops.segment_sum(
            qual.astype(jnp.int32), si, num_segments=N) > 0).sum()
        cost = jnp.where(qual, r_s[:, 5], 0).astype(jnp.int32).sum()
        profit = jnp.where(qual, r_s[:, 6], 0).astype(jnp.int32).sum()
        overflowed = of1 | of2 | of3 | of4 | of5
        return (jnp.stack([distinct, cost, profit])[None],
                overflowed[None])

    return step


def run_q95(mesh: Mesh, cfg: Q95Config, axis_name: str = "shuffle",
            seed: int = 0, impl: str = "auto") -> Tuple[int, int, int]:
    """Host driver: returns the exact global q95 answer."""
    n = mesh.shape[axis_name]
    ws, wr, date, addr, site = generate_q95(cfg, n, seed)
    step = make_q95_step(mesh, axis_name, cfg, impl)
    shard = NamedSharding(mesh, P(axis_name))
    args = [jax.device_put(pad_rows_to_devices(t, n), shard)
            for t in (ws, wr, date, addr, site)]
    partial, overflowed = jax.block_until_ready(step(*args))
    if np.asarray(overflowed).any():
        raise OverflowError("q95 exchange overflowed; raise out_factor")
    totals = np.asarray(partial).sum(axis=0).astype(np.int64)
    return int(totals[0]), int(totals[1]), int(totals[2])


# ===========================================================================
# q64
# ===========================================================================


@dataclass(frozen=True)
class Q64Config:
    ss_rows_per_device: int
    cs_rows_per_device: int
    num_items: int             # < 2**16
    num_dates: int = 365
    first_year_mod: int = 0    # dates with (date % 3) == mod are year Y
    sr_fraction: float = 0.5   # store returns coverage of store sales
    cr_fraction: float = 0.5   # catalog returns coverage
    zipf_a: float = 1.3        # item popularity skew
    out_factor: int = 4


def _zipf_items(rng, num_items, size, a):
    z = rng.zipf(a, size=size * 2)
    z = z[z <= num_items][:size]
    while len(z) < size:
        more = rng.zipf(a, size=size)
        z = np.concatenate([z, more[more <= num_items]])[:size]
    return (z - 1).astype(np.uint32)


def generate_q64(cfg: Q64Config, num_devices: int, seed: int = 0):
    """(ss[N,4], sr[R,2], cs[M,3], cr[Q,3], date[D,2]) as u32.

    ss: item, ticket, sold_date, price.  sr: item, ticket.
    cs: item, order, price.              cr: item, order, refund.
    date: date_sk, year (0 = Y, 1 = Y+1, 2 = other -> filtered).
    Tickets/orders are globally unique (row index), so (item, key) pairs
    are unique — the join-on-pair contract of the real tables."""
    assert cfg.num_items < (1 << _KEY_BITS)
    rng = np.random.default_rng(seed)
    n_ss = cfg.ss_rows_per_device * num_devices
    n_cs = cfg.cs_rows_per_device * num_devices
    assert max(n_ss, n_cs) < (1 << _KEY_BITS)
    ss = np.stack([
        _zipf_items(rng, cfg.num_items, n_ss, cfg.zipf_a),
        np.arange(n_ss, dtype=np.uint32),
        rng.integers(0, cfg.num_dates, n_ss).astype(np.uint32),
        rng.integers(0, 1000, n_ss).astype(np.uint32),
    ], axis=1)
    sr_rows = rng.permutation(n_ss)[: int(n_ss * cfg.sr_fraction)]
    sr = ss[np.sort(sr_rows)][:, :2].copy()
    cs = np.stack([
        _zipf_items(rng, cfg.num_items, n_cs, cfg.zipf_a),
        np.arange(n_cs, dtype=np.uint32),
        rng.integers(0, 1000, n_cs).astype(np.uint32),
    ], axis=1)
    cr_rows = rng.permutation(n_cs)[: int(n_cs * cfg.cr_fraction)]
    cr = np.concatenate(
        [cs[np.sort(cr_rows)][:, :2],
         rng.integers(0, 1000, len(cr_rows)).astype(np.uint32)
         .reshape(-1, 1)], axis=1)
    date = np.stack([
        np.arange(cfg.num_dates, dtype=np.uint32),
        ((np.arange(cfg.num_dates) + cfg.first_year_mod) % 3)
        .astype(np.uint32),
    ], axis=1)
    return ss, sr, cs, cr, date


def numpy_q64(ss, sr, cs, cr, date, cfg: Q64Config) -> Tuple[int, int]:
    """Oracle: (qualifying item count, sum of both years' price sums)."""
    year = dict(zip(date[:, 0].tolist(), date[:, 1].tolist()))
    # cs_ui: join cr on (item, order), group by item, HAVING
    refund_by_pair = {(i, o): r for i, o, r in cr.tolist()}
    sale: dict = {}
    refund: dict = {}
    for i, o, p in cs.tolist():
        sale[i] = sale.get(i, 0) + p
        refund[i] = refund.get(i, 0) + refund_by_pair.get((i, o), 0)
    ui = {i for i in sale if sale[i] > 2 * refund[i]}
    # store_sales ⋈ store_returns (inner) ⋈ date ⋈ cs_ui (semi)
    returned_pairs = {(i, t) for i, t in sr.tolist()}
    cnt = {}
    psum = {}
    for i, t, d, p in ss.tolist():
        if (i, t) not in returned_pairs or i not in ui:
            continue
        y = year.get(d)
        if y not in (0, 1):
            continue
        cnt[(i, y)] = cnt.get((i, y), 0) + 1
        psum[(i, y)] = psum.get((i, y), 0) + p
    # CTE self-join across years: cnt(Y+1) <= cnt(Y)
    items = 0
    total = 0
    for i in ui:
        c0, c1 = cnt.get((i, 0), 0), cnt.get((i, 1), 0)
        if c0 > 0 and c1 > 0 and c1 <= c0:
            items += 1
            total += psum.get((i, 0), 0) + psum.get((i, 1), 0)
    return items, total


def make_q64_step(mesh: Mesh, axis_name: str, cfg: Q64Config,
                  impl: str = "auto"):
    """q64 as FIVE chained exchange rounds in one jitted SPMD step.

    1. catalog_sales + catalog_returns by hash(item, order): pair join.
    2. joined rows by hash(item): per-item sale/refund sums -> cs_ui.
    3. store_sales + store_returns by hash(item, ticket): inner pair join.
    4. survivors + date_dim by hash(sold_date): year lookup + filter.
    5. survivors by hash(item): per-(item, year) aggregation, cs_ui
       semi-join, and the across-years CTE self-join (items co-located).
    Returns per-device ``(i32[D, 2], overflowed[D])`` partials."""
    n = mesh.shape[axis_name]
    impl = resolve_impl(mesh, impl, axis_name)
    spec = P(axis_name)
    cap_ss = cfg.ss_rows_per_device * cfg.out_factor
    cap_cs = cfg.cs_rows_per_device * cfg.out_factor

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec,) * 5, out_specs=(spec, spec))
    def step(ss, sr, cs, cr, date):
        all_valid = jnp.ones  # shorthand

        # -- round 1: catalog pair join ---------------------------------
        cs_pk = _pairkey(cs[:, 0], cs[:, 1])
        cs_r, cs_v, o1 = _exchange(
            jnp.concatenate([cs, cs_pk[:, None]], axis=1),
            _route(cs_pk, all_valid(cs.shape[0], bool), n),
            axis_name, n, cap_cs, impl)
        cr_pk = _pairkey(cr[:, 0], cr[:, 1])
        cr_r, cr_v, o2 = _exchange(
            jnp.concatenate([cr, cr_pk[:, None]], axis=1),
            _route(cr_pk, all_valid(cr.shape[0], bool), n),
            axis_name, n, cap_cs, impl)
        refund, _found = _lookup(cr_r[:, 3], cr_v, cr_r[:, 2],
                                 jnp.where(cs_v, cs_r[:, 3], PAD))
        refund = jnp.where(_found, refund, jnp.uint32(0))

        # -- round 2: group catalog by item -> cs_ui --------------------
        joined = jnp.stack([cs_r[:, 0], cs_r[:, 2], refund], axis=1)
        j_r, j_v, o3 = _exchange(
            joined, _route(cs_r[:, 0], cs_v, n), axis_name, n,
            cap_cs, impl)
        ik = jnp.where(j_v, j_r[:, 0], PAD)
        perm = jnp.argsort(ik)
        ik_s = jnp.take(ik, perm)
        j_s = jnp.take(j_r, perm, axis=0)
        Ncs = ik_s.shape[0]
        new_seg = jnp.concatenate([jnp.ones(1, bool),
                                   ik_s[1:] != ik_s[:-1]])
        si = jnp.cumsum(new_seg.astype(jnp.int32)) - 1
        live = ik_s != PAD
        sale_sum = jax.ops.segment_sum(
            jnp.where(live, j_s[:, 1], 0).astype(jnp.int32), si,
            num_segments=Ncs)
        refund_sum = jax.ops.segment_sum(
            jnp.where(live, j_s[:, 2], 0).astype(jnp.int32), si,
            num_segments=Ncs)
        seg_item = jax.ops.segment_max(ik_s, si, num_segments=Ncs)
        ui_flag = sale_sum > 2 * refund_sum
        # representative row per segment -> local (item, ui) table
        ui_item = jnp.where(ui_flag & (seg_item != PAD), seg_item, PAD)

        # -- round 3: store pair join (inner) ---------------------------
        ss_pk = _pairkey(ss[:, 0], ss[:, 1])
        ss_r, ss_v, o4 = _exchange(
            jnp.concatenate([ss, ss_pk[:, None]], axis=1),
            _route(ss_pk, all_valid(ss.shape[0], bool), n),
            axis_name, n, cap_ss, impl)
        sr_pk = _pairkey(sr[:, 0], sr[:, 1])
        sr_r, sr_v, o5 = _exchange(
            jnp.concatenate([sr, sr_pk[:, None]], axis=1),
            _route(sr_pk, all_valid(sr.shape[0], bool), n),
            axis_name, n, cap_ss, impl)
        _, ret_found = _lookup(sr_r[:, 2], sr_v, sr_r[:, 2],
                               jnp.where(ss_v, ss_r[:, 4], PAD))
        surv_v = ss_v & ret_found

        # -- round 4: date join on survivors ----------------------------
        d_r, d_v, o6 = _exchange(
            date, _route(date[:, 0], all_valid(date.shape[0], bool), n),
            axis_name, n, _dim_cap(date.shape[0], n), impl)
        s2, s2_v, o7 = _exchange(
            ss_r[:, :4], _route(ss_r[:, 2], surv_v, n),
            axis_name, n, cap_ss, impl)
        year, y_found = _lookup(d_r[:, 0], d_v, d_r[:, 1],
                                jnp.where(s2_v, s2[:, 2], PAD))
        in_years = y_found & (year <= 1)
        s2_v = s2_v & in_years

        # -- round 5: group by item; semi-join cs_ui; CTE self-join -----
        rows5 = jnp.stack([s2[:, 0], year, s2[:, 3]], axis=1)
        r5, v5, o8 = _exchange(rows5, _route(s2[:, 0], s2_v, n),
                               axis_name, n, cap_ss, impl)
        ik5 = jnp.where(v5, r5[:, 0], PAD)
        perm5 = jnp.argsort(ik5)
        ik5_s = jnp.take(ik5, perm5)
        r5_s = jnp.take(r5, perm5, axis=0)
        N5 = ik5_s.shape[0]
        ns5 = jnp.concatenate([jnp.ones(1, bool), ik5_s[1:] != ik5_s[:-1]])
        si5 = jnp.cumsum(ns5.astype(jnp.int32)) - 1
        live5 = ik5_s != PAD
        y1 = live5 & (r5_s[:, 1] == 1)
        y0 = live5 & (r5_s[:, 1] == 0)
        cnt0 = jax.ops.segment_sum(y0.astype(jnp.int32), si5,
                                   num_segments=N5)
        cnt1 = jax.ops.segment_sum(y1.astype(jnp.int32), si5,
                                   num_segments=N5)
        sum01 = jax.ops.segment_sum(
            jnp.where(live5, r5_s[:, 2], 0).astype(jnp.int32), si5,
            num_segments=N5)
        item5 = jax.ops.segment_max(ik5_s, si5, num_segments=N5)
        # semi-join against this device's cs_ui slice: items were routed
        # by the SAME hash in rounds 2 and 5, so the lookup is local
        _, is_ui = _lookup(ui_item, ui_item != PAD, ui_item, item5)
        qual = is_ui & (item5 != PAD) & (cnt0 > 0) & (cnt1 > 0) \
            & (cnt1 <= cnt0)
        items = qual.astype(jnp.int32).sum()
        total = jnp.where(qual, sum01, 0).sum()
        overflowed = o1 | o2 | o3 | o4 | o5 | o6 | o7 | o8
        return jnp.stack([items, total])[None], overflowed[None]

    return step


def run_q64(mesh: Mesh, cfg: Q64Config, axis_name: str = "shuffle",
            seed: int = 0, impl: str = "auto") -> Tuple[int, int]:
    """Host driver: returns the exact global q64 answer."""
    n = mesh.shape[axis_name]
    ss, sr, cs, cr, date = generate_q64(cfg, n, seed)
    step = make_q64_step(mesh, axis_name, cfg, impl)
    shard = NamedSharding(mesh, P(axis_name))
    args = [jax.device_put(pad_rows_to_devices(t, n), shard)
            for t in (ss, sr, cs, cr, date)]
    partial, overflowed = jax.block_until_ready(step(*args))
    if np.asarray(overflowed).any():
        raise OverflowError("q64 exchange overflowed; raise out_factor")
    totals = np.asarray(partial).sum(axis=0).astype(np.int64)
    return int(totals[0]), int(totals[1])


def pad_rows_to_devices(table: np.ndarray, n: int) -> np.ndarray:
    """Pad a global table to a device multiple with PAD rows (dead keys
    never match a lookup and never route anywhere)."""
    rem = (-len(table)) % n
    if rem == 0:
        return table
    padding = np.full((rem, table.shape[1]), PAD, dtype=table.dtype)
    return np.concatenate([table, padding])


# ===========================================================================
# engine-DAG variants (the drop-in SPI path)
# ===========================================================================


def _engine_dep(num_partitions: int, width: int):
    from sparkrdma_tpu.shuffle.manager import PartitionerSpec
    from sparkrdma_tpu.shuffle.spark_compat import ShuffleDependency

    return ShuffleDependency(num_partitions, PartitionerSpec("modulo"),
                             row_payload_bytes=4 * width)


def _engine_src(table: np.ndarray, keyfn, num_maps: int):
    """Source-stage task fn: stripe ``table`` across map tasks, write
    u32 rows keyed by ``keyfn(rows) -> u64``."""
    width = table.shape[1] * 4

    def fn(ctx, writer, task, _t=table, _w=width):
        rows = _t[task::num_maps]
        writer.write((keyfn(rows), np.ascontiguousarray(rows, "<u4")
                      .view(np.uint8).reshape(len(rows), _w)))
    return fn


def _read_u32(ctx, parent: int, width: int):
    """Drain one parent shuffle into (keys u64[N], cols u32[N, width])."""
    ks, vs = [], []
    for keys, payload in ctx.read(parent).readBatches():
        ks.append(keys)
        vs.append(np.ascontiguousarray(payload).view("<u4")
                  .reshape(len(keys), -1))
    if not ks:
        return np.zeros(0, np.uint64), np.zeros((0, width), np.uint32)
    return np.concatenate(ks), np.concatenate(vs)


def _np_lookup(dkeys, dattr, probes):
    """Vectorized unique-key join: (attr[N] u32, found[N] bool)."""
    if len(dkeys) == 0:
        return (np.zeros(len(probes), np.uint32),
                np.zeros(len(probes), bool))
    order = np.argsort(dkeys)
    ks, at = dkeys[order], dattr[order]
    idx = np.clip(np.searchsorted(ks, probes), 0, len(ks) - 1)
    return at[idx].astype(np.uint32), ks[idx] == probes


def build_q95_job(cfg: Q95Config, num_maps: int, num_partitions: int,
                  seed: int = 0, data_scale: int = 1):
    """q95 as a stage DAG for ``engine.DAGEngine.run``: five sources,
    three dimension shuffle-join MapStages, a final by-order ResultStage
    — seven shuffles through the SPI. Returns (result_stage, finish)."""
    from sparkrdma_tpu.engine import MapStage, ResultStage

    ws, wr, date, addr, site = generate_q95(cfg, data_scale, seed)

    def dep(width):
        return _engine_dep(num_partitions, width)

    def col(key_col):
        return lambda rows, _k=key_col: rows[:, _k].astype(np.uint64)

    # working rows carry an extra flags column (col 7)
    ws8 = np.concatenate(
        [ws, np.zeros((len(ws), 1), np.uint32)], axis=1)
    ws_st = MapStage(num_maps, dep(8),
                     _engine_src(ws8, col(2), num_maps))   # by ship_date
    date_st = MapStage(num_maps, dep(2), _engine_src(date, col(0), num_maps))
    addr_st = MapStage(num_maps, dep(2), _engine_src(addr, col(0), num_maps))
    site_st = MapStage(num_maps, dep(2), _engine_src(site, col(0), num_maps))
    wr_st = MapStage(num_maps, dep(1),
                     _engine_src(wr, col(0), num_maps))    # by order

    lo, hi = cfg.window_start, cfg.window_start + 60

    def join_stage(key_col, next_key_col, flag_bit, pred):
        def fn(ctx, writer, task, _k=key_col, _nk=next_key_col,
               _b=flag_bit, _p=pred):
            _, rows = _read_u32(ctx, 0, 8)
            dkeys, dcols = _read_u32(ctx, 1, 2)
            attr, found = _np_lookup(dkeys, dcols[:, 1],
                                     rows[:, _k].astype(np.uint64))
            ok = found & _p(attr)
            rows = rows.copy()
            rows[:, 7] |= np.where(ok, np.uint32(_b), np.uint32(0))
            writer.write((rows[:, _nk].astype(np.uint64),
                          np.ascontiguousarray(rows, "<u4").view(np.uint8)
                          .reshape(len(rows), 32)))
            del task
        return fn

    j1 = MapStage(num_partitions, dep(8),
                  join_stage(2, 3, 1, lambda d: (d >= lo) & (d < hi)),
                  parents=[ws_st, date_st])
    j2 = MapStage(num_partitions, dep(8),
                  join_stage(3, 4, 2, lambda s: s == cfg.target_state),
                  parents=[j1, addr_st])
    j3 = MapStage(num_partitions, dep(8),
                  join_stage(4, 0, 4, lambda c: c == cfg.target_company),
                  parents=[j2, site_st])

    def final_fn(ctx, task):
        _, rows = _read_u32(ctx, 0, 8)
        wr_keys, _wr_rows = _read_u32(ctx, 1, 1)
        returned = set(wr_keys.tolist())
        wh_by_order: dict = {}
        for o, w in zip(rows[:, 0].tolist(), rows[:, 1].tolist()):
            wh_by_order.setdefault(o, set()).add(w)
        multi = {o for o, s in wh_by_order.items() if len(s) > 1}
        orders = set()
        cost = profit = 0
        for r in rows.tolist():
            o = r[0]
            if r[7] == 7 and o in multi and o in returned:
                orders.add(o)
                cost += r[5]
                profit += r[6]
        del task
        return len(orders), cost, profit

    result = ResultStage(num_partitions, final_fn, parents=[j3, wr_st])

    def finish(results):
        return (sum(r[0] for r in results), sum(r[1] for r in results),
                sum(r[2] for r in results))

    return result, finish


def build_q64_job(cfg: Q64Config, num_maps: int, num_partitions: int,
                  seed: int = 0, data_scale: int = 1):
    """q64 as a stage DAG: five sources, catalog pair-join, catalog
    group-by(item) -> cs_ui, store pair-join, date join, final by-item
    ResultStage with the across-years CTE self-join — eight shuffles
    through the SPI. Returns (result_stage, finish)."""
    from sparkrdma_tpu.engine import MapStage, ResultStage

    ss, sr, cs, cr, date = generate_q64(cfg, data_scale, seed)

    def dep(width):
        return _engine_dep(num_partitions, width)

    def pair_u64(rows):
        return (rows[:, 0].astype(np.uint64) << _KEY_BITS) | \
            rows[:, 1].astype(np.uint64)

    def col0_u64(rows):
        return rows[:, 0].astype(np.uint64)

    cs_st = MapStage(num_maps, dep(3), _engine_src(cs, pair_u64, num_maps))
    cr_st = MapStage(num_maps, dep(3), _engine_src(cr, pair_u64, num_maps))
    ss_st = MapStage(num_maps, dep(4), _engine_src(ss, pair_u64, num_maps))
    sr_st = MapStage(num_maps, dep(2), _engine_src(sr, pair_u64, num_maps))
    date_st = MapStage(num_maps, dep(2),
                       _engine_src(date, col0_u64, num_maps))

    def cat_join_fn(ctx, writer, task):
        cs_keys, cs_rows = _read_u32(ctx, 0, 3)
        cr_keys, cr_rows = _read_u32(ctx, 1, 3)
        refund_by_pair = dict(zip(cr_keys.tolist(),
                                  cr_rows[:, 2].tolist()))
        refunds = np.array([refund_by_pair.get(k, 0)
                            for k in cs_keys.tolist()], np.uint32)
        out = np.stack([cs_rows[:, 0], cs_rows[:, 2], refunds], axis=1)
        writer.write((cs_rows[:, 0].astype(np.uint64),
                      np.ascontiguousarray(out, "<u4").view(np.uint8)
                      .reshape(len(out), 12)))
        del task

    cat_join = MapStage(num_partitions, dep(3), cat_join_fn,
                        parents=[cs_st, cr_st])

    def ui_fn(ctx, writer, task):
        _, rows = _read_u32(ctx, 0, 3)
        sale: dict = {}
        refund: dict = {}
        for i, p, r in rows.tolist():
            sale[i] = sale.get(i, 0) + p
            refund[i] = refund.get(i, 0) + r
        ui = np.array([i for i in sale if sale[i] > 2 * refund[i]],
                      np.uint32).reshape(-1, 1)
        writer.write((ui[:, 0].astype(np.uint64),
                      np.ascontiguousarray(ui, "<u4").view(np.uint8)
                      .reshape(len(ui), 4)))
        del task

    ui_st = MapStage(num_partitions, dep(1), ui_fn, parents=[cat_join])

    def store_join_fn(ctx, writer, task):
        ss_keys, ss_rows = _read_u32(ctx, 0, 4)
        sr_keys, _ = _read_u32(ctx, 1, 2)
        returned = set(sr_keys.tolist())
        keep = np.array([k in returned for k in ss_keys.tolist()], bool)
        rows = ss_rows[keep]
        writer.write((rows[:, 2].astype(np.uint64),   # by sold_date
                      np.ascontiguousarray(rows, "<u4").view(np.uint8)
                      .reshape(len(rows), 16)))
        del task

    store_join = MapStage(num_partitions, dep(4), store_join_fn,
                          parents=[ss_st, sr_st])

    def date_join_fn(ctx, writer, task):
        _, rows = _read_u32(ctx, 0, 4)
        dkeys, dcols = _read_u32(ctx, 1, 2)
        year = dict(zip(dkeys.tolist(), dcols[:, 1].tolist()))
        ys = np.array([year.get(d, 99) for d in rows[:, 2].tolist()],
                      np.uint32)
        keep = ys <= 1
        out = np.stack([rows[:, 0][keep], ys[keep], rows[:, 3][keep]],
                       axis=1)
        writer.write((out[:, 0].astype(np.uint64),    # by item
                      np.ascontiguousarray(out, "<u4").view(np.uint8)
                      .reshape(len(out), 12)))
        del task

    date_join = MapStage(num_partitions, dep(3), date_join_fn,
                         parents=[store_join, date_st])

    def final_fn(ctx, task):
        _, rows = _read_u32(ctx, 0, 3)
        ui_keys, _ = _read_u32(ctx, 1, 1)
        ui = set(ui_keys.tolist())
        cnt: dict = {}
        psum: dict = {}
        for i, y, p in rows.tolist():
            if i not in ui:
                continue
            cnt[(i, y)] = cnt.get((i, y), 0) + 1
            psum[(i, y)] = psum.get((i, y), 0) + p
        items = total = 0
        for i in {i for i, _y in cnt}:
            c0, c1 = cnt.get((i, 0), 0), cnt.get((i, 1), 0)
            if c0 > 0 and c1 > 0 and c1 <= c0:
                items += 1
                total += psum.get((i, 0), 0) + psum.get((i, 1), 0)
        del task
        return items, total

    result = ResultStage(num_partitions, final_fn,
                         parents=[date_join, ui_st])

    def finish(results):
        return (sum(r[0] for r in results), sum(r[1] for r in results))

    return result, finish
