"""The ICI data plane: size-exchange + ragged all-to-all.

This is the TPU-native replacement for the reference's entire one-sided READ
data path (scala/RdmaShuffleFetcherIterator.scala:119-180 — the M×R matrix of
scatter RDMA READs), and for its metadata location reads (293-315): on a TPU
mesh the exchange is a *collective*, so the "remote CPU bypass" property the
reference buys with RDMA verbs comes for free from the ICI fabric — no host
is involved once the step is launched.

Scheme (per device, inside ``shard_map`` over the shuffle axis):

1. **Size exchange** — ``all_gather`` of each device's ``send_counts`` row
   builds the D×D count matrix (the analogue of reading every map's
   ``RdmaMapTaskOutput`` table: it tells everyone where everything goes).
   O(D²) int32s — negligible next to the payload, like the reference's
   16-byte entries.
2. **Data exchange** — ``lax.ragged_all_to_all`` moves the ragged
   destination-grouped rows over ICI. Receiver-side landing offsets are
   column-wise exclusive prefix sums of the count matrix, so the result is
   densely packed, grouped by source — the same layout a reducer sees after
   the reference's grouped fetches.

Everything is static-shape: ``data`` and ``output`` are fixed-capacity
buffers; raggedness lives in the offset/size vectors, which is what keeps
XLA happy (no dynamic shapes under jit).

Four transports (``impl``):

* ``"native"`` — ``lax.ragged_all_to_all`` (TPU; switch-routed ICI; the
  v5e compiler accepts it only up to 16 chips — larger slices have
  limited ICI routing and reject the opcode, so ``resolve_impl``
  probe-compiles per mesh).
* ``"dense"`` — ``lax.all_to_all`` over fixed per-pair slots (supported
  at every scale): each (source, dest) pair gets ``out_capacity / D``
  slot rows; skew past a slot raises the callers' overflow flag exactly
  like a capacity overflow. Bandwidth = the padded capacity, i.e. an
  ``out_factor``-bounded overhead instead of gather's D× — the auto
  fallback where native is rejected.
* ``"gather"`` — decomposed ``all_gather`` + mask-compaction, D×
  bandwidth; the last-resort oracle (XLA:CPU validation meshes use it as
  the reference semantics).
* ``"ring"`` / ``"ring_interpret"`` — the hand-scheduled Pallas ring kernel
  (``ops.ring_exchange``): explicit chip-to-chip async remote DMAs, the
  closest structural analogue of the reference's one-sided verbs engine;
  available through the chunked exchange, whose static per-pair quota gives
  the ring its block shape.
"""

from __future__ import annotations

import functools
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.utils.compat import shard_map

# Host-side dispatch tally for the ICI data plane. Callers that launch a
# collective exchange (mesh_service, models) record here so tests and the
# engine can assert that a job's shuffle bytes actually crossed the mesh
# rather than the TCP fetch path (the reference's equivalent evidence is
# its verbs counters vs. socket counters).
DATA_PLANE = {"exchanges": 0, "rows": 0}
_DATA_PLANE_LOCK = threading.Lock()


def record_exchange(rows: int) -> None:
    """Tally one dispatched collective exchange moving ``rows`` rows."""
    with _DATA_PLANE_LOCK:
        DATA_PLANE["exchanges"] += 1
        DATA_PLANE["rows"] += int(rows)


def _exclusive_cumsum(x: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    return jnp.cumsum(x, axis=axis) - x


def _slot_fill(data: jnp.ndarray, starts: jnp.ndarray, counts: jnp.ndarray,
               n: int, q: int):
    """Fill fixed per-destination slots: result ``[n*q, ...]`` where slot
    (j, k) holds row ``starts[j] + k`` of ``data`` when ``k < counts[j]``
    and zeros otherwise. Shared by the dense transport and the
    chunked-ring round (their block shape IS this slot layout)."""
    cap = data.shape[0]
    slot = jnp.arange(n * q, dtype=jnp.int32)
    dest_of_slot = jnp.minimum(slot // q, n - 1)
    within = slot - dest_of_slot * q
    src_idx = starts[dest_of_slot] + within
    valid = within < counts[dest_of_slot]
    picked = jnp.take(
        data, jnp.where(valid, jnp.minimum(src_idx, cap - 1), 0), axis=0)
    vmask = valid.reshape((-1,) + (1,) * (data.ndim - 1))
    return jnp.where(vmask, picked, 0), valid, dest_of_slot, within


def _pack_by_source(blocks: jnp.ndarray, recv_counts: jnp.ndarray,
                    base: jnp.ndarray) -> jnp.ndarray:
    """Compact per-source slot blocks ``[n, q, ...]`` into ``base``-shaped
    packed rows grouped by source (``recv_counts[j] <= q`` rows from
    source j, in slot order); ``base`` supplies rows past the total."""
    n, q = blocks.shape[0], blocks.shape[1]
    out_len = base.shape[0]
    off = _exclusive_cumsum(recv_counts)
    cum = jnp.cumsum(recv_counts)
    pos = jnp.arange(out_len, dtype=jnp.int32)
    src_of_pos = jnp.minimum(
        jnp.sum(pos[:, None] >= cum[None, :], axis=1), n - 1)
    flat_idx = src_of_pos * q + jnp.minimum(pos - off[src_of_pos], q - 1)
    packed = jnp.take(blocks.reshape((n * q,) + blocks.shape[2:]),
                      flat_idx, axis=0)
    mask = (pos < cum[-1]).reshape((-1,) + (1,) * (base.ndim - 1))
    return jnp.where(mask, packed, base)


def ragged_exchange_shard(data: jnp.ndarray, send_counts: jnp.ndarray,
                          axis_name: str,
                          output: Optional[jnp.ndarray] = None,
                          impl: str = "native",
                          ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                     jnp.ndarray]:
    """Per-shard ragged all-to-all. Call inside ``shard_map``.

    Args:
      data: ``[capacity, ...]`` local rows, grouped by destination device in
        axis order (rows for device 0 first, then device 1, ...). Rows beyond
        ``send_counts.sum()`` are padding and are not sent.
      send_counts: ``i32[D]`` — rows destined for each device.
      axis_name: mesh axis to exchange over.
      output: optional ``[out_capacity, ...]`` buffer to receive into
        (defaults to a zeroed buffer shaped like ``data``).
      impl: ``"native"`` uses ``lax.ragged_all_to_all`` (TPU: rides ICI
        with no padding overhead); ``"dense"`` is fixed per-pair slots
        over ``lax.all_to_all`` (every topology; padding bounded by
        out_factor; pair skew past a slot trips the overflow flag);
        ``"gather"`` is the ``all_gather`` + mask-compaction oracle
        (D× bandwidth; XLA:CPU validation meshes). Identical results
        whenever dense's slots fit.

    Returns:
      ``(received, recv_counts, recv_offsets, overflowed)`` where
      ``received`` is packed grouped-by-source, ``recv_counts[j]`` is rows
      received from device j, ``recv_offsets`` is their exclusive prefix
      (start of each source's segment in ``received``), and ``overflowed``
      is a bool scalar: True when this shard's receive exceeded
      ``out_capacity`` OR (dense transport) some pair exceeded its fixed
      slot. When it is set, ``received`` is truncated — counts/offsets stay
      real, but callers MUST check the flag before trusting the rows
      (remedy: raise ``out_factor`` / chunk into rounds).
    """
    send_counts = send_counts.astype(jnp.int32)
    # 1. size exchange: full D x D count matrix; mat[j, i] = j sends to i.
    mat = lax.all_gather(send_counts, axis_name, axis=0, tiled=False)
    my = lax.axis_index(axis_name)

    input_offsets = _exclusive_cumsum(send_counts)
    send_sizes = send_counts
    # Landing offset of MY slice on receiver i = sum of what devices before
    # me send to i (column-wise exclusive prefix, my row).
    output_offsets = _exclusive_cumsum(mat, axis=0)[my]
    recv_sizes = mat[:, my]

    if output is None:
        output = jnp.zeros_like(data)
    # 2. data exchange over ICI.
    if impl in ("dense", "ring", "ring_interpret") \
            and output.shape[0] < mat.shape[0]:
        # q = out_cap // D would be zero: no slot can carry even one row.
        # gather handles any capacity; static shapes make this a
        # trace-time branch
        impl = "gather"
    pair_overflow = jnp.bool_(False)
    if impl == "native":
        received = lax.ragged_all_to_all(
            data, output, input_offsets, send_sizes, output_offsets, recv_sizes,
            axis_name=axis_name)
    elif impl == "dense":
        received, recv_sizes, pair_overflow = _dense_exchange(
            data, mat, my, output, axis_name)
    elif impl in ("ring", "ring_interpret"):
        received, recv_sizes, pair_overflow = _ring_exchange(
            data, mat, my, output, axis_name,
            interpret=(impl == "ring_interpret"))
    elif impl == "gather":
        received = _gather_exchange(data, mat, my, output, axis_name)
    else:
        raise ValueError(f"unknown exchange impl {impl!r}")
    overflowed = pair_overflow | (jnp.sum(recv_sizes) > output.shape[0])
    return received, recv_sizes, _exclusive_cumsum(recv_sizes), overflowed


def _dense_exchange(data: jnp.ndarray, mat: jnp.ndarray, my: jnp.ndarray,
                    output: jnp.ndarray, axis_name: str):
    """Fixed-slot ``lax.all_to_all`` exchange: every (src, dst) pair owns
    ``Q = out_capacity // D`` slot rows (any ``out_capacity % D``
    remainder rows are unused headroom).

    Exact (bit-identical to native/gather) whenever no pair exceeds its
    slot; a pair overflow is reported as an explicit bool (third return
    value) that ``ragged_exchange_shard`` folds into its ``overflowed``
    flag — receive counts are always the TRUE per-source counts (remedy
    for an overflow is the same as for capacity: raise ``out_factor``,
    which grows Q). Unlike ragged-all-to-all this lowers on every
    topology (plain all-to-all) and on XLA:CPU, so the path is
    executable in CI.
    """
    n = mat.shape[0]
    out_cap = output.shape[0]
    q = out_cap // n
    counts = mat[my]                      # what I send to each dest
    send, _, _, _ = _slot_fill(data, _exclusive_cumsum(counts), counts, n, q)
    got = lax.all_to_all(send.reshape((n, q) + data.shape[1:]), axis_name,
                         split_axis=0, concat_axis=0)

    recv_true = mat[:, my]
    received = _pack_by_source(got, jnp.minimum(recv_true, q), output)
    # pair overflow (anyone sent me more than a slot): explicit flag;
    # counts stay true so offsets derived from them are never garbage
    return received, recv_true, (recv_true > q).any()


def _ring_move_blocks(blocks: jnp.ndarray, axis_name: str, n: int,
                      interpret: bool) -> jnp.ndarray:
    """Move per-destination blocks ``[n, ...]`` (row j -> device j) with
    the Pallas ring kernel; returns the per-source received blocks, same
    shape. Mosaic remote-DMA slices need the lane (last) dim 128-aligned,
    so each block travels as flat words reshaped to [*, 128] lanes
    (padded by <128 words when the block size isn't a lane multiple) and
    is unflattened on arrival."""
    from sparkrdma_tpu.ops.ring_exchange import ring_all_to_all_shard

    words = int(np.prod(blocks.shape[1:]))
    lanes = -(-words // 128) * 128
    flat = blocks.reshape(n, words)
    if lanes != words:
        flat = jnp.pad(flat, ((0, 0), (0, lanes - words)))
    got = ring_all_to_all_shard(flat.reshape(n, lanes // 128, 128),
                                axis_name, n, interpret=interpret)
    return got.reshape(n, lanes)[:, :words].reshape(blocks.shape)


def _ring_exchange(data: jnp.ndarray, mat: jnp.ndarray, my: jnp.ndarray,
                   output: jnp.ndarray, axis_name: str,
                   interpret: bool = False):
    """Fixed-slot exchange with the SAME slot layout and overflow
    semantics as ``_dense_exchange``, moved by the hand-scheduled Pallas
    ring (``ops.ring_exchange``) instead of ``lax.all_to_all``: explicit
    chip-to-chip async remote DMAs, neighbor-hop traffic only — the
    production transport for slices whose compiler rejects
    ragged-all-to-all and whose topology favors ring traffic
    (O(D/2) blocks per link) over switch routing. Bit-identical to
    dense/native/gather whenever no pair exceeds its slot."""
    n = mat.shape[0]
    q = output.shape[0] // n
    counts = mat[my]
    send, _, _, _ = _slot_fill(data, _exclusive_cumsum(counts), counts, n, q)
    got = _ring_move_blocks(send.reshape((n, q) + data.shape[1:]),
                            axis_name, n, interpret)
    recv_true = mat[:, my]
    received = _pack_by_source(got, jnp.minimum(recv_true, q), output)
    return received, recv_true, (recv_true > q).any()


def _gather_exchange(data: jnp.ndarray, mat: jnp.ndarray, my: jnp.ndarray,
                     output: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Decomposed ragged exchange: all_gather everything, keep what's mine.

    Bandwidth is D× the native path (every row visits every device), which is
    fine for validation meshes; results are bit-identical to the native path:
    rows packed grouped-by-source, stable within source.
    """
    num_dev, capacity = mat.shape[0], data.shape[0]
    rows_all = lax.all_gather(data, axis_name, axis=0, tiled=False)  # [D, cap, ...]
    # Reconstruct each row's destination from the count matrix (rows are
    # destination-grouped per sender): row i of sender j targets the bucket
    # whose cumulative count straddles i; i >= total(j) is padding (-> D).
    bounds = jnp.cumsum(mat, axis=1)  # [D, D] inclusive per-sender
    row_idx = jnp.arange(capacity, dtype=jnp.int32)
    dest_all = jnp.sum(row_idx[None, :, None] >= bounds[:, None, :],
                       axis=-1)  # [D, cap] in [0, D]
    keep = dest_all == my
    order = (jnp.arange(num_dev, dtype=jnp.int32)[:, None] * capacity
             + row_idx[None, :])
    key = jnp.where(keep, order, jnp.int32(num_dev * capacity)).reshape(-1)
    perm = jnp.argsort(key, stable=True)
    flat = rows_all.reshape((num_dev * capacity,) + rows_all.shape[2:])
    # output capacity may exceed D*capacity (generous receive headroom);
    # pad the permutation with index 0 — those slots are masked off below
    # (total received rows can never exceed D*capacity)
    out_cap = output.shape[0]
    k = min(out_cap, num_dev * capacity)
    sel = jnp.zeros(out_cap, dtype=perm.dtype).at[:k].set(perm[:k])
    packed = jnp.take(flat, sel, axis=0)
    total = jnp.sum(mat[:, my])
    mask = jnp.arange(out_cap) < total
    mask = mask.reshape((-1,) + (1,) * (output.ndim - 1))
    return jnp.where(mask, packed, output)


def group_by_destination(data: jnp.ndarray, dest: jnp.ndarray,
                         num_partitions: int,
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stable local grouping of rows by destination partition.

    The local analogue of the reference writer's sort-by-partition spill
    (its wrapped SortShuffleWriter produces partition-contiguous files,
    writer/wrapper/RdmaWrapperShuffleWriter.scala:83-99). Rows with
    ``dest >= num_partitions`` or ``dest < 0`` are treated as padding: they
    sort to the end and don't count.

    Returns ``(grouped_rows, counts)`` with ``counts: i32[num_partitions]``.
    """
    dest = jnp.where((dest < 0) | (dest >= num_partitions),
                     num_partitions, dest.astype(jnp.int32))
    order = jnp.argsort(dest, stable=True)
    grouped = jnp.take(data, order, axis=0)
    counts = jnp.bincount(dest, length=num_partitions + 1)[:num_partitions]
    return grouped, counts.astype(jnp.int32)


def shuffle_shard(data: jnp.ndarray, dest: jnp.ndarray, axis_name: str,
                  num_devices: int,
                  output: Optional[jnp.ndarray] = None,
                  impl: str = "native"):
    """Full per-shard shuffle step: group locally by destination device,
    then ragged-exchange. Returns (received, recv_counts, recv_offsets,
    overflowed) — see ``ragged_exchange_shard``."""
    grouped, counts = group_by_destination(data, dest, num_devices)
    return ragged_exchange_shard(grouped, counts, axis_name, output, impl)


@functools.lru_cache(maxsize=32)
def _native_compiles(mesh: Mesh, axis_name: str) -> Tuple[bool, str]:
    """(supported, reason): whether THIS mesh's TPU compiler accepts
    ragged-all-to-all over ``axis_name``.

    Not every topology does: v5e slices above 16 chips have limited ICI
    routing and the opcode is rejected at compile time ("Ragged
    all-to-all is currently not supported in limited ICI routing
    settings" — found via AOT compile, tests/test_tpu_aot.py). One tiny
    throwaway compile per (mesh, axis), cached; the actual compiler
    error is preserved so a transient/unexpected failure is never
    misreported as a topology limit.
    """
    n = mesh.shape[axis_name]
    spec = P(axis_name)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,) * 4,
                       out_specs=spec)
    def probe(op, out, iof, sz):
        return lax.ragged_all_to_all(op[0], out[0], iof[0], sz[0], iof[0],
                                     sz[0], axis_name=axis_name)[None]

    sh = jax.sharding.NamedSharding(mesh, spec)
    arg = jax.ShapeDtypeStruct((n, n * 8), jnp.int32, sharding=sh)
    idx = jax.ShapeDtypeStruct((n, n), jnp.int32, sharding=sh)
    try:
        probe.lower(arg, arg, idx, idx).compile()
        return True, ""
    except Exception as e:  # noqa: BLE001 — any rejection means no
        return False, f"{type(e).__name__}: {e}"


def resolve_impl(mesh: Mesh, impl: str = "auto",
                 axis_name: Optional[str] = None) -> str:
    """``auto`` -> native on TPU meshes whose compiler supports the
    ragged-all-to-all opcode over the exchange axis, decomposed fallback
    elsewhere (XLA:CPU has no opcode at all; large v5e slices reject it
    for limited ICI routing — there the gather decomposition keeps
    results correct, and ``make_chunked_exchange(impl="ring")`` is the
    bandwidth-efficient alternative). ``axis_name`` defaults to the last
    mesh axis (the convention everywhere in this package)."""
    if impl != "auto":
        return impl
    platform = next(iter(mesh.devices.flat)).platform
    if platform != "tpu":
        return "gather"
    axis = axis_name or mesh.axis_names[-1]
    ok, reason = _native_compiles(mesh, axis)
    if ok:
        return "native"
    _warn_topology_once(mesh, axis, reason)
    return "dense"


def resolve_transport(mesh: Mesh, impl: str,
                      axis_name: Optional[str] = None) -> str:
    """The transport resolution every plan/build site shares: ring
    transports pass through verbatim (they are explicit asks, never
    probed), everything else goes through ``resolve_impl``'s per-mesh
    probe. One helper so the step builders and the cost model's plan
    sites can't drift apart."""
    return (impl if impl in ("ring", "ring_interpret")
            else resolve_impl(mesh, impl, axis_name))


# (mesh, axis) pairs whose topology-rejection warning already fired:
# only _native_compiles is cached, so without this memo EVERY
# resolve_impl call re-logged the same rejection — iterative stages
# (ALS supersteps, per-stage cost-model probes) flooded the log.
_topology_warned: set = set()
_TOPOLOGY_WARN_LOCK = threading.Lock()


def _warn_topology_once(mesh: Mesh, axis_name: str, reason: str) -> None:
    """Log the "topology rejects ragged-all-to-all" warning once per
    (mesh, axis); later resolutions of the same pair stay silent."""
    key = (mesh, axis_name)
    with _TOPOLOGY_WARN_LOCK:
        if key in _topology_warned:
            return
        _topology_warned.add(key)
    import logging

    logging.getLogger(__name__).warning(
        "this TPU topology rejects ragged-all-to-all; using the dense "
        "fixed-slot all-to-all transport (out_factor-bounded padding "
        "overhead; the chunked ring is the neighbor-traffic "
        "alternative). Compiler said: %s", reason[:300])


def bucket_quota(quota: int) -> int:
    """Round ``quota`` up to the next power of two — the memoization
    bucket for the chunked-exchange builders. Iterative stages derive
    per-round quotas from drifting byte budgets; memoizing per EXACT
    quota recompiled every superstep, while pow2 bucketing caps the
    compile count at log2(max quota) with identical results (quota only
    bounds per-round chunking, never the data moved). Rounding UP means
    a round may buffer up to 2x the requested quota — callers sizing
    quota against a hard memory bound should pass the pow2 at or below
    their budget."""
    return 1 << max(0, int(quota) - 1).bit_length()


def make_chunked_exchange(mesh: Mesh, axis_name: str, quota: int,
                          impl: str = "auto"):
    """Bounded-round ragged exchange for arbitrary skew; ``quota`` is
    bucketed to the next power of two (``bucket_quota``) before the
    memoized build, so drifting quotas share compiles. The returned
    ``round_fn``'s shapes are sized by the BUCKETED quota — drive the
    round loop with ``bucket_quota(quota)`` (``chunked_exchange`` does).
    See ``_make_chunked_exchange``."""
    return _make_chunked_exchange(mesh, axis_name, bucket_quota(quota),
                                  impl)


@functools.lru_cache(maxsize=128)
def _make_chunked_exchange(mesh: Mesh, axis_name: str, quota: int,
                           impl: str = "auto"):
    """Bounded-round ragged exchange for arbitrary skew. Memoized per
    (mesh, axis, quota, impl) so iterative callers (ALS) compile once.

    One round moves at most ``quota`` rows per (source, destination) pair,
    so a receiver never nets more than ``D * quota`` rows per round no
    matter how skewed the traffic — the collective analogue of the
    reference's bounded in-flight window + grouped fetches
    (scala/RdmaShuffleFetcherIterator.scala:240-276): total transfer is
    unbounded, per-round memory is not.

    Returns ``round_fn(grouped, counts, round_idx) -> (received[D*quota,...],
    recv_counts[D])`` to be driven by a host loop over
    ``ceil(max_pair_count / quota)`` rounds (the host knows counts — it
    computed them or fetched the size exchange). ``grouped`` must be
    destination-grouped rows with per-destination ``counts`` (as produced by
    ``group_by_destination``).
    """
    n = mesh.shape[axis_name]
    impl_resolved = resolve_transport(mesh, impl, axis_name)
    spec = P(axis_name)

    # pallas interpret-mode outputs confuse the vma checker when mixed
    # with collectives; disable it ONLY for the ring transports so the
    # static varying-axes check still guards the collective paths
    shard_kwargs = dict(mesh=mesh, in_specs=(spec, spec, None),
                        out_specs=(spec, spec))
    if impl_resolved in ("ring", "ring_interpret"):
        shard_kwargs["check_vma"] = False

    @jax.jit
    @functools.partial(shard_map, **shard_kwargs)
    def round_fn(grouped, counts, round_idx):
        received, recv_counts = _chunked_round_shard(
            grouped, counts, round_idx, axis_name, n, quota, impl_resolved)
        return received, recv_counts[None]

    return round_fn


def _chunked_round_shard(grouped, counts, round_idx, axis_name: str, n: int,
                         quota: int, impl_resolved: str):
    """One chunked round, inside shard_map: returns this round's received
    rows packed grouped-by-source plus per-source counts."""
    counts = counts.reshape(-1).astype(jnp.int32)
    seg_starts = _exclusive_cumsum(counts)
    # This round's slice of each destination segment:
    # [start + r*quota, start + min((r+1)*quota, count))
    lo = jnp.minimum(round_idx * quota, counts)
    hi = jnp.minimum(lo + quota, counts)
    send_counts = hi - lo
    # per-destination slot layout, shared with the dense transport
    filled, valid, dest_of_slot, within = _slot_fill(
        grouped, seg_starts + lo, send_counts, n, quota)

    if impl_resolved in ("ring", "ring_interpret"):
        # Hand-scheduled ICI transport (ops/ring_exchange.py): send rows
        # stay in natural [D, quota] block layout — no compaction needed
        # on the send side; the ring's fixed block shape IS the quota.
        got = _ring_move_blocks(
            filled.reshape((n, quota) + grouped.shape[1:]), axis_name, n,
            interpret=(impl_resolved == "ring_interpret"))
        mat = lax.all_gather(send_counts, axis_name, axis=0, tiled=False)
        my = lax.axis_index(axis_name)
        recv_counts = mat[:, my]
        # compact [D, quota] -> packed grouped-by-source (recv_counts
        # <= quota by construction)
        received = _pack_by_source(
            got, recv_counts,
            jnp.zeros((n * quota,) + grouped.shape[1:], grouped.dtype))
        return received, recv_counts

    # Collective transport: compact send buffer, destination-grouped.
    send_off = _exclusive_cumsum(send_counts)
    compact_idx = jnp.where(valid,
                            send_off[dest_of_slot] + within,
                            n * quota - 1)
    send_buf = jnp.zeros((n * quota,) + grouped.shape[1:], grouped.dtype)
    # scatter picked rows to their compact position (invalid rows all
    # collide harmlessly on the last slot, then get overwritten only by
    # at most one valid row — counts guarantee compact positions unique)
    send_buf = send_buf.at[compact_idx].set(filled)
    # overflow is impossible by construction here: per-pair send_counts
    # <= quota and the output capacity is exactly n * quota (= dense's
    # slot size), so the flag is statically dead — dropped
    received, recv_counts, _, _ = ragged_exchange_shard(
        send_buf, send_counts, axis_name, impl=impl_resolved)
    return received, recv_counts


def make_chunked_exchange_acc(mesh: Mesh, axis_name: str, quota: int,
                              impl: str = "auto"):
    """``make_chunked_exchange_acc`` with the same pow2 quota bucketing
    as ``make_chunked_exchange`` (see ``bucket_quota``)."""
    return _make_chunked_exchange_acc(mesh, axis_name,
                                      bucket_quota(quota), impl)


@functools.lru_cache(maxsize=128)
def _make_chunked_exchange_acc(mesh: Mesh, axis_name: str, quota: int,
                               impl: str = "auto"):
    """``make_chunked_exchange`` with a DEVICE-RESIDENT accumulator: each
    round scatters its received rows straight into a per-device output
    buffer at their final source-major position, so the host loop touches
    no data at all — per-round host work is the loop counter, and the
    whole result crosses to the host (if ever) exactly once.

    Landing offsets need no device->host sync: every shard re-derives the
    full DxD count matrix with one O(D^2)-int ``all_gather`` per round and
    computes ``base[src] + already_sent[src] + within`` locally — the same
    trick the one-shot exchange uses for its receive offsets.

    Returns ``round_acc(grouped, counts, round_idx, acc) -> acc`` where
    ``acc`` is ``[D * cap_out, ...]`` sharded on the leading axis (its
    shape IS the capacity — jit re-specializes per shape); rows a device
    nets beyond ``cap_out`` are the CALLER's sizing error (cap_out must be
    ``max_d sum_s counts[s, d]``, which the caller knows — it has the
    count matrix).
    """
    n = mesh.shape[axis_name]
    impl_resolved = resolve_transport(mesh, impl, axis_name)
    spec = P(axis_name)
    shard_kwargs = dict(mesh=mesh, in_specs=(spec, spec, None, spec),
                        out_specs=spec)
    if impl_resolved in ("ring", "ring_interpret"):
        shard_kwargs["check_vma"] = False

    @functools.partial(jax.jit, donate_argnums=(3,))
    @functools.partial(shard_map, **shard_kwargs)
    def round_acc(grouped, counts, round_idx, acc):
        counts = counts.reshape(-1).astype(jnp.int32)
        received, _ = _chunked_round_shard(
            grouped, counts, round_idx, axis_name, n, quota, impl_resolved)
        # full count matrix -> my column = total rows each source sends me
        mat = lax.all_gather(counts, axis_name, axis=0, tiled=False)
        my = lax.axis_index(axis_name)
        to_me = mat[:, my]
        base = _exclusive_cumsum(to_me)          # source-major layout
        lo = jnp.minimum(round_idx * quota, to_me)
        hi = jnp.minimum(lo + quota, to_me)
        rcnt = hi - lo                           # received per source now
        off = _exclusive_cumsum(rcnt)            # packed positions
        src = jnp.repeat(jnp.arange(n), quota)
        w = jnp.tile(jnp.arange(quota), n)
        valid = w < rcnt[src]
        rows = received[jnp.where(valid, off[src] + w, 0)]
        # invalid slots aim past the buffer and drop
        dst = jnp.where(valid, base[src] + lo[src] + w, acc.shape[0])
        return acc.at[dst].set(rows, mode="drop")

    return round_acc


def chunked_exchange(mesh: Mesh, axis_name: str, grouped: np.ndarray,
                     counts: np.ndarray, quota: int, impl: str = "auto"):
    """Host driver for the chunked exchange: runs all rounds with the
    device-resident accumulator, returns (received_rows_per_device,
    total_rounds). Each device's rows are grouped by source device, in the
    source's original within-destination order (same contract as
    ``ragged_exchange_shard``). ``grouped``/``counts`` are global arrays
    sharded on axis 0.

    ``quota`` is bucketed UP to the next power of two (``bucket_quota``)
    to share compiles across drifting quotas — a round may buffer up to
    2x the requested per-pair bound, so callers sizing quota against a
    hard memory budget should pass the pow2 at or below it.

    Host cost model: O(1) work per round (the loop index), one
    device->host transfer at the end. The previous per-round
    ``np.asarray`` + O(D^2) Python segment slicing made the HOST the
    bottleneck at ALS/skew scale — the round loop now leaves data in HBM
    (the reference's analogous property: fetched blocks land in
    registered memory and stay there,
    scala/RdmaShuffleFetcherIterator.scala:240-276)."""
    n = mesh.shape[axis_name]
    quota = bucket_quota(quota)  # match the builders' memoization bucket
    counts_host = np.asarray(counts).reshape(n, n)
    num_rounds = max(1, int(-(-counts_host.max() // quota)))
    recv_totals = counts_host.sum(axis=0)        # rows landing per device
    cap_out = max(1, int(recv_totals.max()))
    round_acc = make_chunked_exchange_acc(mesh, axis_name, quota, impl)
    sharding = NamedSharding(mesh, P(axis_name))
    grouped_d = jax.device_put(grouped, sharding)
    counts_d = jax.device_put(counts_host.reshape(-1), sharding)
    # host-side zeros: device_put then ships each device ONLY its shard —
    # a jnp.zeros here would transiently commit the whole global buffer to
    # the default device before resharding (D-fold HBM spike)
    acc = jax.device_put(
        np.zeros((n * cap_out,) + grouped.shape[1:], grouped.dtype),
        sharding)
    # Bound dispatch run-ahead. On XLA:CPU a collective BLOCKS its worker
    # thread inside the rendezvous (InProcessCommunicator); unbounded
    # async dispatch lets fast device threads queue rounds ahead and fill
    # the shared pool with executions parked at future-round rendezvous,
    # starving some device of a thread for the CURRENT round — after 40s
    # the rendezvous aborts the process ("Expected 8 ... only 7 arrived").
    # Reproduced deterministically on a 1-core host at rehearsal scale:
    # synchronized rounds run at ~0.1s/round, the first unsynchronized
    # batch of rounds SIGABRTs. On TPU collectives run device-side (the
    # host thread is not parked), so a deeper pipeline is safe and keeps
    # dispatch off the critical path.
    platform = next(iter(mesh.devices.flat)).platform
    sync_every = 1 if platform == "cpu" else 8
    for r in range(num_rounds):
        acc = round_acc(grouped_d, counts_d, r, acc)
        if (r + 1) % sync_every == 0:
            jax.block_until_ready(acc)
    record_exchange(int(counts_host.sum()))
    # Epilogue peak control: pull ONE device's shard to the host at a
    # time and free buffers as we go. Materializing the whole padded
    # accumulator host-side while the device copy is still alive doubles
    # the padded footprint (up to D x the real data under skew) — at
    # rehearsal scale that is the difference between fitting the memory
    # contract and an honest MemoryError under RLIMIT_AS.
    del grouped_d, counts_d
    shards = {s.index[0].start or 0: s for s in acc.addressable_shards}
    results: list = []
    if len(shards) == n:
        for d in range(n):
            host = np.asarray(shards[d * cap_out].data)
            # copies, not views: under skew the padded shard is up to D x
            # the real rows, and callers (ALS) hold results across solves
            results.append(host[:int(recv_totals[d])].copy())
            del host
    else:  # multi-process mesh: only local shards are addressable —
        # assemble the global array (callers at that scale stream)
        out = np.asarray(acc).reshape(n, cap_out, *grouped.shape[1:])
        del acc
        results = [out[d][:int(recv_totals[d])].copy() for d in range(n)]
    return results, num_rounds


@functools.lru_cache(maxsize=64)
def make_shuffle_exchange(mesh: Mesh, axis_name: str, impl: str = "auto",
                          out_factor: int = 1):
    """Build a jitted all-device shuffle-exchange over ``mesh``. Memoized
    per (mesh, axis, impl, out_factor) like ``make_chunked_exchange`` so
    per-job callers (mesh_service) compile once.

    The returned callable takes globally-sharded arrays
    ``(data[D*capacity, ...], dest[D*capacity])`` (sharded on the leading
    axis) and returns ``(received, recv_counts[D, D], recv_offsets[D, D],
    overflowed[D])`` with the same leading-axis sharding; ``overflowed[d]``
    is device d's explicit receive-overflow flag (capacity or dense pair
    slot) — check it before trusting ``received``.

    ``out_factor`` scales each device's receive capacity relative to its send
    capacity: a receiver may legitimately net-gain rows (skew). Callers bound
    worst-case skew or chunk into rounds (the reference's analogous knob is
    the grouped-fetch ceiling ``shuffleReadBlockSize``,
    scala/RdmaShuffleFetcherIterator.scala:240-263).
    """
    spec = P(axis_name)
    n = mesh.shape[axis_name]
    impl = resolve_transport(mesh, impl, axis_name)

    # pallas interpret-mode outputs confuse the vma checker when mixed
    # with collectives; disable it ONLY for the ring transports so the
    # static varying-axes check still guards the collective paths
    shard_kwargs = dict(mesh=mesh, in_specs=(spec, spec),
                        out_specs=(spec, spec, spec, spec))
    if impl in ("ring", "ring_interpret"):
        shard_kwargs["check_vma"] = False

    @jax.jit
    @functools.partial(shard_map, **shard_kwargs)
    def exchange(data, dest):
        output = jnp.zeros((data.shape[0] * out_factor,) + data.shape[1:],
                           dtype=data.dtype)
        received, recv_counts, recv_offsets, overflowed = shuffle_shard(
            data, dest, axis_name, n, output=output, impl=impl)
        return received, recv_counts[None], recv_offsets[None], \
            overflowed[None]

    return exchange
