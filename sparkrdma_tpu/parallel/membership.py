"""Epoch-versioned executor membership: mid-job join, graceful drain,
and the autoscaler loop (ROADMAP item 2).

The driver's member list used to be a static slot array where
loss-tombstoning was the only state change. This module makes membership
a versioned plane of its own:

* :class:`MembershipPlane` — the driver-side source of truth: an
  append-only slot list (indices stay stable forever, the property every
  driver-table entry depends on) plus a per-slot STATE
  (``SLOT_LIVE`` / ``SLOT_DRAINING`` / ``SLOT_DEAD``) and ONE monotone
  membership epoch. Every change — join, drain begin, retire, tombstone
  — bumps the epoch; the driver pushes the new state vector as a
  ``MembershipBumpMsg`` on the existing announce broadcast channel, so
  planners, pushers and health monitors recompute from live membership
  instead of the startup snapshot. Old peers that don't know the frame
  simply keep the announce-only view (static-membership behavior — the
  mixed-version degrade is tested).

* :func:`drain_slot` — the graceful decommission protocol, PR 10's
  repair machinery run as a PLANNED operation: mark the slot DRAINING
  (planner placement, merge-target choice and admission capacity drop it
  immediately), ask the drainee to push-merge its committed outputs to
  surviving peers (``DrainReq`` — duplicate pushes dedupe on the ledger
  fence, so a fleet whose background replication already covered
  everything pays nothing), re-finalize the merge targets so the new
  segments publish into the driver's merged directory, and wait until
  every map the drainee owns is servable WITHOUT it (a live owner
  elsewhere, or a merged replica the reducers' merged-first resolution
  selects). Then the slot retires under a bumped location epoch with
  ZERO re-executions — recovery's ``merged_covering`` re-point answers
  any straggler that still held cached locations. A drainee that dies
  mid-drain (or a deadline expiry) falls back to the ordinary tombstone
  path: same epoch bump, re-execution on demand — strictly the
  pre-drain behavior, never worse.

* :class:`Autoscaler` — the resize loop: watches per-tenant admission
  backlog, a queue-depth gauge and the ``reduce_balance`` skew gauge,
  and resizes within ``[min_executors, max_executors]`` — growth calls
  the installed ``scale_up`` hook (the embedding harness owns process
  creation), shrink picks the highest live slot (LIFO, deterministic)
  and drains it via :func:`drain_slot`.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from sparkrdma_tpu.utils.ids import ShuffleManagerId

log = logging.getLogger(__name__)

# Per-slot membership states. The dead state exists in the members list
# itself (the TOMBSTONE sentinel keeps indices stable); it is mirrored
# here so ONE vector answers "may I place/push/admit against this slot".
SLOT_LIVE = 0
SLOT_DRAINING = 1
SLOT_DEAD = 2


class MembershipPlane:
    """Driver-side epoch-versioned membership state.

    Thread-safe; every mutation returns the ``(members, states, epoch)``
    snapshot it produced so the caller can broadcast exactly what it
    committed (announce + membership bump) without re-reading racing
    state."""

    def __init__(self, tombstone: Optional[ShuffleManagerId] = None):
        if tombstone is None:
            from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
            tombstone = TOMBSTONE
        self._tombstone = tombstone
        self._lock = threading.Lock()
        self._members: List[ShuffleManagerId] = []
        self._states: List[int] = []
        self._epoch = 0
        # the fleet size capacity hints were tuned for: frozen at the
        # first registerShuffle (the fleet that existed when work
        # started) so admission caps scale as live/baseline afterwards
        self._baseline = 0
        self.joins = 0       # audit: members appended after the baseline
        self.drains_begun = 0

    # -- reads -----------------------------------------------------------

    def members(self) -> List[ShuffleManagerId]:
        with self._lock:
            return list(self._members)

    def states(self) -> List[int]:
        with self._lock:
            return list(self._states)

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def restore(self, members: List[ShuffleManagerId], states: List[int],
                epoch: int) -> None:
        """Install a replicated snapshot wholesale (driver failover
        restore). The epoch only ratchets up — a stale snapshot behind
        ops already replayed must not rewind the version the rebased
        re-announce is built on."""
        with self._lock:
            self._members = list(members)
            self._states = list(states)
            if epoch > self._epoch:
                self._epoch = epoch

    def rebase_epoch(self, min_epoch: int) -> int:
        """Raise the epoch floor (never lowers it) and return the result.

        A promoted driver rebases the replayed plane into its own
        incarnation's epoch space so its first re-announce dominates
        every broadcast the dead primary ever sent — receivers keep the
        highest epoch, so a stale in-flight announce from the old
        incarnation loses at every executor without any extra fencing.
        """
        with self._lock:
            if min_epoch > self._epoch:
                self._epoch = min_epoch
            return self._epoch

    def snapshot(self) -> Tuple[List[ShuffleManagerId], List[int], int]:
        with self._lock:
            return list(self._members), list(self._states), self._epoch

    def live_slots(self, include_draining: bool = False) -> List[int]:
        """Slots that may carry work: LIVE, plus DRAINING when asked
        (draining slots still SERVE — they just take no new work)."""
        ok = ((SLOT_LIVE, SLOT_DRAINING) if include_draining
              else (SLOT_LIVE,))
        with self._lock:
            return [i for i, s in enumerate(self._states) if s in ok]

    def draining_slots(self) -> Set[int]:
        with self._lock:
            return {i for i, s in enumerate(self._states)
                    if s == SLOT_DRAINING}

    def state_of(self, slot: int) -> int:
        with self._lock:
            if not 0 <= slot < len(self._states):
                return SLOT_DEAD
            return self._states[slot]

    def baseline(self) -> int:
        """The frozen startup fleet size (0 = not frozen yet: callers
        treat the current live count as the baseline)."""
        with self._lock:
            return self._baseline or len(
                [s for s in self._states if s == SLOT_LIVE])

    def freeze_baseline(self) -> int:
        """Pin the capacity baseline to the current live count (no-op
        once frozen). The driver calls this at the first
        registerShuffle — that is the fleet admission was sized for."""
        with self._lock:
            if self._baseline == 0:
                self._baseline = len(
                    [s for s in self._states if s == SLOT_LIVE])
            return self._baseline

    # -- mutations (each returns the snapshot it committed) --------------

    def join(self, manager_id: ShuffleManagerId
             ) -> Tuple[List[ShuffleManagerId], List[int], int, bool]:
        """Append (or re-greet) a member; epoch always bumps — a
        re-hello after a restart must still re-announce. Returns
        ``(members, states, epoch, is_new)``."""
        with self._lock:
            is_new = manager_id not in self._members
            if is_new:
                self._members.append(manager_id)
                self._states.append(SLOT_LIVE)
                if self._baseline:
                    self.joins += 1
            self._epoch += 1
            return (list(self._members), list(self._states), self._epoch,
                    is_new)

    def begin_drain(self, slot: int
                    ) -> Optional[Tuple[List[ShuffleManagerId],
                                        List[int], int]]:
        """LIVE -> DRAINING (None if the slot is not currently LIVE)."""
        with self._lock:
            if not 0 <= slot < len(self._states) \
                    or self._states[slot] != SLOT_LIVE:
                return None
            self._states[slot] = SLOT_DRAINING
            self._epoch += 1
            self.drains_begun += 1
            return list(self._members), list(self._states), self._epoch

    def abort_drain(self, slot: int
                    ) -> Optional[Tuple[List[ShuffleManagerId],
                                        List[int], int]]:
        """DRAINING -> LIVE (the operator changed their mind and the
        drainee is still healthy)."""
        with self._lock:
            if not 0 <= slot < len(self._states) \
                    or self._states[slot] != SLOT_DRAINING:
                return None
            self._states[slot] = SLOT_LIVE
            self._epoch += 1
            return list(self._members), list(self._states), self._epoch

    def retire(self, slot: int
               ) -> Optional[Tuple[List[ShuffleManagerId], List[int],
                                   int]]:
        """DRAINING/LIVE -> DEAD: the slot's entry becomes the tombstone
        sentinel (unroutable, index preserved)."""
        with self._lock:
            if not 0 <= slot < len(self._states) \
                    or self._states[slot] == SLOT_DEAD:
                return None
            self._members[slot] = self._tombstone
            self._states[slot] = SLOT_DEAD
            self._epoch += 1
            return list(self._members), list(self._states), self._epoch

    def tombstone(self, manager_id: ShuffleManagerId
                  ) -> Optional[Tuple[List[ShuffleManagerId], List[int],
                                      int, int]]:
        """Failure-path eviction by identity; converges (None when the
        member is unknown or already dead). Returns
        ``(members, states, epoch, dead_slot)``."""
        with self._lock:
            if manager_id not in self._members \
                    or manager_id == self._tombstone:
                return None
            slot = self._members.index(manager_id)
            self._members[slot] = self._tombstone
            self._states[slot] = SLOT_DEAD
            self._epoch += 1
            return (list(self._members), list(self._states), self._epoch,
                    slot)


# -- the graceful decommission protocol ------------------------------------

def drain_slot(driver, slot: int,
               deadline_ms: Optional[int] = None) -> Dict[str, object]:
    """Gracefully decommission one executor slot at ``driver`` (a
    :class:`~sparkrdma_tpu.parallel.endpoints.DriverEndpoint`).

    Protocol (PR 10's repair path as a planned operation):

    1. mark the slot DRAINING under a bumped membership epoch (pushed on
       the broadcast channel: planner placement, merge-target choice and
       admission capacity recompute from live membership immediately);
    2. ask the drainee to replicate — ``DrainReq`` makes it re-push
       every committed map output (ledger fences dedupe what background
       push-merge already delivered) and hand off the merged-segment
       rows it HOSTS for other executors' maps to surviving targets;
    3. re-finalize merge targets of completed shuffles so the drain
       pushes publish into the merged directory;
    4. wait (bounded by ``drain_deadline_ms``) until every map of every
       registered shuffle is servable WITHOUT the drainee, then retire
       the slot: tombstone + location epoch bumps, zero re-executions —
       the maps the drainee owned re-point to merged replicas exactly
       like :func:`~sparkrdma_tpu.shuffle.recovery.recover_lost_maps`'
       repoint path, with nothing to recompute.

    A drainee that dies mid-drain, a transport failure, or a deadline
    expiry FALLS BACK to the ordinary tombstone: the retire still
    happens (the operator asked for the slot back), recovery re-executes
    what no replica covers, and the result is byte-identical — strictly
    the pre-drain failure behavior.

    Returns ``{"status": "drained"|"fallback"|"unknown", "slot", ...}``
    with the re-point/re-push accounting.
    """
    from sparkrdma_tpu.parallel import messages as M
    from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
    from sparkrdma_tpu.parallel.transport import TransportError

    conf = driver.conf
    deadline_ms = deadline_ms or conf.drain_deadline_ms
    result: Dict[str, object] = {"status": "unknown", "slot": slot,
                                 "maps_pushed": 0, "bytes_handed_off": 0,
                                 "repointed": 0, "unservable": []}
    members = driver.members()
    if not 0 <= slot < len(members) or members[slot] == TOMBSTONE:
        return result
    from sparkrdma_tpu.shuffle.ha import DRAIN_BEGIN
    begun = driver.drain_transition(slot, DRAIN_BEGIN)
    if begun is None:
        return result  # already draining or dead
    snapshot, states, epoch = begun
    driver.publish_membership(snapshot, states, epoch)
    driver.tracer.instant("member.drain", "member", slot=slot,
                          epoch=epoch, deadline_ms=deadline_ms)
    log.info("driver: draining executor slot %d (membership epoch %d, "
             "deadline %dms)", slot, epoch, deadline_ms)
    deadline = time.monotonic() + deadline_ms / 1000

    # 2) drainee replication (best-effort: existing merged coverage may
    # already suffice, and a dead drainee is exactly the fallback case)
    drainee = members[slot]
    drain_ok = False
    try:
        conn = driver.client_conn(drainee)
        remaining_ms = max(1, int((deadline - time.monotonic()) * 1000))
        resp = conn.request(
            M.DrainReq(conn.next_req_id(), slot, remaining_ms),
            timeout=deadline - time.monotonic() + 5.0)
        if isinstance(resp, M.DrainResp):
            result["drain_resp_status"] = resp.status
            result["maps_pushed"] = resp.maps_pushed
            result["bytes_handed_off"] = resp.bytes_pushed
            drain_ok = resp.status == M.STATUS_OK
            if not drain_ok:
                log.warning("driver: drainee slot %d answered status %d "
                            "(partial replication); the coverage check "
                            "decides", slot, resp.status)
    except (TransportError, TimeoutError, OSError) as e:
        result["drain_req_error"] = f"{type(e).__name__}: {e}"[:120]
        log.warning("driver: drain request to slot %d failed (%s); "
                    "relying on existing replica coverage", slot, e)

    # 3) re-finalize completed shuffles so drain pushes publish; 4) wait
    # for the retire-safety invariant
    sids = driver.live_shuffles()
    for sid in sids:
        driver.refinalize_merge(sid)
    unservable: Dict[int, List[int]] = {}
    while True:
        unservable = {sid: maps for sid in driver.live_shuffles()
                      if (maps := driver.unservable_without(sid, slot))}
        if not unservable or time.monotonic() > deadline:
            break
        time.sleep(0.02)

    repointed = sum(len(driver.maps_owned_by(sid, slot))
                    for sid in driver.live_shuffles())
    from sparkrdma_tpu.shuffle.ha import DRAIN_RETIRE
    retired = driver.drain_transition(slot, DRAIN_RETIRE)
    if retired is not None:
        driver.publish_membership(*retired)
        driver.on_slot_dead(slot)
    if unservable:
        # deadline expired (drainee died mid-drain, pushes shed, targets
        # over their segment caps, ...): ordinary tombstone recovery owns
        # the rest — re-execution on demand, byte-identical
        result["status"] = "fallback"
        result["unservable"] = sorted(
            (sid, m) for sid, maps in unservable.items() for m in maps)
        driver.drain_fallbacks += 1
        driver.tracer.instant("member.drain_fallback", "member",
                              slot=slot, drain_ok=int(drain_ok),
                              unservable=len(result["unservable"]))
        log.warning("driver: drain of slot %d fell back to tombstone "
                    "recovery (%d map(s) not yet covered)", slot,
                    len(result["unservable"]))
    else:
        result["status"] = "drained"
        result["repointed"] = repointed
        driver.drains_completed += 1
        driver.tracer.instant("member.retire", "member", slot=slot,
                              repointed=repointed)
        log.info("driver: slot %d retired cleanly (%d owned map(s) now "
                 "served from merged replicas; zero re-executions)",
                 slot, repointed)
    return result


# -- the autoscaler loop ---------------------------------------------------

class Autoscaler:
    """Watches load gauges and resizes the fleet within
    ``[min_executors, max_executors]``.

    Signals (``gauges()``): per-tenant admission backlog (queued
    ``registerShuffle`` waiters at the driver), a ``queue_depth`` gauge
    (pending work units — the embedding harness supplies it via
    ``load_fn``, e.g. undispatched tasks), and ``reduce_balance``
    (max/mean reduce-task bytes — sustained skew means more slots to
    split hot partitions across). Policy, deterministic for tests:

    * scale UP when admission backlog is non-zero, queue depth exceeds
      2x the live count, or reduce_balance exceeds 2.0 — target
      ``live + max(1, backlog)``, clamped to ``max_executors``;
    * scale DOWN one slot after two consecutive idle ticks (no backlog,
      queue depth under half the live count), clamped to
      ``min_executors`` — the HIGHEST live slot drains first (LIFO:
      joiners leave before the founding fleet, which keeps shard hosts
      and long-lived merge targets stable).

    ``scale_up(n)`` is the harness's spawn hook (the driver cannot fork
    executors); ``scale_down(slot)`` defaults to
    :func:`drain_slot` via ``driver.decommission_slot``. ``start()``
    runs ``tick()`` every ``autoscale_interval_ms``; tests call
    ``tick()`` directly with an injected ``load_fn``.
    """

    def __init__(self, driver, conf=None,
                 scale_up: Optional[Callable[[int], None]] = None,
                 scale_down: Optional[Callable[[int], None]] = None,
                 load_fn: Optional[Callable[[], Dict[str, float]]] = None):
        self.driver = driver
        self.conf = conf or driver.conf
        self.scale_up = scale_up
        self.scale_down = (scale_down if scale_down is not None
                           else lambda slot: driver.decommission_slot(slot))
        self.load_fn = load_fn
        self.resizes = 0  # audit: actions taken
        self._idle_ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def gauges(self) -> Dict[str, float]:
        snap = self.driver.admission.snapshot()
        g: Dict[str, float] = {
            "admission_backlog": float(sum(snap["queued"].values())),
            "inflight_shuffles": float(sum(snap["inflight"].values())
                                       or len(self.driver.live_shuffles())),
            "queue_depth": 0.0,
            "reduce_balance": 1.0,
        }
        if self.load_fn is not None:
            try:
                g.update(self.load_fn() or {})
            except Exception:  # noqa: BLE001 — a broken gauge must not
                # kill the loop; the defaults above are the safe answer
                log.exception("autoscaler load_fn failed")
        return g

    def desired_size(self, live: int, g: Dict[str, float]) -> int:
        lo = max(1, int(self.conf.min_executors))
        # 0 = unbounded (the config contract): the ceiling must NOT
        # collapse to the current live count, or scale-up could never
        # fire on a default config no matter the backlog
        hi = int(self.conf.max_executors) or (1 << 20)
        hi = max(hi, lo)
        backlog = int(g.get("admission_backlog", 0))
        depth = float(g.get("queue_depth", 0.0))
        balance = float(g.get("reduce_balance", 1.0))
        if backlog > 0 or depth > 2.0 * live or balance > 2.0:
            self._idle_ticks = 0
            return min(hi, live + max(1, backlog))
        if backlog == 0 and depth < max(1.0, 0.5 * live):
            self._idle_ticks += 1
            if self._idle_ticks >= 2:
                return max(lo, live - 1)
            return max(lo, min(hi, live))
        self._idle_ticks = 0
        return max(lo, min(hi, live))

    def tick(self) -> Optional[Tuple[str, int]]:
        """One evaluation: returns ``("up", n)`` / ``("down", slot)`` /
        None (no resize)."""
        live_slots = self.driver.membership.live_slots()
        live = len(live_slots)
        if live == 0:
            return None
        target = self.desired_size(live, self.gauges())
        if target > live and self.scale_up is not None:
            n = target - live
            self.resizes += 1
            self._idle_ticks = 0
            self.driver.tracer.instant("autoscale.resize", "member",
                                       direction="up", count=n, live=live)
            log.info("autoscaler: scaling UP by %d (live %d)", n, live)
            self.scale_up(n)
            return ("up", n)
        if target < live:
            slot = max(live_slots)
            self.resizes += 1
            self._idle_ticks = 0
            self.driver.tracer.instant("autoscale.resize", "member",
                                       direction="down", count=1,
                                       live=live)
            log.info("autoscaler: draining slot %d (live %d)", slot, live)
            self.scale_down(slot)
            return ("down", slot)
        return None

    def start(self) -> None:
        if self._thread is not None:
            return
        interval = self.conf.autoscale_interval_ms / 1000
        if interval <= 0:
            return

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the loop must live
                    log.exception("autoscaler tick failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
