"""Threaded TCP control-plane transport.

Plays the role of the reference's ``RdmaNode``/``RdmaChannel`` pair for
*control* traffic only (the data plane rides ICI collectives — see
``sparkrdma_tpu.parallel.exchange``). Preserved semantics:

* listener with port-retry bind (java/RdmaNode.java:74-88),
* a per-process connection cache keyed by remote address, built lazily with
  a bounded retry/timeout loop (java/RdmaNode.java:283-353, connect budget
  ``maxConnectionAttempts`` x event timeout),
* request pipelining over one connection with completion callbacks — the
  QP work-request model (java/RdmaChannel.java:484-589) mapped to req_id
  correlation on a stream socket, with a bounded in-flight budget standing
  in for the send-queue-depth semaphore (java/RdmaChannel.java:66-67,
  422-482),
* parallel teardown that fails all outstanding requests
  (java/RdmaChannel.java:872-956).

Threading model mirrors the reference's one-CQ-thread-per-channel
(java/RdmaThread.java:26-64): one reader thread per connection dispatches
completions; senders never block on the network for replies.
"""

from __future__ import annotations

import itertools
import logging
import random
import socket
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Callable, Dict, Optional, Tuple

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel.rpc_msg import Reassembler, RpcMsg

log = logging.getLogger(__name__)

Addr = Tuple[str, int]


class TransportError(RuntimeError):
    """Base transport failure. ``retryable`` classifies the outcome for
    the fetch retry envelope: connection loss / connect failure default to
    retryable (a re-dial or refetch usually heals); subclasses and raisers
    that know better override it (an authoritative unknown-map answer
    re-fails identically — retrying just doubles failure-path load)."""

    retryable = True


class ChecksumError(TransportError):
    """A fetch payload failed its CRC32 verification (bit-flip on the
    wire, or corruption at the server between read and send). Always
    retryable: the refetch re-reads the source bytes.

    When the verifier can tell WHICH blocks failed it attaches
    ``bad_blocks`` (request-order indices) and ``body`` (the full
    trailer-stripped payload): a vectored (cross-map) fetch then salvages
    every clean sub-range and refetches only the ranges that actually
    failed, attributing the retry to the map that owns them. Both stay
    ``None`` for failures with no per-block verdict (decompress/unwrap
    errors, size mismatches) — those retry whole-request."""

    def __init__(self, msg: str, bad_blocks=None, body=None):
        super().__init__(msg)
        self.bad_blocks = bad_blocks
        self.body = body


class FetchStatusError(TransportError):
    """A peer answered a fetch with a non-OK status. The raiser sets
    ``retryable`` from the status semantics it knows: transient
    server-side failures (credit-window expiry) heal on refetch,
    authoritative rejections (unknown map/shuffle, bad range) do not."""

    def __init__(self, what: str, status: int, retryable: bool = True):
        super().__init__(f"{what} status={status}")
        self.status = status
        self.retryable = retryable


class Backoff:
    """Exponential backoff with equal jitter: attempt ``k`` (0-based)
    sleeps in ``[s/2, s]`` where ``s = min(cap, base * 2^k)``. Equal
    jitter rather than full jitter so a retry budget provably spans
    wall-clock time (full jitter can draw ~0 on every attempt, turning
    the budget back into the hot-spin it exists to prevent) while still
    decorrelating the retry storms of many peers. A seeded ``rng`` makes
    chaos scenarios replay exactly."""

    def __init__(self, base_s: float, cap_s: float,
                 rng: Optional[random.Random] = None):
        self.base_s = max(0.0, base_s)
        self.cap_s = max(self.base_s, cap_s)
        self._rng = rng if rng is not None else random

    @classmethod
    def from_conf(cls, conf: TpuShuffleConf,
                  rng: Optional[random.Random] = None) -> "Backoff":
        return cls(conf.retry_backoff_base_ms / 1000,
                   conf.retry_backoff_cap_ms / 1000, rng)

    def delay(self, attempt: int) -> float:
        span = min(self.cap_s, self.base_s * (1 << max(0, min(attempt, 60))))
        return span / 2 + self._rng.uniform(0, span / 2)

    def sleep(self, attempt: int,
              interrupt: Optional[threading.Event] = None) -> bool:
        """Sleep out attempt ``attempt``'s delay; with ``interrupt``, an
        abort wakes the sleep early (returns True iff interrupted)."""
        d = self.delay(attempt)
        if interrupt is not None:
            return interrupt.wait(d)
        time.sleep(d)
        return False


def await_response(fut: Future, timeout: Optional[float]) -> RpcMsg:
    """Wait out a request future with the claim-back race handling every
    caller needs: on timeout, cancel() failing means the reader won the
    race and a response already landed — return it rather than dropping a
    consumed message on the floor (a credited fetch would otherwise leak
    the server's window forever: the response never reaches the orphan
    path AND the requester never reports). cancel() succeeding poisons
    the future, so a late set_result in _dispatch raises and the response
    is re-routed to the unsolicited-message path.

    Catches both timeout flavors — on this interpreter (3.10)
    ``concurrent.futures.TimeoutError`` is NOT the builtin — and always
    re-raises the BUILTIN ``TimeoutError`` so every caller can catch one
    class (pre-normalization, 3.10 callers writing ``except
    TimeoutError`` silently missed the futures flavor)."""
    try:
        return fut.result(timeout=timeout)
    except (TimeoutError, FutureTimeoutError) as e:
        if not fut.cancel():
            return fut.result(timeout=0)
        raise TimeoutError("request timed out") from e


class Connection:
    """One pipelined control connection.

    Requests carry a ``req_id``; the reader thread completes the matching
    Future when the response echoes it. Unsolicited messages (announce,
    publish) go to ``on_message``.
    """

    def __init__(self, sock: socket.socket, conf: TpuShuffleConf,
                 on_message: Optional[Callable[["Connection", RpcMsg], Optional[RpcMsg]]] = None,
                 name: str = "conn"):
        self._sock = sock
        self._conf = conf
        self._on_message = on_message
        self.name = name
        self._send_lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._req_ids = itertools.count(1)
        # Send-budget semaphore (java/RdmaChannel.java:66-67): bounds
        # outstanding requests on one connection.
        self._budget = threading.BoundedSemaphore(max(1, conf.send_queue_depth))
        self._closed = threading.Event()
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"ctl-reader-{name}")
        self._reader.start()

    # -- sending ---------------------------------------------------------

    def next_req_id(self) -> int:
        return next(self._req_ids)

    def send(self, msg: RpcMsg) -> None:
        """Fire-and-forget (SEND without completion interest)."""
        data = msg.encode()
        with self._send_lock:
            if self._closed.is_set():
                raise TransportError(f"{self.name}: connection closed")
            try:
                self._sock.sendall(data)
            except OSError as e:
                raise TransportError(f"{self.name}: send failed: {e}") from e

    def request_async(self, msg: RpcMsg) -> Future:
        """Send a req_id-bearing message; the returned Future completes
        with the echoed response (reader thread), a TransportError
        (teardown/lost connection), or cancellation (caller gave up).

        This is the req-id pipelining surface: many requests ride one
        connection concurrently, each holding a send-budget slot
        (java/RdmaChannel.java:66-67) from issue until its future is done
        — acquisition blocks when the queue-depth budget is exhausted,
        exactly like the reference's send-queue semaphore.
        """
        req_id = getattr(msg, "req_id", None)
        if req_id is None:
            raise ValueError("request_async() needs a msg with req_id")
        fut: Future = Future()
        self._budget.acquire()

        def _cleanup(f: Future, _req_id=req_id) -> None:
            with self._pending_lock:
                self._pending.pop(_req_id, None)
            self._budget.release()

        # done-callback cleanup fires exactly once per future, whether the
        # reader completed it, teardown failed it, or the caller cancelled
        fut.add_done_callback(_cleanup)
        with self._pending_lock:
            self._pending[req_id] = fut
        try:
            self.send(msg)
        except TransportError as e:
            if not fut.cancel():
                # the reader raced a (stale) completion in; surface that
                return fut
            # cancel() already triggered _cleanup; hand back a failed
            # future so callers see one error path
            failed: Future = Future()
            failed.set_exception(e)
            return failed
        except BaseException:
            # non-transport failure (encode bug, codec error): resolve
            # the future so _cleanup reclaims the budget slot + pending
            # entry, then let the bug propagate as itself — same contract
            # as the replaced blocking request()'s try/finally
            fut.cancel()
            raise
        return fut

    def request(self, msg: RpcMsg, timeout: Optional[float] = None) -> RpcMsg:
        """Send a req_id-bearing message and wait for the echoed response
        (default wait: the per-request deadline, ``request_deadline_ms``,
        falling back to the connect timeout)."""
        fut = self.request_async(msg)
        tmo = (timeout if timeout is not None
               else self._conf.resolved_request_deadline_s())
        return await_response(fut, tmo)

    # -- receiving -------------------------------------------------------

    def _read_loop(self) -> None:
        reasm = Reassembler()
        try:
            while not self._closed.is_set():
                chunk = self._sock.recv(1 << 16)
                if not chunk:
                    break
                for msg in reasm.feed(chunk):
                    self._dispatch(msg)
        except (OSError, ValueError) as e:
            if not self._closed.is_set():
                log.debug("%s: reader stopped: %s", self.name, e)
        finally:
            self._fail_pending(TransportError(f"{self.name}: connection lost"))
            self._closed.set()
            try:
                self._sock.close()
            except OSError:
                pass

    def _dispatch(self, msg: RpcMsg) -> None:
        req_id = getattr(msg, "req_id", None)
        if req_id is not None:
            with self._pending_lock:
                fut = self._pending.pop(req_id, None)
            if fut is not None:
                try:
                    fut.set_result(msg)
                    return
                except InvalidStateError:
                    # the requester timed out and cancelled the future in
                    # the race window — deliver as unsolicited instead
                    # (the endpoint's orphan path reports its credits)
                    pass
        if self._on_message is not None:
            try:
                reply = self._on_message(self, msg)
            except Exception as e:  # handler bug must not kill the reader
                log.exception("%s: handler error for %s: %s",
                              self.name, type(msg).__name__, e)
                return
            if reply is not None:
                try:
                    self.send(reply)
                except TransportError:
                    pass

    def _fail_pending(self, exc: Exception) -> None:
        # Fail-all-outstanding on teardown (java/RdmaChannel.java:872-956).
        with self._pending_lock:
            pending, self._pending = dict(self._pending), {}
        for fut in pending.values():
            try:
                if not fut.done():
                    fut.set_exception(exc)
            except InvalidStateError:
                # a caller's cancel() won the race between the done()
                # check and here (the pipelined fetcher cancels whole
                # windows at exactly this moment); cancelled is resolved
                pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._fail_pending(TransportError(f"{self.name}: closed"))

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class ControlServer:
    """Listening endpoint; one reader thread per accepted connection."""

    def __init__(self, host: str, port: int, conf: TpuShuffleConf,
                 handler: Callable[[Connection, RpcMsg], Optional[RpcMsg]],
                 name: str = "server"):
        self._conf = conf
        self._handler = handler
        self.name = name
        self._conns: list = []
        self._conns_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # Port-retry bind (java/RdmaNode.java:74-88).
        bound = False
        for attempt in range(max(1, conf.port_max_retries)):
            try:
                self._sock.bind((host, port + attempt if port else 0))
                bound = True
                break
            except OSError:
                continue
        if not bound:
            raise TransportError(
                f"{name}: could not bind {host}:{port} after "
                f"{conf.port_max_retries} attempts")
        self._sock.listen(128)  # BACKLOG, java/RdmaNode.java:92
        self.host, self.port = self._sock.getsockname()[:2]
        self._stopped = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True, name=f"ctl-accept-{name}")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, addr = self._sock.accept()
            except OSError:
                break
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = Connection(sock, self._conf, on_message=self._handler,
                             name=f"{self.name}<-{addr[0]}:{addr[1]}")
            with self._conns_lock:
                # reap connections whose reader died (peer went away):
                # accepted conns are otherwise append-only and a
                # long-lived server accumulates one dead entry per client
                # lifetime, without bound
                self._conns = [c for c in self._conns if not c.closed]
                self._conns.append(conn)

    def live_connections(self) -> int:
        """Count of accepted connections whose reader is still alive
        (reaps dead entries as a side effect — the audit surface for the
        leak the accept-time reap closes)."""
        with self._conns_lock:
            self._conns = [c for c in self._conns if not c.closed]
            return len(self._conns)

    @property
    def stopped(self) -> bool:
        """Liveness signal for schedulers (engine task placement)."""
        return self._stopped.is_set()

    def stop(self) -> None:
        self._stopped.set()
        # shutdown() before close(): a close() alone does not tear down a
        # listening socket another thread is blocked accept()ing on — the
        # kernel keeps it in LISTEN and keeps completing handshakes into the
        # backlog, so peers never see the endpoint die. shutdown() interrupts
        # the blocked accept and kills the listen state immediately.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=self._conf.teardown_timeout_ms / 1000)
        with self._conns_lock:
            conns, self._conns = list(self._conns), []
        for c in conns:
            c.close()


class ConnectionCache:
    """Lazy per-peer client connections with bounded retry
    (java/RdmaNode.java:283-353)."""

    def __init__(self, conf: TpuShuffleConf,
                 on_message: Optional[Callable[[Connection, RpcMsg], Optional[RpcMsg]]] = None):
        self._conf = conf
        self._on_message = on_message
        self._conns: Dict[Addr, Connection] = {}
        self._lock = threading.Lock()

    def get(self, host: str, port: int) -> Connection:
        addr = (host, port)
        with self._lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed:
                return conn
        conn = self._connect(addr)
        with self._lock:
            existing = self._conns.get(addr)
            if existing is not None and not existing.closed:
                conn.close()  # lost the race (java/RdmaNode.java:303-305)
                return existing
            self._conns[addr] = conn
        return conn

    def _dial(self, addr: Addr, timeout: float) -> socket.socket:
        """One connect attempt, separated from the retry loop so the
        fault shim can refuse/delay individual dials."""
        sock = socket.create_connection(addr, timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        return sock

    def _connect(self, addr: Addr) -> Connection:
        timeout = self._conf.connect_timeout_ms / 1000
        backoff = Backoff.from_conf(self._conf)
        last: Optional[Exception] = None
        for attempt in range(max(1, self._conf.max_connection_attempts)):
            if attempt:
                # between attempts only — a refused dial re-tried with
                # zero sleep burns the whole budget in microseconds, so
                # the budget never spans the restart it exists to ride out
                backoff.sleep(attempt - 1)
            try:
                sock = self._dial(addr, timeout)
                return Connection(sock, self._conf, on_message=self._on_message,
                                  name=f"->{addr[0]}:{addr[1]}")
            except OSError as e:
                last = e
        raise TransportError(
            f"connect to {addr} failed after "
            f"{self._conf.max_connection_attempts} attempts: {last}")

    def peek(self, host: str, port: int) -> Optional[Connection]:
        """The cached live connection to ``(host, port)``, or None —
        never dials (the heartbeat monitor pings only over connections
        the fetch path already holds; a monitor that dialed would stall
        a whole beat on one unreachable peer's connect budget)."""
        with self._lock:
            conn = self._conns.get((host, port))
        return conn if conn is not None and not conn.closed else None

    def drop(self, host: str, port: int) -> bool:
        """Close and forget the cached connection to ``(host, port)``
        WITHOUT dialing (the peer-health monitor's suspect path: closing
        fails every outstanding request on it immediately instead of
        letting them wait out a TCP timeout). Returns True if a cached
        connection existed."""
        with self._lock:
            conn = self._conns.pop((host, port), None)
        if conn is None:
            return False
        conn.close()
        return True

    def close_all(self) -> None:
        with self._lock:
            conns, self._conns = list(self._conns.values()), {}
        for c in conns:
            c.close()
