"""Two-level (ICI/DCN) topology: the dataplane layer's first-class input.

Production TPU jobs span *slices*: devices inside a slice are joined by
ICI (the fabric the fused exchange rides), slices are joined by DCN /
host links an order of magnitude slower. Until now the cost model
(`parallel/device_plane.select_dataplane`) treated the world as one flat
link — a whole stage was device-or-host. This module makes the two-level
structure explicit so the dataplane layer can *factor* a redistribution
into composable intra- and inter-slice moves (the recipe of
"Memory-efficient array redistribution through portable collective
communication", PAPERS.md) and treat the inter-slice channel as a
first-class link with its own cost (RAMC, PAPERS.md):

* :class:`Topology` — contiguous slice sizes along the exchange axis
  plus per-link bandwidth coefficients (config-seeded via ``ici_gbps``
  / ``dcn_gbps``, probe-refinable via :meth:`Topology.refine`). The
  single-slice case is the *degenerate* topology: ``is_flat`` is True
  and every consumer reproduces today's behavior bit-for-bit.
* :func:`detect_topology` — derive the slice grouping automatically
  from the mesh (`jax` device ``slice_index`` on TPU pods; the
  per-process ownership seams ``multihost.py`` already carries on
  virtual-device clusters), or from the ``slice_topology`` conf key
  (virtual slicing for CI / benches on one host).
* :func:`slice_mesh` — the per-slice sub-mesh the intra-slice fused
  step runs over (memoized like the step builders).
* ``CROSS_SLICE`` / :func:`record_cross_slice` — the host-side tally of
  bytes that actually crossed the slice boundary (the analogue of
  ``exchange.DATA_PLANE``), plus the ``cross_slice_shim`` hook point a
  bench installs to charge a modeled DCN cost per residue byte (the
  ``fetch_bench`` delay-shim precedent).

Executor slots get the same treatment (:func:`topology_for_slots`,
:meth:`Topology.slice_of_slot`): the reduce planner scores placements by
link cost so partition ranges land slice-aligned and the bytes that
cross DCN are minimized by construction.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, replace
from typing import Dict, Optional, Tuple

# Host-side tally of bytes moved ACROSS a slice boundary (the residue
# the hierarchical exchange hands the host dataplane). Tests and the
# bench assert against it the way they assert DATA_PLANE — the
# hierarchical plan's whole point is keeping this strictly below the
# flat plan's cross-slice traffic.
CROSS_SLICE = {"moves": 0, "bytes": 0}
_CROSS_SLICE_LOCK = threading.Lock()

# Bench/chaos hook: a callable charged ``(nbytes)`` at every cross-slice
# move — no-op until installed (the storage/fault shim precedent,
# parallel/faults.py). The topo bench installs a sleep modeling the DCN
# cost per byte so a CPU loopback run prices the two plans honestly.
cross_slice_shim = None


def record_cross_slice(nbytes: int) -> None:
    """Tally one host-side cross-slice move of ``nbytes`` bytes and
    charge the installed shim (if any)."""
    with _CROSS_SLICE_LOCK:
        CROSS_SLICE["moves"] += 1
        CROSS_SLICE["bytes"] += int(nbytes)
    shim = cross_slice_shim
    if shim is not None:
        shim(int(nbytes))


def cross_slice_snapshot() -> Dict[str, int]:
    with _CROSS_SLICE_LOCK:
        return dict(CROSS_SLICE)


@dataclass(frozen=True)
class Topology:
    """Two-level description of the exchange fabric.

    ``slice_sizes[s]`` is the number of contiguous devices (along the
    exchange axis, in mesh order) slice ``s`` owns; devices inside a
    slice are ICI-joined, slices are DCN-joined. ``ici_gbps`` /
    ``dcn_gbps`` are the per-link bandwidth coefficients in GB/s —
    config-seeded (they only need to be *relatively* right for the cost
    model to rank plans) and refinable from a probe
    (:meth:`refine`)."""

    slice_sizes: Tuple[int, ...]
    ici_gbps: float = 100.0
    dcn_gbps: float = 10.0

    @property
    def num_slices(self) -> int:
        return len(self.slice_sizes)

    @property
    def num_devices(self) -> int:
        return sum(self.slice_sizes)

    @property
    def is_flat(self) -> bool:
        """True for the degenerate single-slice (or empty) topology: one
        ICI domain, no DCN seam — consumers must reproduce the
        pre-topology behavior bit-for-bit."""
        return self.num_slices <= 1

    def slice_of(self, device_pos: int) -> int:
        """The slice owning axis position ``device_pos``."""
        lo = 0
        for s, size in enumerate(self.slice_sizes):
            lo += size
            if device_pos < lo:
                return s
        raise IndexError(f"device position {device_pos} outside the "
                         f"{self.num_devices}-device topology")

    def device_slices(self):
        """``i32[num_devices]`` — slice id per axis position (the
        vectorized ``slice_of``, what the hierarchical runner indexes
        row destinations through)."""
        import numpy as np

        return np.repeat(np.arange(self.num_slices, dtype=np.int32),
                         self.slice_sizes)

    def slice_bounds(self, s: int) -> Tuple[int, int]:
        """``[lo, hi)`` axis positions of slice ``s``."""
        lo = sum(self.slice_sizes[:s])
        return lo, lo + self.slice_sizes[s]

    def slice_of_slot(self, slot: int, num_slots: int) -> int:
        """The home slice of executor slot ``slot`` out of
        ``num_slots``: contiguous slot ranges map onto slices
        proportionally (the same contiguous-range convention the
        push-merge target assignment and the metadata shard map use), so
        co-hosted executors and their slice's devices agree on a home.
        """
        if num_slots <= 0:
            return 0
        slot = max(0, min(int(slot), num_slots - 1))
        return self.slice_of(min(self.num_devices - 1,
                                 slot * self.num_devices // num_slots))

    def link_seconds(self, intra_bytes: int, inter_bytes: int) -> float:
        """The two-level cost: ``intra/ici_bw + inter/dcn_bw`` (seconds
        for the byte volumes at the configured coefficients) — the score
        ``select_dataplane`` ranks candidate plans by."""
        gb = 1 << 30
        return (max(0, intra_bytes) / (self.ici_gbps * gb)
                + max(0, inter_bytes) / (self.dcn_gbps * gb))

    def uniform_inter_fraction(self) -> float:
        """Expected cross-slice traffic fraction when sources and
        destinations are uniform over devices: a row homed in slice s
        stays intra with probability ``|s|/D``, so the inter fraction is
        ``1 - sum((|s|/D)^2)`` — the cost model's estimate when a stage
        carries no per-link byte decomposition."""
        d = self.num_devices
        if d == 0:
            return 0.0
        return 1.0 - sum((sz / d) ** 2 for sz in self.slice_sizes)

    def refine(self, ici_gbps: Optional[float] = None,
               dcn_gbps: Optional[float] = None) -> "Topology":
        """A copy with probe-measured link coefficients (the config
        seeds are only priors; a bench round that measured real rates
        re-anchors the cost model here)."""
        return replace(self,
                       ici_gbps=self.ici_gbps if ici_gbps is None
                       else float(ici_gbps),
                       dcn_gbps=self.dcn_gbps if dcn_gbps is None
                       else float(dcn_gbps))

    def describe(self) -> dict:
        """Provenance record (bench round JSONs carry it alongside
        ``host_load_avg``)."""
        return {"slices": self.num_slices,
                "devices_per_slice": list(self.slice_sizes),
                "ici_gbps": self.ici_gbps, "dcn_gbps": self.dcn_gbps}


def _parse_slice_spec(spec: str, num_devices: int) -> Optional[Tuple[int, ...]]:
    """Parse the ``slice_topology`` conf value: ``""`` = auto (None),
    ``"N"`` = N equal contiguous slices, ``"a,b,c"`` = explicit sizes
    (must sum to the device count). Invalid specs return None (auto) —
    conf values log-and-default, never raise (config.py contract)."""
    spec = (spec or "").strip()
    if not spec:
        return None
    try:
        parts = [int(p) for p in spec.split(",") if p.strip()]
    except ValueError:
        return None
    if not parts or any(p <= 0 for p in parts):
        return None
    if len(parts) == 1:
        n = parts[0]
        if n < 1 or num_devices % n != 0:
            return None
        return tuple([num_devices // n] * n)
    return tuple(parts) if sum(parts) == num_devices else None


def _auto_slice_sizes(devices) -> Tuple[int, ...]:
    """Group the axis-ordered devices into contiguous runs by physical
    slice: TPU pods expose ``slice_index`` per device; virtual-device
    clusters fall back to ``process_index`` (the per-host seams
    ``multihost.py`` stages across). Devices with neither (single-host
    CPU meshes) collapse to one slice — the degenerate case."""
    sizes = []
    prev = object()
    for d in devices:
        marker = getattr(d, "slice_index", None)
        if marker is None:
            marker = getattr(d, "process_index", 0)
        if marker != prev:
            sizes.append(0)
            prev = marker
        sizes[-1] += 1
    return tuple(sizes) if sizes else (0,)


def _conf_topology(conf, num_units: int, devices=None) -> Topology:
    """THE conf -> Topology construction every detector shares: parse
    the ``slice_topology`` spec against ``num_units``, fall back to the
    device-marker grouping (when ``devices`` given) or one flat slice,
    and seed the link coefficients — one path, so the cost model, the
    planner's slot view, and bench provenance can never disagree about
    how a conf reads."""
    spec = str(getattr(conf, "slice_topology", "") or "")
    sizes = _parse_slice_spec(spec, num_units)
    if sizes is None:
        if devices:
            sizes = _auto_slice_sizes(devices)
        else:
            sizes = (num_units,) if num_units else (0,)
    return Topology(sizes).refine(
        ici_gbps=getattr(conf, "ici_gbps", None),
        dcn_gbps=getattr(conf, "dcn_gbps", None))


def detect_topology(mesh, axis_name: Optional[str] = None,
                    conf=None) -> Topology:
    """The mesh's two-level topology: slice grouping from the
    ``slice_topology`` conf key when set (virtual slicing for CI /
    benches), else auto-derived from device ``slice_index`` /
    ``process_index``; link coefficients seeded from ``ici_gbps`` /
    ``dcn_gbps``. A single-slice result is the degenerate topology
    (``is_flat``) and changes nothing downstream.

    The grouping runs along the mesh's flat device order — the same
    order every exchange in this package shards its leading axis over
    (meshes here are one-axis by construction)."""
    devices = list(mesh.devices.flat) if mesh is not None else []
    return _conf_topology(conf, len(devices), devices or None)


def host_topology(conf=None) -> Topology:
    """The topology of EVERY device this process can see (no mesh
    needed) — what bench rounds record in their provenance block: the
    detected slice grouping plus the link coefficients the topo bench
    ran under. Falls back to the empty degenerate topology when jax has
    no devices (or is absent)."""
    try:
        import jax

        devices = list(jax.devices())
    except Exception:  # noqa: BLE001 — provenance must never fail a round
        devices = []
    return _conf_topology(conf, len(devices), devices or None)


def topology_for_slots(conf, num_slots: int) -> Topology:
    """The executor-slot view of the topology (for the reduce planner,
    which places tasks on slots, not devices): ``slice_topology``
    partitions the ``num_slots`` contiguous slots the same way it
    partitions devices; auto (no spec) is flat — on a real multi-host
    cluster the driver knows host boundaries from the membership plane
    and passes an explicit topology instead."""
    return _conf_topology(conf, num_slots)


@functools.lru_cache(maxsize=64)
def _slice_mesh_cached(mesh, axis_name: str, lo: int, hi: int):
    import numpy as np
    from jax.sharding import Mesh

    devices = list(mesh.devices.flat)[lo:hi]
    return Mesh(np.array(devices), (axis_name,))


def slice_mesh(mesh, axis_name: str, topology: Topology, s: int):
    """The sub-mesh over slice ``s``'s contiguous devices — what the
    intra-slice fused step runs over. Memoized per (mesh, axis, bounds)
    so per-stage callers reuse the same Mesh object and, through it, the
    fused-step compile cache."""
    lo, hi = topology.slice_bounds(s)
    return _slice_mesh_cached(mesh, axis_name, lo, hi)
