"""Driver/executor control-plane endpoints.

The reference splits roles the same way (java/RdmaNode.java:150-158 — the
driver accepts RPC channels, executors accept passive read-responder
channels; scala/RdmaShuffleManager.scala:73-134 — the driver's receive
listener runs membership):

* ``DriverEndpoint`` — accepts hellos, maintains the ordered membership
  list, broadcasts announces to every known executor
  (scala/RdmaShuffleManager.scala:76-115), hosts per-shuffle driver tables
  (allocated at registerShuffle, scala/RdmaShuffleManager.scala:168-183),
  applies positional publish writes, serves whole-table fetches.
* ``ExecutorEndpoint`` — sends hello on start
  (scala/RdmaShuffleManager.scala:204-226), learns membership from
  announces, serves block-location and block-byte reads out of a local
  ``ShuffleDataSource``, and exposes the client-side fetch calls used by the
  fetcher iterator.

Executor *indices* — the compact ints stored in driver-table entries — are
positions in the announce-ordered membership list (append-only), playing the
role the (address, lkey) pair plays in the reference.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import queue
import struct
import threading
import time
from typing import Dict, List, Optional, Protocol, Tuple

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.driver_client import (DriverClient,
                                                  DriverUnreachableError)
from sparkrdma_tpu.parallel.rpc_msg import (AnnounceMsg, HelloMsg, RpcMsg,
                                            decode_message)
from sparkrdma_tpu.parallel.transport import (
    ChecksumError,
    Connection,
    ConnectionCache,
    ControlServer,
    FetchStatusError,
    TransportError,
    await_response,
)
from sparkrdma_tpu.shuffle.map_output import (
    MAP_ENTRY_SIZE,
    DriverTable,
    MapTaskOutput,
)
from sparkrdma_tpu.utils import trace as trace_mod
from sparkrdma_tpu.utils.ids import ShuffleManagerId

log = logging.getLogger(__name__)

# Dead-slot marker in membership lists: keeps executor indices stable after a
# loss while making the slot unroutable.
from sparkrdma_tpu.utils.ids import ExecutorId as _ExecutorId  # noqa: E402

TOMBSTONE = ShuffleManagerId(_ExecutorId("", "", 0), "", 0)


class DeadExecutorError(RuntimeError):
    """Raised when a fetch resolves to a tombstoned (lost) executor slot."""


def _codec_aad(req, flags: int) -> bytes:
    """Associated data binding a wrapped fetch payload to its request:
    a recorded response replayed onto a different req_id/shuffle or with
    flipped flags fails verification (both sides derive this
    independently — it never travels)."""
    import struct

    return struct.pack("<qiI", req.req_id, req.shuffle_id, flags)


class AsyncFetch:
    """Completion handle for a pipelined fetch issued via
    ``Connection.request_async``: the request is already on the wire;
    ``result()`` finishes it on the CALLING thread (decode, credit
    bookkeeping, status handling) so connection reader threads never
    carry per-fetch CPU work. ``wire_done_s`` is stamped
    (``time.monotonic``) the instant the raw response lands — the
    issue→wire→complete boundary the fetcher's trace spans use."""

    __slots__ = ("wire_done_s", "_fut", "_default_timeout_s", "_complete")

    def __init__(self, fut, default_timeout_s: float, complete):
        self.wire_done_s: Optional[float] = None
        self._fut = fut
        self._default_timeout_s = default_timeout_s
        self._complete = complete
        fut.add_done_callback(self._stamp)

    def _stamp(self, _fut) -> None:
        self.wire_done_s = time.monotonic()

    def done(self) -> bool:
        """True once the raw response (or failure) has landed; a
        subsequent ``result()`` will not block on the wire."""
        return self._fut.done()

    def result(self, timeout: Optional[float] = None):
        tmo = self._default_timeout_s if timeout is None else timeout
        return self._complete(await_response(self._fut, tmo))

    def cancel(self) -> None:
        """Abandon the request: cancelling a still-pending future fires
        the connection's cleanup callback, reclaiming its send-budget
        slot (an abandoned-but-never-answered request must not hold a
        slot forever). No-op once the response has landed — the
        done-callback already released the slot, and the credit
        bookkeeping's orphan path owns any landed-late response."""
        self._fut.cancel()


class ShuffleDataSource(Protocol):
    """What an executor serves to its peers (implemented by the resolver)."""

    def get_output_table(self, shuffle_id: int, map_id: int) -> Optional[MapTaskOutput]:
        ...

    def read_block(self, shuffle_id: int, buf_token: int, offset: int,
                   length: int) -> Optional[bytes]:
        ...


class DriverEndpoint:
    """Control-plane driver.

    With driver HA armed (``ha_standbys`` > 0, or constructed by a
    promoting :class:`~sparkrdma_tpu.shuffle.ha.DriverStandby`), every
    mutation of the tables below is wrapped in an
    :class:`~sparkrdma_tpu.shuffle.ha.OpLog` and streamed to registered
    standbys over the same push channel executors use. ``incarnation``
    is the lease term this endpoint was built at: it composes into the
    HIGH bits of every epoch this endpoint mints
    (:func:`~sparkrdma_tpu.shuffle.ha.compose_epoch`), so after a
    failover every epoch the new primary publishes strictly dominates
    anything the deposed one can still push — the existing keep-highest
    guards ARE the zombie fence. ``restore`` is the promoting standby's
    ``(snapshot_blob | None, tail_records)``: replayed before serving,
    then the authoritative state is re-broadcast (membership, epoch
    rebases, plans, re-finalize, TakeoverMsg)."""

    def __init__(self, conf: Optional[TpuShuffleConf] = None, host: str = "",
                 incarnation: int = 0, server: Optional[ControlServer] = None,
                 lease_store=None, lease_holder: Optional[str] = None,
                 restore=None):
        from sparkrdma_tpu.shuffle.ha import OpLog
        self.conf = conf or TpuShuffleConf()
        bind_host = host or self.conf.driver_host or "127.0.0.1"
        # elastic membership (parallel/membership.py): the epoch-versioned
        # membership plane replaces the old static slot list — slots keep
        # stable indices forever, but each carries a LIVE/DRAINING/DEAD
        # state and every change bumps ONE monotone epoch, pushed to
        # executors as a MembershipBumpMsg on the announce channel.
        from sparkrdma_tpu.parallel.membership import MembershipPlane
        self.membership = MembershipPlane(tombstone=TOMBSTONE)
        # planned-drain accounting (membership.drain_slot): completed
        # graceful retires (zero re-executions) vs deadline/death
        # fallbacks into ordinary tombstone recovery
        self.drains_completed = 0
        self.drain_fallbacks = 0
        self.autoscaler = None
        self._tables: Dict[int, DriverTable] = {}
        self._tables_lock = threading.Lock()
        # metadata plane (shuffle/location_plane.py): per-shuffle location
        # EPOCH — the version reducers' caches validate against. Starts
        # at 1 on register; moves ONLY when location state is repaired
        # (an applied publish overwrites an existing entry, an executor
        # is tombstoned) or the shuffle dies (EPOCH_DEAD). Guarded by
        # _tables_lock (epoch and table always move together).
        self._epochs: Dict[int, int] = {}
        # shuffle -> (ShardMap, owner_gen). The generation is composed
        # like an epoch (ha.compose_epoch: incarnation high, per-
        # incarnation handoff seq low) so a post-failover assignment
        # always dominates every pre-failover owner's.
        self._shard_maps: Dict[int, tuple] = {}
        self.epoch_bumps = 0  # audit: pushed invalidations
        self.shard_handoffs = 0  # audit: shard ownership moves pushed
        self.shard_batches = 0  # audit: owner batches converged
        # adaptive reduce planning (shuffle/planner.py): per-shuffle size
        # histograms fed by publish lengths, the published plans, and the
        # reduce-partition count the manager registered with. Guarded by
        # _tables_lock (sizes and tables always move together).
        self._size_hists: Dict[int, object] = {}
        self._plans: Dict[int, object] = {}
        self._num_partitions: Dict[int, int] = {}
        self.plan_replans = 0  # audit: mid-stage re-plans pushed
        # push-merge (shuffle/push_merge.py): the driver's merged-segment
        # directory per shuffle — fed one-sided by merge targets'
        # MergedPublishMsg, served to reducers (FetchMergedReq), pruned
        # on repair publishes (drop_map) and tombstones (drop_slot).
        # Guarded by _tables_lock like every other per-shuffle table.
        self._merged: Dict[int, object] = {}
        self._finalize_sent: set = set()
        self.merged_publishes = 0  # audit: directory entries applied
        self.merged_zombie_drops = 0  # publishes from a DEAD slot dropped
        # cold tier (shuffle/cold_tier.py): the driver's tiered-blob
        # directory per shuffle — fed one-sided by TieredPublishMsg,
        # served to reducers (FetchTieredReq), pruned on repair
        # publishes (drop_map) but NEVER on tombstones: blobs outlive
        # the executor that uploaded them (that is the point). Guarded
        # by _tables_lock like every other per-shuffle table.
        self._tiered: Dict[int, object] = {}
        self.tiered_publishes = 0  # audit: tiered entries applied
        self.tiered_stale_drops = 0  # publishes of superseded maps dropped
        # (shuffle, map) pairs a repair publish superseded: an upload
        # that was mid-flight when the repair landed publishes LATE —
        # its blob carries the replaced attempt's bytes and must never
        # enter the directory (modelcheck tier_vs_replan). Bounded the
        # same two ways as the merge store's zombie markers; the race
        # it defends against is bounded by upload latency.
        from sparkrdma_tpu.utils.tombstones import TombstoneCache
        self._tiered_superseded = TombstoneCache(ttl_s=30.0, cap=4096)
        self._clients = ConnectionCache(self.conf)
        # One broadcaster thread + a coalescing slot instead of a thread per
        # membership event: N executors joining produce O(N) sends of the
        # newest snapshot, not O(N^2) (the reference pre-connects async and
        # caches for the same reason, java/RdmaNode.java:283-353).
        self._announce_cond = threading.Condition()
        self._announce_pending: Optional[Tuple[List[ShuffleManagerId], int]] = None
        # metadata-plane pushes (epoch bumps, shard maps, shard-entry
        # forwards) ride the SAME broadcaster thread as announces:
        # invalidation is pushed on the existing channel, never polled,
        # and a dead peer's connect budget can never stall a publish
        # handler or the engine's register call. Items are
        # (target | None, msg); None broadcasts to every live member.
        self._push_pending: List[Tuple[Optional[ShuffleManagerId], RpcMsg]] = []
        self._announce_stop = False
        self._broadcaster = threading.Thread(
            target=self._broadcast_loop, daemon=True, name="driver-announce")
        self._broadcaster.start()
        # Long-poll table waiters: shuffle_id -> [(conn, req_id,
        # min_published, deadline)]. Registered when a fetch can't be
        # satisfied yet; answered by the publish that satisfies it (push,
        # not client polling) or by the expiry sweeper with the partial
        # table. Never blocks a handler thread — a blocked handler would
        # deadlock against publishes arriving on the same connection.
        self._waiters: Dict[int, list] = {}
        self._waiters_lock = threading.Lock()
        self._sweeper = threading.Thread(target=self._sweep_waiters,
                                         daemon=True, name="driver-sweeper")
        self._sweeper.start()
        # broadcast blobs (shared_vars.Broadcast): id -> pickled value,
        # served to executors on GetBroadcastReq
        self._broadcasts: Dict[int, bytes] = {}
        self._broadcasts_lock = threading.Lock()
        # commit-fencing audit: publishes rejected as stale (a zombie
        # speculative attempt's late publish)
        self.fenced_publishes = 0
        # tenancy (shuffle/tenancy.py): per-shuffle owning tenant +
        # registration time (the TTL clock), the admission gate on
        # registerShuffle, and the GC sweeper that unregisters expired
        # shuffles (terminal EPOCH_DEAD push; executors reap disk on
        # receipt). Guarded by _tables_lock: tenant and table always
        # move together.
        from sparkrdma_tpu.shuffle.tenancy import AdmissionController
        from sparkrdma_tpu.utils import trace as trace_mod
        self.tracer = trace_mod.get(self.conf)
        self.admission = AdmissionController(
            self.conf.admission_max_inflight,
            self.conf.admission_queue_depth,
            self.conf.admission_retry_after_ms)
        self._tenants: Dict[int, int] = {}
        self._register_times: Dict[int, float] = {}
        self.gc_expired = 0  # audit: TTL-expired shuffles unregistered
        # driver HA (shuffle/ha.py): the replicated-state-machine plane.
        # The op log is armed when HA is configured or this endpoint was
        # promoted from a standby; _ha_lock (reentrant: logged mutations
        # nest — a replayed publish derives epoch bumps) serializes
        # {append, replicate-queue, apply, compact} so log order IS
        # apply order and a snapshot at seq S reflects every op <= S.
        self.incarnation = int(incarnation)
        ha_armed = (self.conf.ha_standbys > 0 or self.incarnation > 0
                    or lease_store is not None)
        self.oplog = (OpLog(self.incarnation,
                            self.conf.oplog_snapshot_every)
                      if ha_armed else None)
        self._ha_lock = threading.RLock()
        self._standbys: List[Tuple[str, str, int]] = []  # (name, host, port)
        self._standbys_lock = threading.Lock()
        self._replaying = False
        self._derived = threading.local()  # in-apply derived-mutation flag
        self.lease_store = lease_store
        self.lease_holder = lease_holder or f"driver-{os.getpid()}"
        self._lease_lost = threading.Event()
        self.ha_failovers_count = 0  # audit: takeovers this endpoint did
        # the server LAST: its accept thread dispatches hellos/joins the
        # moment the socket opens, and the handlers touch membership,
        # admission and tracer state — every field above must exist
        # before the first frame can arrive. A promoting standby hands
        # its OWN server in: its handler delegates here only after
        # promotion returns, so no frame reaches a half-built endpoint.
        if server is not None:
            self.server = server
        else:
            self.server = ControlServer(bind_host, self.conf.driver_port,
                                        self.conf, self._handle,
                                        name="driver")
        if restore is not None:
            self._restore(restore)
        self._lease_thread: Optional[threading.Thread] = None
        if self.lease_store is not None:
            ttl_s = self.conf.driver_lease_ms / 1000
            # a fresh primary claims its term; a promoted one already
            # holds it (try_acquire refuses term == current, harmlessly)
            self.lease_store.try_acquire(self.lease_holder,
                                         self.incarnation, ttl_s)
            self._lease_thread = threading.Thread(
                target=self._lease_loop, daemon=True, name="driver-lease")
            self._lease_thread.start()
        self._gc_thread: Optional[threading.Thread] = None
        if self.conf.shuffle_ttl_ms > 0:
            self._gc_thread = threading.Thread(
                target=self._gc_loop, daemon=True, name="driver-gc")
            self._gc_thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.host, self.server.port

    # -- driver HA: op log, snapshots, restore (shuffle/ha.py) -----------

    def _ha_apply(self, kind: int, payload: bytes, apply_fn):
        """Log one mutation, replicate it, apply it, maybe compact —
        one critical section. The append and its standby-stream push
        are queued BEFORE ``apply_fn`` runs (and so before any
        executor-facing push the apply queues): the broadcaster drains
        FIFO, so a standby holds the op before any executor observes
        its effect — the ordering the failover_vs_ttl_sweep model
        scenario depends on. Derived mutations inside the apply (epoch
        bumps a publish causes, tombstone fallout) see
        ``_derived.active`` and skip logging themselves: replay
        re-derives them from the logged cause."""
        if self.oplog is None or self._replaying:
            return apply_fn()
        with self._ha_lock:
            self._log_op(kind, payload)
            was = getattr(self._derived, "active", False)
            self._derived.active = True
            try:
                out = apply_fn()
            finally:
                self._derived.active = was
            self._maybe_compact()
            return out

    def _in_derived_apply(self) -> bool:
        return getattr(self._derived, "active", False)

    def _log_op(self, kind: int, payload: bytes) -> None:
        rec = self.oplog.append(kind, payload)
        with self._standbys_lock:
            standbys = list(self._standbys)
        for _name, h, p in standbys:
            self._queue_push((h, p), M.OpLogAppendMsg(
                rec.incarnation, rec.seq, rec.kind, rec.payload))

    def _maybe_compact(self) -> None:
        """Fold state into a snapshot every ``oplog_snapshot_every``
        ops. Runs AFTER the triggering op applied (inside _ha_lock), so
        the snapshot at seq S really contains every op <= S and the
        truncated tail loses nothing."""
        from sparkrdma_tpu.shuffle import ha
        if not self.oplog.snapshot_due():
            return
        seq = self.oplog.last_seq()
        blob = ha.encode_snapshot(self.snapshot_state())
        self.oplog.install_snapshot(seq, blob)
        with self._standbys_lock:
            standbys = list(self._standbys)
        for _name, h, p in standbys:
            self._queue_push((h, p), M.SnapshotMsg(self.incarnation, seq,
                                                   blob))

    def snapshot_state(self) -> dict:
        """The replicated control-plane state as a plain dict (bytes
        leaves allowed — the ha snapshot codec base64s them). Size
        histograms are deliberately NOT carried: publishes after the
        snapshot re-feed them via the logged frames, and a post-failover
        plan built from a thinner histogram is still a valid plan (the
        planner degrades to coarser splits, never to an error)."""
        unix_now, mono_now = time.time(), time.monotonic()
        with self._tables_lock:
            shuffles = {}
            for sid, table in self._tables.items():
                plan = self._plans.get(sid)
                merged = self._merged.get(sid)
                tiered = self._tiered.get(sid)
                shuffles[str(sid)] = {
                    "num_maps": table.num_maps,
                    "num_partitions": self._num_partitions.get(sid, 0),
                    "tenant": self._tenants.get(sid, 0),
                    "epoch": self._epochs.get(sid, 1),
                    # wall-clock registration time: monotonic clocks
                    # don't travel between processes, and the promoted
                    # standby must re-derive the TTL sweep from the
                    # REPLICATED registration time (the no-resurrect
                    # invariant), not from its own replay instant
                    "reg_unix": unix_now - (mono_now
                                            - self._register_times.get(
                                                sid, mono_now)),
                    "table": table.to_bytes(),
                    "plan": (plan.to_bytes() if plan is not None
                             else None),
                    "merged": (merged.to_bytes() if merged is not None
                               else None),
                    "tiered": (tiered.to_bytes() if tiered is not None
                               else None),
                    "finalized": sid in self._finalize_sent,
                }
        members, states, epoch = self.membership.snapshot()
        return {"shuffles": shuffles,
                "membership": {"members": [m.serialize() for m in members],
                               "states": list(states),
                               "epoch": epoch}}

    def _restore(self, restore) -> None:
        """Replay ``(snapshot_blob | None, tail_records)`` into this
        endpoint, then re-broadcast the authoritative state under the
        new incarnation. Executor-facing pushes are suppressed during
        the replay (_queue_push drops them) — the takeover re-announce
        at the end is the one authoritative broadcast."""
        from sparkrdma_tpu.shuffle import ha
        blob, tail = restore
        self._replaying = True  # analysis: unguarded-ok(restore runs in __init__ before the server dispatches any handler thread)
        try:
            if blob:
                self._load_snapshot(ha.decode_snapshot(blob))
            for rec in sorted(tail, key=lambda r: (r.incarnation, r.seq)):
                try:
                    self._apply_op(rec)
                except Exception:  # noqa: BLE001 — one bad op must not
                    # strand the takeover; the rebased re-announce below
                    # still invalidates every stale cache
                    log.exception("driver restore: op (%d,%d) kind %d "
                                  "failed", rec.incarnation, rec.seq,
                                  rec.kind)
        finally:
            self._replaying = False  # analysis: unguarded-ok(still inside __init__, single-threaded)
        # seed OUR log with a complete snapshot at seq 0: a standby
        # registering before the first compaction must receive the
        # restored state, or a second failover would lose it
        self.oplog.install_snapshot(0, ha.encode_snapshot(
            self.snapshot_state()))
        self._announce_takeover()

    def _load_snapshot(self, state: dict) -> None:
        from sparkrdma_tpu.shuffle.push_merge import MergedDirectory
        from sparkrdma_tpu.shuffle.planner import ReducePlan
        from sparkrdma_tpu.shuffle.tenancy import AdmissionRejected
        unix_now, mono_now = time.time(), time.monotonic()
        mem = state.get("membership", {})
        if mem.get("members"):
            members = []
            for raw in mem["members"]:
                mid, _ = ShuffleManagerId.deserialize(raw)
                members.append(mid)
            self.membership.restore(members, list(mem.get("states", [])),
                                    int(mem.get("epoch", 0)))
        for key, s in state.get("shuffles", {}).items():
            sid = int(key)
            tenant = int(s.get("tenant", 0))
            try:
                self.admission.admit(tenant, sid)
            except AdmissionRejected:
                # config drift between primaries; the shuffle EXISTS, so
                # restore it anyway — admission re-converges on its next
                # unregister
                log.warning("driver restore: admission rejected restored "
                            "shuffle %d (tenant %d); restoring anyway",
                            sid, tenant)
            with self._tables_lock:
                self._tables[sid] = DriverTable.from_bytes(s["table"])
                self._epochs[sid] = int(s.get("epoch", 1))
                self._num_partitions[sid] = int(s.get("num_partitions", 0))
                self._tenants[sid] = tenant
                age = max(0.0, unix_now - float(s.get("reg_unix",
                                                      unix_now)))
                self._register_times[sid] = mono_now - age
                if s.get("plan") is not None:
                    self._plans[sid] = ReducePlan.from_bytes(s["plan"])
                if s.get("merged") is not None:
                    self._merged[sid] = MergedDirectory.from_bytes(
                        s["merged"])
                if s.get("tiered") is not None:
                    from sparkrdma_tpu.shuffle.cold_tier import \
                        TieredDirectory
                    self._tiered[sid] = TieredDirectory.from_bytes(
                        s["tiered"])
                if s.get("finalized"):
                    self._finalize_sent.add(sid)
                if self.conf.adaptive_plan and sid not in self._size_hists:
                    from sparkrdma_tpu.shuffle.planner import SizeHistogram
                    self._size_hists[sid] = SizeHistogram(
                        int(s["num_maps"]), int(s.get("num_partitions",
                                                      0)))

    def _apply_op(self, rec) -> None:
        """Replay one op record (``_replaying`` is set: handlers mutate
        but push nothing). OP_WIRE replays the encoded frame through the
        normal dispatch — fence floors and epoch guards make an op the
        snapshot already contains a no-op, which is what the replay
        idempotency tests pin."""
        from sparkrdma_tpu.shuffle import ha
        if rec.kind == ha.OP_WIRE:
            try:
                msg = decode_message(rec.payload)
            except ValueError:
                log.warning("driver restore: undecodable wire op (%d,%d)",
                            rec.incarnation, rec.seq)
                return
            self._handle(None, msg)
        elif rec.kind == ha.OP_REGISTER:
            sid, num_maps, num_partitions, tenant, reg_unix = \
                ha.unpack_register(rec.payload)
            self.register_shuffle(sid, num_maps, num_partitions, tenant)
            with self._tables_lock:
                if sid in self._register_times:
                    age = max(0.0, time.time() - reg_unix)
                    self._register_times[sid] = time.monotonic() - age
        elif rec.kind == ha.OP_UNREGISTER:
            self.unregister_shuffle(ha.unpack_sid(rec.payload))
        elif rec.kind == ha.OP_BUMP:
            self.bump_epoch(ha.unpack_sid(rec.payload),
                            reason="replayed bump")
        elif rec.kind == ha.OP_TOMBSTONE:
            mid, _ = ShuffleManagerId.deserialize(rec.payload)
            self.remove_member(mid)
        elif rec.kind == ha.OP_DRAIN:
            slot, step = ha.unpack_drain(rec.payload)
            self.drain_transition(slot, step)
        elif rec.kind == ha.OP_PLAN:
            from sparkrdma_tpu.shuffle.planner import ReducePlan
            plan = ReducePlan.from_bytes(rec.payload)
            self._install_plan(plan.shuffle_id, plan)
        elif rec.kind == ha.OP_FINALIZE:
            self.finalize_merge(ha.unpack_sid(rec.payload))
        else:
            log.warning("driver restore: unknown op kind %d", rec.kind)

    def _announce_takeover(self) -> None:
        """The promoted primary's one authoritative re-broadcast:
        membership snapshot, every live shuffle's location epoch rebased
        into the new incarnation, the newest plans, re-finalize triggers
        (merge targets idempotently re-publish segments the op-log lag
        window may have missed), and the TakeoverMsg that re-points
        every executor's DriverClient."""
        from sparkrdma_tpu.shuffle.ha import rebase_epoch
        inc = self.incarnation
        # TTL re-derive FIRST, from the replicated registration clocks:
        # a restored-but-expired shuffle dies (ordinary EPOCH_DEAD push)
        # before any re-broadcast could resurrect it at a reducer
        self.gc_sweep()
        # the takeover pointer leads the queue so executor retries
        # re-aim before the state pushes land behind it
        self._queue_push(None, M.TakeoverMsg(inc, self.server.host,
                                             self.server.port))
        members, states, mepoch = self.membership.snapshot()
        mepoch = self.membership.rebase_epoch(rebase_epoch(mepoch, inc))
        self.publish_membership(members, states, mepoch)
        with self._tables_lock:
            sids = sorted(self._tables)
            plans = {}
            for sid in sids:
                self._epochs[sid] = rebase_epoch(self._epochs[sid], inc)
                plan = self._plans.get(sid)
                if plan is not None:
                    plan = dataclasses.replace(
                        plan, plan_epoch=rebase_epoch(plan.plan_epoch,
                                                      inc))
                    self._plans[sid] = plan
                    plans[sid] = plan.to_bytes()
            epochs = {sid: self._epochs[sid] for sid in sids}
            refinalize = [sid for sid in sids
                          if sid in self._finalize_sent]
        for sid in sids:
            self._queue_push(None, M.EpochBumpMsg(sid, epochs[sid]))
        for sid in sids:
            if sid in plans:
                self._queue_push(None, M.ReducePlanMsg(plans[sid]))
        for sid in refinalize:
            self._queue_push(None, M.FinalizeSegmentsReq(0, sid))
        self.ha_failovers_count += 1
        log.warning("driver: incarnation %d serving — %d shuffles "
                    "restored, membership epoch %d re-announced", inc,
                    len(sids), mepoch)

    def _on_standby_hello(self, msg: "M.StandbyHelloMsg") -> None:
        """Register (or re-register) a standby and queue its catch-up:
        the newest snapshot plus the whole tail. The standby dedupes by
        (incarnation, seq), so over-sending is harmless; under-sending
        would strand it cold."""
        if self.oplog is None:
            log.warning("driver: standby hello from %s with HA off "
                        "(set ha_standbys > 0)", msg.name)
            return
        addr = (msg.host, msg.port)
        with self._standbys_lock:
            self._standbys = ([s for s in self._standbys
                               if s[0] != msg.name]
                              + [(msg.name, msg.host, msg.port)])
        with self._ha_lock:
            snap = self.oplog.snapshot()
            blob, tail = self.oplog.restore_point()
            if blob is not None:
                self._queue_push(addr, M.SnapshotMsg(self.incarnation,
                                                     snap[0], blob))
            for rec in tail:
                if rec.seq > msg.last_seq or blob is not None:
                    self._queue_push(addr, M.OpLogAppendMsg(
                        rec.incarnation, rec.seq, rec.kind, rec.payload))
        log.info("driver: standby %s registered at %s:%d (caught up "
                 "from seq %d)", msg.name, msg.host, msg.port,
                 msg.last_seq)

    def _lease_loop(self) -> None:
        """Renew the leadership lease at a quarter TTL. The instant a
        renew fails a higher term exists — we are the zombie: go mute
        (stop the broadcaster) so no further push leaves this endpoint.
        Everything already in flight is fenced by incarnation at every
        receiver; muting just stops paying for doomed sends."""
        ttl_s = self.conf.driver_lease_ms / 1000
        period = max(0.01, ttl_s / 4)
        while not self._announce_stop and not self._lease_lost.is_set():
            if not self.lease_store.renew(self.lease_holder,
                                          self.incarnation, ttl_s):
                self._lease_lost.set()
                log.warning("driver: lease lost at incarnation %d — a "
                            "newer primary exists; muting broadcasts",
                            self.incarnation)
                with self._announce_cond:
                    self._announce_stop = True
                    self._announce_cond.notify()
                return
            self._lease_lost.wait(period)

    def deposed(self) -> bool:
        """True once this endpoint observed a higher lease term (tests
        and the chaos harness poll this)."""
        return self._lease_lost.is_set()

    def drain_transition(self, slot: int, step: int):
        """The logged form of the three membership drain mutations
        (``ha.DRAIN_BEGIN/ABORT/RETIRE``) — drain_slot and abort_drain
        route through here so a failover mid-drain replays to the same
        slot states."""
        from sparkrdma_tpu.shuffle import ha
        mutators = {ha.DRAIN_BEGIN: self.membership.begin_drain,
                    ha.DRAIN_ABORT: self.membership.abort_drain,
                    ha.DRAIN_RETIRE: self.membership.retire}
        base_fn = mutators[step]

        def apply_fn(s: int):
            res = base_fn(s)
            if res is not None and step == ha.DRAIN_BEGIN:
                # a draining OWNER hands its shards off NOW, not at the
                # eventual tombstone: the drain exists to walk work off
                # the host, and a fence-CAS range it still owned would
                # re-pin every publish in its map-range to it
                self._shard_handoff(s, reason="drain")
            return res

        if self.oplog is not None and not self._replaying:
            return self._ha_apply(ha.OP_DRAIN, ha.op_drain(slot, step),
                                  lambda: apply_fn(slot))
        return apply_fn(slot)

    # -- shuffle registry (driver side of registerShuffle) ---------------

    def register_shuffle(self, shuffle_id: int, num_maps: int,
                         num_partitions: int = 0,
                         tenant: int = 0) -> None:
        """Allocate the per-shuffle map-output table
        (scala/RdmaShuffleManager.scala:168-172) at epoch 1, and — with
        ``metadata_shards`` on — assign map-range shards over the live
        members and push the assignment so reducers aim cold-path table
        syncs at shard hosts instead of the driver. With
        ``adaptive_plan`` on, a :class:`~.planner.SizeHistogram` is
        allocated too (fed by the lengths riding each publish).

        ``tenant`` mints the owning tenant: admission control gates
        here (queue-or-reject past the per-tenant in-flight cap — see
        ``admission_max_inflight``) and the mapping is pushed to every
        executor as a TenantMapMsg so serve-path fair share and quota
        ledgers charge the right owner."""
        from sparkrdma_tpu.shuffle import ha
        if self.oplog is not None and not self._replaying:
            return self._ha_apply(
                ha.OP_REGISTER,
                ha.op_register(shuffle_id, num_maps, num_partitions,
                               tenant, time.time()),
                lambda: self._register_impl(shuffle_id, num_maps,
                                            num_partitions, tenant))
        return self._register_impl(shuffle_id, num_maps, num_partitions,
                                   tenant)

    def _register_impl(self, shuffle_id: int, num_maps: int,
                       num_partitions: int = 0, tenant: int = 0) -> None:
        from sparkrdma_tpu.shuffle.ha import compose_epoch
        from sparkrdma_tpu.shuffle.location_plane import ShardMap

        def admit_event(kind: str, t: int, waited_ms: int) -> None:
            # literal names: the trace registry's drift lint rejects
            # computed emission names by design
            if kind == "accept":
                self.tracer.instant("admit.accept", "tenant",
                                    shuffle=shuffle_id, tenant=t,
                                    waited_ms=waited_ms)
            elif kind == "queue":
                self.tracer.instant("admit.queue", "tenant",
                                    shuffle=shuffle_id, tenant=t)
            else:
                self.tracer.instant("admit.reject", "tenant",
                                    shuffle=shuffle_id, tenant=t,
                                    waited_ms=waited_ms)

        # elastic capacity: the fleet present at the FIRST register is
        # the baseline admission was sized for; from here every
        # membership change rescales the cap/retry hints (set_fleet)
        if self.membership.freeze_baseline():
            self._update_admission_fleet()
        # may raise AdmissionRejected (retry-after hint attached); an
        # admitted-then-duplicate register releases its slot below
        self.admission.admit(tenant, shuffle_id, on_event=admit_event)
        shard_map = None
        with self._tables_lock:
            if shuffle_id in self._tables:
                # a duplicate register under a DIFFERENT tenant id just
                # added the shuffle to that tenant's inflight set, and
                # on_unregister will only ever release the RECORDED
                # owner's slot — release the stray one (outside the
                # table lock, matching unregister's lock order)
                stray = self._tenants.get(shuffle_id, 0) != tenant
            else:
                stray = None
        if stray is not None:
            if stray:
                self.admission.on_unregister(tenant, shuffle_id)
            return
        with self._tables_lock:
            if shuffle_id in self._tables:
                # lost a same-sid register race since the check above:
                # same stray-slot rule as the fast duplicate path
                if self._tenants.get(shuffle_id, 0) != tenant:
                    self.admission.on_unregister(tenant, shuffle_id)
                return
            self._tables[shuffle_id] = DriverTable(num_maps)
            # epoch 1 of THIS incarnation: identical to the pre-HA 1 at
            # incarnation 0; after a failover, strictly above anything
            # the previous incarnation ever published for a reused id
            self._epochs[shuffle_id] = compose_epoch(self.incarnation, 1)
            self._num_partitions[shuffle_id] = num_partitions
            self._tenants[shuffle_id] = int(tenant)
            self._register_times[shuffle_id] = time.monotonic()
            if self.conf.adaptive_plan:
                from sparkrdma_tpu.shuffle.planner import SizeHistogram
                self._size_hists[shuffle_id] = SizeHistogram(
                    num_maps, num_partitions)
            if self.conf.metadata_shards > 0:
                # shard hosts come from PLACEABLE membership: assign
                # consults the plane directly, so a draining slot —
                # about to leave — can never adopt a replica or (in
                # ownership mode) a fence-CAS range
                shard_map = ShardMap.assign(num_maps, self.membership,
                                            self.conf.metadata_shards)
                if shard_map is not None:
                    shard_gen = compose_epoch(self.incarnation, 1)
                    self._shard_maps[shuffle_id] = (shard_map, shard_gen)
        if shard_map is not None:
            self._queue_push(None, M.ShardMapMsg(
                shuffle_id, shard_gen, num_maps, shard_map.shard_slots))
        if tenant != 0:
            # teach executors the owner (serve-path fair share, cache
            # charging). Skipped for the default tenant so pre-tenancy
            # deployments put ZERO new frames on the wire — TTL alone
            # needs no push (only the driver enforces it; expiry
            # arrives as the ordinary EPOCH_DEAD).
            self._queue_push(None, M.TenantMapMsg(
                shuffle_id, int(tenant), self.conf.shuffle_ttl_ms))

    def unregister_shuffle(self, shuffle_id: int) -> None:
        from sparkrdma_tpu.shuffle import ha
        if self.oplog is not None and not self._replaying:
            # log-before-push discipline: the standby stream holds the
            # unregister before any executor can observe the EPOCH_DEAD
            # it causes, so a takeover can never resurrect a shuffle a
            # reducer already saw die
            return self._ha_apply(ha.OP_UNREGISTER, ha.op_sid(shuffle_id),
                                  lambda: self._unregister_impl(shuffle_id))
        return self._unregister_impl(shuffle_id)

    def _unregister_impl(self, shuffle_id: int) -> None:
        with self._tables_lock:
            known = self._tables.pop(shuffle_id, None) is not None
            self._epochs.pop(shuffle_id, None)
            self._shard_maps.pop(shuffle_id, None)
            self._size_hists.pop(shuffle_id, None)
            self._plans.pop(shuffle_id, None)
            self._num_partitions.pop(shuffle_id, None)
            self._merged.pop(shuffle_id, None)
            self._tiered.pop(shuffle_id, None)
            self._finalize_sent.discard(shuffle_id)
            tenant = self._tenants.pop(shuffle_id, 0)
            self._register_times.pop(shuffle_id, None)
        if known:
            # free the tenant's admission slot (wakes queued registers)
            self.admission.on_unregister(tenant, shuffle_id)
        # unblock long-pollers: the shuffle is gone, answer "unknown"
        with self._waiters_lock:
            waiters = self._waiters.pop(shuffle_id, [])
        for conn, req_id, _, _ in waiters:
            self._answer_waiter(conn, M.FetchTableResp(req_id, -1, b"",
                                                       M.EPOCH_DEAD))
        if known:
            # terminal push: caches (location views, warm partitions,
            # shard replicas) drop the shuffle instead of re-validating
            # against a version that will never exist again
            self._queue_push(None, M.EpochBumpMsg(shuffle_id,
                                                  M.EPOCH_DEAD))

    def epoch_of(self, shuffle_id: int) -> Optional[int]:
        """The shuffle's current location-state version (None =
        unregistered)."""
        with self._tables_lock:
            return self._epochs.get(shuffle_id)

    # -- tenancy (shuffle/tenancy.py) ------------------------------------

    def tenant_of(self, shuffle_id: int) -> int:
        with self._tables_lock:
            return self._tenants.get(shuffle_id, 0)

    def _touch_locked(self, shuffle_id: int) -> None:
        """Refresh the shuffle's TTL clock (caller holds _tables_lock):
        the TTL is an IDLE bound, not a registration-age bound — a
        publish or driver table sync proves the job is alive, so the
        GC sweep reaps only shuffles no one has touched for a full
        TTL. Warm iterative jobs that issue zero driver RPCs by design
        should size shuffle_ttl_ms above their run or disable it."""
        if self._replaying:
            # Failover replay must not freshen TTL clocks: the restored
            # reg_unix already carries the true idle age, and replayed
            # publishes are history, not fresh liveness proof.
            return
        if shuffle_id in self._register_times:
            self._register_times[shuffle_id] = time.monotonic()

    def live_shuffles(self) -> List[int]:
        """Registered shuffle ids (the GC sweep's authoritative live
        set — ``manager.gc_orphans`` feeds it to executors)."""
        with self._tables_lock:
            return sorted(self._tables)

    def active_tenant_count(self) -> int:
        """Distinct tenants holding registered shuffles (>= 1): the
        divisor for the even-share HBM/cache sizing."""
        with self._tables_lock:
            return max(1, len(set(self._tenants.values()) or {0}))

    def gc_sweep(self, now: Optional[float] = None) -> List[int]:
        """Unregister shuffles idle (no publish, no table sync) longer
        than ``shuffle_ttl_ms`` (ROADMAP item 1's shuffle TTL/GC). The
        terminal EPOCH_DEAD push makes every executor reap the
        shuffle's committed outputs, merged segments and overflow blobs
        from disk. Returns the expired ids (the GC thread calls this on
        a ttl/4 cadence; public for deterministic tests)."""
        ttl_s = self.conf.shuffle_ttl_ms / 1000
        if ttl_s <= 0:
            return []
        now = time.monotonic() if now is None else now
        with self._tables_lock:
            expired = [sid for sid, t0 in self._register_times.items()
                       if now - t0 > ttl_s]
        for sid in expired:
            self.tracer.instant("admit.expire", "tenant", shuffle=sid,
                                tenant=self.tenant_of(sid))
            log.info("driver GC: shuffle %d exceeded its %dms TTL",
                     sid, self.conf.shuffle_ttl_ms)
            self.unregister_shuffle(sid)
            self.gc_expired += 1
        return expired

    def _gc_loop(self) -> None:
        period = max(0.05, self.conf.shuffle_ttl_ms / 4000)
        while not self.server.stopped:
            time.sleep(period)
            try:
                self.gc_sweep()
            except Exception:  # noqa: BLE001 — the sweeper must live
                log.exception("shuffle TTL sweep failed")

    def bump_epoch(self, shuffle_id: int, reason: str = "") -> Optional[int]:
        """Advance one shuffle's epoch and push the invalidation. The
        driver calls this itself on repair publishes and tombstones
        (DERIVED bumps — replay re-derives them from the logged cause,
        so only out-of-band calls log their own OP_BUMP); public for
        engines that learn of staleness out of band."""
        from sparkrdma_tpu.shuffle import ha
        if (self.oplog is not None and not self._replaying
                and not self._in_derived_apply()):
            return self._ha_apply(ha.OP_BUMP, ha.op_sid(shuffle_id),
                                  lambda: self._bump_impl(shuffle_id,
                                                          reason))
        return self._bump_impl(shuffle_id, reason)

    def _bump_impl(self, shuffle_id: int, reason: str = "") -> Optional[int]:
        with self._tables_lock:
            if shuffle_id not in self._epochs:
                return None
            self._epochs[shuffle_id] += 1
            epoch = self._epochs[shuffle_id]
        self.epoch_bumps += 1
        log.info("driver: epoch bump shuffle %d -> %d%s", shuffle_id,
                 epoch, f" ({reason})" if reason else "")
        self._queue_push(None, M.EpochBumpMsg(shuffle_id, epoch))
        return epoch

    # -- adaptive reduce planning (shuffle/planner.py) -------------------

    def size_histogram(self, shuffle_id: int):
        """The shuffle's SizeHistogram (None when adaptive planning is
        off or the shuffle is unregistered)."""
        with self._tables_lock:
            return self._size_hists.get(shuffle_id)

    def reduce_plan(self, shuffle_id: int):
        """The current published ReducePlan, or None."""
        with self._tables_lock:
            return self._plans.get(shuffle_id)

    def _plan_inputs(self, shuffle_id: int):
        """(hist, owners, live_slots, avoid_slots) for plan
        construction, or None. ``live_slots`` keeps DRAINING members —
        their bytes still count for locality accounting and split
        bounds — while ``avoid_slots`` names them so placement steers
        new reduce work onto slots that will outlive the stage."""
        with self._tables_lock:
            hist = self._size_hists.get(shuffle_id)
            table = self._tables.get(shuffle_id)
        if hist is None or table is None:
            return None
        owners = {}
        for m in range(table.num_maps):
            entry = table.entry(m)
            if entry is not None:
                owners[m] = entry[1]
        live = self.membership.live_slots(include_draining=True)
        avoid = self.membership.draining_slots()
        return hist, owners, live, avoid

    def build_reduce_plan(self, shuffle_id: int, tracer=None):
        """Build (or rebuild) the shuffle's ReducePlan from the size
        histogram at map-stage completion and PUSH it on the broadcast
        channel — the plan is a one-sided, driver-published artifact
        like the location tables. Returns the plan, or None when
        adaptive planning is off / the shuffle is unknown / no sizes
        ever arrived (mixed-version executors): callers fall back to
        the identity plan, so a size-less cluster degrades to today's
        behavior, never to an error."""
        from sparkrdma_tpu.shuffle.planner import ReducePlanner
        if self.conf.shard_ownership and self.conf.metadata_shards > 0:
            # owner-batch convergence is asynchronous (bounded by the
            # executors' flush interval): planning at map-stage
            # completion must not read the histogram mid-echo, so wait
            # — briefly, bounded — for the table to reach its map count
            with self._tables_lock:
                table = self._tables.get(shuffle_id)
            if table is not None:
                deadline = time.monotonic() + 0.5
                while (table.num_published < table.num_maps
                       and time.monotonic() < deadline):
                    time.sleep(0.005)
        inputs = self._plan_inputs(shuffle_id)
        if inputs is None:
            return None
        hist, owners, live, avoid = inputs
        if hist.maps_recorded == 0 or hist.num_partitions == 0:
            return None
        from sparkrdma_tpu.shuffle.ha import compose_epoch
        with self._tables_lock:
            prev = self._plans.get(shuffle_id)
        epoch = (prev.plan_epoch + 1 if prev is not None
                 else compose_epoch(self.incarnation, 1))
        plan = ReducePlanner(self.conf).plan(shuffle_id, hist, owners,
                                             live, plan_epoch=epoch,
                                             tracer=tracer,
                                             avoid_slots=avoid)
        if not self._install_plan(shuffle_id, plan):
            return None  # unregistered while planning
        log.info("driver: reduce plan shuffle %d epoch %d: %s",
                 shuffle_id, plan.plan_epoch, plan.counts())
        return plan

    def _install_plan(self, shuffle_id: int, plan) -> bool:
        """Install + push one plan, logged as OP_PLAN (the plan BYTES
        are authoritative — replay installs rather than re-deriving, so
        a failover preserves the exact task layout reducers hold)."""
        from sparkrdma_tpu.shuffle import ha

        def apply() -> bool:
            with self._tables_lock:
                if shuffle_id not in self._tables:
                    return False
                self._plans[shuffle_id] = plan
            self._queue_push(None, M.ReducePlanMsg(plan.to_bytes()))
            return True

        if (self.oplog is not None and not self._replaying
                and not self._in_derived_apply()):
            return self._ha_apply(ha.OP_PLAN, plan.to_bytes(), apply)
        return apply()

    def replan_reduce(self, shuffle_id: int, completed_task_ids,
                      dead_slot: int = -1, tracer=None):
        """Mid-stage re-plan after an executor loss: surviving reducers
        keep their completed ranges; only ORPHANED tasks (incomplete,
        placed on a slot that is dead or tombstoned) re-assign to live
        slots, under a bumped plan epoch, pushed like the original."""
        from sparkrdma_tpu.shuffle.planner import ReducePlanner
        with self._tables_lock:
            plan = self._plans.get(shuffle_id)
        if plan is None:
            return None
        inputs = self._plan_inputs(shuffle_id)
        if inputs is None:
            return None
        hist, owners, live, avoid = inputs
        if dead_slot >= 0:
            live = [s for s in live if s != dead_slot]
        if not live:
            return None
        new_plan = ReducePlanner(self.conf).replan(
            plan, hist, owners, live, completed_task_ids, tracer=tracer,
            avoid_slots=avoid)
        if not self._install_plan(shuffle_id, new_plan):
            return None
        self.plan_replans += 1
        log.info("driver: reduce RE-plan shuffle %d epoch %d (dead slot "
                 "%d)", shuffle_id, new_plan.plan_epoch, dead_slot)
        return new_plan

    def _on_fetch_plan(self, msg: "M.FetchPlanReq") -> RpcMsg:
        with self._tables_lock:
            known = msg.shuffle_id in self._tables
            plan = self._plans.get(msg.shuffle_id)
        if plan is not None:
            return M.FetchPlanResp(msg.req_id, M.STATUS_OK,
                                   plan.to_bytes())
        return M.FetchPlanResp(
            msg.req_id,
            M.STATUS_ERROR if known else M.STATUS_UNKNOWN_SHUFFLE, b"")

    # -- push-merge directory (shuffle/push_merge.py) --------------------

    def _on_merged_publish(self, msg: "M.MergedPublishMsg") -> None:
        """Apply one finalized merged segment into the directory —
        one-sided like a location publish; problems log driver-side."""
        from sparkrdma_tpu.shuffle.push_merge import (MergedDirectory,
                                                      MergedEntry)
        with self._tables_lock:
            # zombie guard: a finalize publish from a slot tombstoned
            # while the message was in flight must not re-enter the
            # directory — on_slot_dead already pruned that slot, and a
            # resurrected entry would serve to reducers stamped with
            # the POST-bump epoch (the modelcheck merged-live
            # invariant). Checked INSIDE _tables_lock: remove_member
            # tombstones the slot before on_slot_dead takes this lock
            # for the prune, so a publish that saw the slot live here
            # applies before the prune, never after it. (The nesting
            # _tables_lock -> membership._lock matches the register
            # path; nothing nests the other way.)
            members = self.membership.members()
            if (0 <= msg.exec_index < len(members)
                    and members[msg.exec_index] == TOMBSTONE):
                self.merged_zombie_drops += 1
                log.info("driver: dropped merged publish from DEAD "
                         "slot %d for shuffle %d", msg.exec_index,
                         msg.shuffle_id)
                return
            table = self._tables.get(msg.shuffle_id)
            if table is None:
                log.warning("driver: merged publish for unknown shuffle "
                            "%d", msg.shuffle_id)
                return
            parts = self._num_partitions.get(msg.shuffle_id, 0)
            if parts and not 0 <= msg.partition_id < parts:
                log.warning("driver: merged publish with bad partition "
                            "%d for shuffle %d", msg.partition_id,
                            msg.shuffle_id)
                return
            directory = self._merged.get(msg.shuffle_id)
            if directory is None:
                directory = MergedDirectory()
                self._merged[msg.shuffle_id] = directory
            directory.apply(MergedEntry(
                msg.partition_id, msg.exec_index, msg.token, msg.nbytes,
                msg.crc32, msg.covered, msg.ranges))
            self.merged_publishes += 1

    def _on_fetch_merged(self, msg: "M.FetchMergedReq") -> RpcMsg:
        with self._tables_lock:
            known = msg.shuffle_id in self._tables
            epoch = self._epochs.get(msg.shuffle_id, 0)
            directory = self._merged.get(msg.shuffle_id)
            data = directory.to_bytes() if directory is not None else b""
        if not known:
            return M.FetchMergedResp(msg.req_id, M.STATUS_UNKNOWN_SHUFFLE,
                                     M.EPOCH_DEAD, b"")
        return M.FetchMergedResp(msg.req_id, M.STATUS_OK, epoch, data)

    def merged_directory(self, shuffle_id: int):
        """Snapshot of the shuffle's merged directory (tests/benches
        poll this for coverage; None = nothing published yet)."""
        from sparkrdma_tpu.shuffle.push_merge import MergedDirectory
        with self._tables_lock:
            directory = self._merged.get(shuffle_id)
            return (MergedDirectory.from_bytes(directory.to_bytes())
                    if directory is not None else None)

    def merged_covering(self, shuffle_id: int, maps, exclude_slot: int = -1
                        ) -> set:
        """Which of ``maps`` have EVERY reduce partition covered by the
        merged entry a retrying reducer will actually SELECT — the
        re-point set of recovery: these maps need no re-execution.

        This mirrors the fetcher's resolution exactly (one entry per
        partition: widest live coverage, slot tie-break — a segment's
        bytes cannot be sliced per map, so a reducer consumes at most
        ONE entry per partition and coverage must be judged against
        that entry, not the union over replicas; a union answer could
        re-point a map the chosen entry doesn't carry and strand the
        retry on the dead owner)."""
        from sparkrdma_tpu.shuffle.push_merge import MergedDirectory
        with self._tables_lock:
            live_dir = self._merged.get(shuffle_id)
            parts = self._num_partitions.get(shuffle_id, 0)
            # snapshot under the lock: late finalize publishes and
            # tombstone pruning mutate the live directory concurrently
            directory = (MergedDirectory.from_bytes(live_dir.to_bytes())
                         if live_dir is not None else None)
        if directory is None or parts <= 0:
            return set()
        members = self.membership.members()

        def live(slot: int) -> bool:
            return (slot != exclude_slot and slot < len(members)
                    and members[slot] != TOMBSTONE)

        chosen = []
        for p in range(parts):
            entries = [e for e in directory.entries(p) if live(e.slot)]
            chosen.append(entries[0] if entries else None)
        covered = set()
        for m in maps:
            if all(e is not None and e.covers(m) for e in chosen):
                covered.add(m)
        return covered

    # -- cold-tier directory (shuffle/cold_tier.py) ----------------------

    def _on_tiered_publish(self, msg: "M.TieredPublishMsg") -> None:
        """Apply one cold-tier blob into the directory — one-sided like
        a merged publish, but with NO zombie-slot guard: a blob
        uploaded by a since-tombstoned executor is still durable and
        still serves (blobs have no owner to die). Unknown-shuffle and
        bad-partition guards stay."""
        from sparkrdma_tpu.shuffle.cold_tier import (TieredDirectory,
                                                     TieredEntry)
        with self._tables_lock:
            table = self._tables.get(msg.shuffle_id)
            if table is None:
                log.warning("driver: tiered publish for unknown shuffle "
                            "%d", msg.shuffle_id)
                return
            parts = self._num_partitions.get(msg.shuffle_id, 0)
            if parts and not 0 <= msg.partition_id < parts:
                log.warning("driver: tiered publish with bad partition "
                            "%d for shuffle %d", msg.partition_id,
                            msg.shuffle_id)
                return
            table_maps = table.num_maps
            from sparkrdma_tpu.shuffle.push_merge import bitmap_get
            if any(bitmap_get(msg.covered, m)
                   and (msg.shuffle_id, m) in self._tiered_superseded
                   for m in range(table_maps)):
                # the blob holds a repair-superseded attempt's bytes:
                # the upload started before the repair landed, the
                # publish arrived after drop_map pruned the directory —
                # letting it in would resurrect the stale coverage
                self.tiered_stale_drops += 1
                log.info("driver: dropped tiered publish of superseded "
                         "map for shuffle %d partition %d",
                         msg.shuffle_id, msg.partition_id)
                return
            directory = self._tiered.get(msg.shuffle_id)
            if directory is None:
                directory = TieredDirectory()
                self._tiered[msg.shuffle_id] = directory
            directory.apply(TieredEntry(
                msg.partition_id, msg.blob_key, msg.nbytes, msg.crc32,
                msg.covered))
            self.tiered_publishes += 1

    def _on_fetch_tiered(self, msg: "M.FetchTieredReq") -> RpcMsg:
        with self._tables_lock:
            known = msg.shuffle_id in self._tables
            epoch = self._epochs.get(msg.shuffle_id, 0)
            directory = self._tiered.get(msg.shuffle_id)
            data = directory.to_bytes() if directory is not None else b""
        if not known:
            return M.FetchTieredResp(msg.req_id, M.STATUS_UNKNOWN_SHUFFLE,
                                     M.EPOCH_DEAD, b"")
        return M.FetchTieredResp(msg.req_id, M.STATUS_OK, epoch, data)

    def tiered_directory(self, shuffle_id: int):
        """Snapshot of the shuffle's tiered directory (tests/benches
        poll this for coverage; None = nothing tiered yet)."""
        from sparkrdma_tpu.shuffle.cold_tier import TieredDirectory
        with self._tables_lock:
            directory = self._tiered.get(shuffle_id)
            return (TieredDirectory.from_bytes(directory.to_bytes())
                    if directory is not None else None)

    def tiered_covering(self, shuffle_id: int, maps) -> set:
        """Which of ``maps`` have EVERY reduce partition covered by the
        cold tier — recovery's second re-point set, checked after
        ``merged_covering``: these maps need no re-execution even when
        no live replica holds them. Coverage is judged against the
        UNION of a partition's blob entries (unlike merged: a reducer
        can restore several blobs per partition — whole-segment blobs
        and per-map drain rows compose), and there is no liveness
        filter — blobs have no owner to exclude."""
        from sparkrdma_tpu.shuffle.cold_tier import TieredDirectory
        with self._tables_lock:
            live_dir = self._tiered.get(shuffle_id)
            parts = self._num_partitions.get(shuffle_id, 0)
            directory = (TieredDirectory.from_bytes(live_dir.to_bytes())
                         if live_dir is not None else None)
        if directory is None or parts <= 0:
            return set()
        covered = set()
        for m in maps:
            if all(directory.covering(m, p) for p in range(parts)):
                covered.add(m)
        return covered

    def finalize_merge(self, shuffle_id: int) -> None:
        """Broadcast the finalize trigger for one shuffle's merge
        targets (also queued automatically when the last map publishes;
        targets finalize idempotently)."""
        from sparkrdma_tpu.shuffle import ha

        def apply() -> None:
            with self._tables_lock:
                if shuffle_id in self._finalize_sent:
                    return
                self._finalize_sent.add(shuffle_id)
            self._queue_push(None, M.FinalizeSegmentsReq(0, shuffle_id))

        with self._tables_lock:
            if shuffle_id in self._finalize_sent:
                return  # cheap pre-check: no op logged for a duplicate
        if (self.oplog is not None and not self._replaying
                and not self._in_derived_apply()):
            return self._ha_apply(ha.OP_FINALIZE, ha.op_sid(shuffle_id),
                                  apply)
        return apply()

    def refinalize_merge(self, shuffle_id: int) -> None:
        """Re-broadcast the finalize trigger: drain re-pushes REOPEN
        already-sealed segments on their targets, and the new rows only
        publish into the merged directory on a fresh finalize. Only
        shuffles whose map stage is COMPLETE re-finalize — sealing a
        mid-stage shuffle early would shed every later background push
        (membership.drain_slot documents the mid-map-stage fallback)."""
        if not self.conf.push_merge:
            return
        with self._tables_lock:
            table = self._tables.get(shuffle_id)
            if table is None or table.num_published < table.num_maps:
                return
            self._finalize_sent.discard(shuffle_id)
        self.finalize_merge(shuffle_id)

    def map_entry(self, shuffle_id: int, map_id: int):
        """Current (token, exec_index) for one map, or None (unpublished
        OR unknown shuffle — use :meth:`has_shuffle` to tell apart). Lets
        an in-process engine VERIFY a repair publish has landed:
        publishes are one-sided (no ack, like the reference's RDMA WRITE
        into the table), and the long-poll sync point only covers the
        publish COUNT — a repair overwrite doesn't change the count, so
        recovery must observe the entry itself."""
        with self._tables_lock:
            table = self._tables.get(shuffle_id)
        return table.entry(map_id) if table is not None else None

    def has_shuffle(self, shuffle_id: int) -> bool:
        with self._tables_lock:
            return shuffle_id in self._tables

    # -- broadcast registry (shared_vars) --------------------------------

    def register_broadcast(self, bcast_id: int, blob: bytes) -> None:
        with self._broadcasts_lock:
            self._broadcasts[bcast_id] = blob

    def unregister_broadcast(self, bcast_id: int) -> None:
        with self._broadcasts_lock:
            self._broadcasts.pop(bcast_id, None)

    def members(self) -> List[ShuffleManagerId]:
        return self.membership.members()

    def client_conn(self, peer: ShuffleManagerId) -> Connection:
        """A cached control connection to one member (the drain
        coordinator's DrainReq rides this)."""
        return self._clients.get(peer.rpc_host, peer.rpc_port)

    def publish_membership(self, snapshot: List[ShuffleManagerId],
                           states: List[int], epoch: int) -> None:
        """Broadcast one committed membership change: the full announce
        (legacy peers understand exactly this much), the slot-state
        bump (elastic peers recompute placement/targets/health from
        it), and the admission capacity rescale."""
        self._queue_announce(snapshot, epoch)
        self._queue_push(None, M.MembershipBumpMsg(epoch, states))
        self._update_admission_fleet()

    def _update_admission_fleet(self) -> None:
        self.admission.set_fleet(len(self.membership.live_slots()),
                                 self.membership.baseline())

    def remove_member(self, manager_id: ShuffleManagerId) -> None:
        """Executor-loss cleanup (scala/RdmaShuffleManager.scala:155-165).

        The slot is kept (indices are stable); the entry is tombstoned so
        fetchers fail fast instead of contacting a dead peer. The tombstoned
        snapshot is re-announced so all executors converge.
        """
        from sparkrdma_tpu.shuffle import ha

        def apply() -> None:
            res = self.membership.tombstone(manager_id)
            if res is None:
                return  # unknown or already tombstoned: nothing to do
            snapshot, states, epoch, dead_slot = res
            self.publish_membership(snapshot, states, epoch)
            self.on_slot_dead(dead_slot)

        if (self.oplog is not None and not self._replaying
                and not self._in_derived_apply()):
            return self._ha_apply(ha.OP_TOMBSTONE, manager_id.serialize(),
                                  apply)
        return apply()

    def on_slot_dead(self, dead_slot: int) -> None:
        """The location-plane half of losing a slot (failure tombstone
        AND planned retire share it): bump shuffles whose table actually
        NAMES the dead slot — their cached locations could route a fetch
        at a dead executor (the chaos matrix asserts none serves after
        this). Shuffles with no entry on the slot keep their epoch:
        invalidating them too would cold-restart every reducer's cache
        fleet-wide and queue O(shuffles x members) pushes for nothing."""
        with self._tables_lock:
            sids = [sid for sid, table in self._tables.items()
                    if any((e := table.entry(m)) is not None
                           and e[1] == dead_slot
                           for m in range(table.num_maps))]
            # merged segments hosted BY the dead slot are gone with it;
            # entries on survivors stay — they are exactly what recovery
            # re-points to instead of re-executing
            for directory in self._merged.values():
                directory.drop_slot(dead_slot)
        for sid in sids:
            self.bump_epoch(sid, reason="executor lost")
        self._shard_handoff(dead_slot, reason="executor lost")

    def _shard_handoff(self, slot: int, reason: str) -> None:
        """Move every shard hosted by ``slot`` to a new owner,
        generation-forward (model-checked: handoff_vs_publish /
        handoff_vs_driver_failover). The refreshed ShardMapMsg rides the
        announce channel first — the new owner adopts its range — then
        one ShardHandoffMsg per moved shard triggers the standby-buffer
        replay (FIFO per member keeps that order). DERIVED from the
        logged membership op (tombstone/drain), never logged itself: a
        standby replaying those ops re-derives the same reassignment,
        and composed generations (incarnation in the high bits) keep any
        replayed assignment strictly above every pre-failover owner's."""
        if self.conf.metadata_shards <= 0:
            return
        from sparkrdma_tpu.shuffle.ha import compose_epoch, epoch_seq
        from sparkrdma_tpu.shuffle.location_plane import ShardMap
        pushes: List[RpcMsg] = []
        moves = []
        with self._tables_lock:
            for sid, (smap, gen) in list(self._shard_maps.items()):
                if slot not in smap.shard_slots:
                    continue
                table = self._tables.get(sid)
                if table is None:
                    continue
                new_map = ShardMap.assign(table.num_maps, self.membership,
                                          self.conf.metadata_shards,
                                          avoid={slot})
                if new_map is None:
                    # nobody left to host shards: driver-only metadata
                    # (the publish path and cold sync both fall back)
                    self._shard_maps.pop(sid, None)
                    continue
                new_gen = compose_epoch(self.incarnation,
                                        epoch_seq(gen) + 1)
                self._shard_maps[sid] = (new_map, new_gen)
                self.shard_handoffs += 1
                pushes.append(M.ShardMapMsg(sid, new_gen, table.num_maps,
                                            new_map.shard_slots))
                for sh in range(new_map.num_shards):
                    old = (smap.shard_slots[sh]
                           if sh < smap.num_shards else -1)
                    new = new_map.shard_slots[sh]
                    if old != new:
                        pushes.append(M.ShardHandoffMsg(sid, sh, new_gen,
                                                        new, old))
                        moves.append((sid, sh, new, old))
        for m in pushes:
            self._queue_push(None, m)
        for sid, sh, new, old in moves:
            self.tracer.instant("meta.shard_handoff", "meta", shuffle=sid,
                                shard=sh, to_slot=new, from_slot=old,
                                reason=reason)

    # -- elastic membership (parallel/membership.py) ---------------------

    def maps_owned_by(self, shuffle_id: int, slot: int) -> List[int]:
        """Maps whose CURRENT table entry names ``slot`` (the drain
        coordinator's re-point accounting)."""
        with self._tables_lock:
            table = self._tables.get(shuffle_id)
        if table is None:
            return []
        return [m for m in range(table.num_maps)
                if (e := table.entry(m)) is not None and e[1] == slot]

    def unservable_without(self, shuffle_id: int, slot: int) -> List[int]:
        """Maps that could NOT be served if ``slot`` retired right now:
        no live owner elsewhere AND no merged replica the reducers'
        merged-first resolution would select. Empty = retiring the slot
        costs zero re-executions (the drain coordinator's safety
        invariant; covers maps re-pointed to segments the drainee
        HOSTS, not just maps it owns)."""
        with self._tables_lock:
            table = self._tables.get(shuffle_id)
        if table is None:
            return []
        members = self.membership.members()

        def owner_live(s: int) -> bool:
            return (s != slot and 0 <= s < len(members)
                    and members[s] != TOMBSTONE)

        pending = []
        for m in range(table.num_maps):
            e = table.entry(m)
            if e is not None and owner_live(e[1]):
                continue
            pending.append(m)
        if not pending:
            return []
        covered = self.merged_covering(shuffle_id, pending,
                                       exclude_slot=slot)
        pending = [m for m in pending if m not in covered]
        if pending:
            # the cold tier counts toward the safety invariant: a blob
            # has no slot to retire, so tiered coverage survives any
            # drain by construction
            cold = self.tiered_covering(shuffle_id, pending)
            pending = [m for m in pending if m not in cold]
        return pending

    def abort_drain(self, slot: int) -> bool:
        """Return a DRAINING slot to LIVE (the operator changed their
        mind and the drainee is still healthy), broadcasting the state
        change — without the publish, peers would treat the slot as
        draining forever. No-op (False) unless the slot is DRAINING."""
        from sparkrdma_tpu.shuffle.ha import DRAIN_ABORT
        reverted = self.drain_transition(slot, DRAIN_ABORT)
        if reverted is None:
            return False
        self.publish_membership(*reverted)
        log.info("driver: drain of slot %d aborted; slot is LIVE again",
                 slot)
        return True

    def decommission_slot(self, slot: int,
                          deadline_ms: Optional[int] = None) -> dict:
        """Gracefully drain + retire one executor slot (see
        :func:`sparkrdma_tpu.parallel.membership.drain_slot`)."""
        from sparkrdma_tpu.parallel.membership import drain_slot
        return drain_slot(self, slot, deadline_ms=deadline_ms)

    def attach_autoscaler(self, scale_up=None, scale_down=None,
                          load_fn=None):
        """Create (and with ``autoscale_interval_ms`` > 0, start) the
        membership autoscaler. ``scale_up(n)`` is the embedding
        harness's spawn hook; ``scale_down(slot)`` defaults to
        :meth:`decommission_slot`. Returns the
        :class:`~sparkrdma_tpu.parallel.membership.Autoscaler`."""
        from sparkrdma_tpu.parallel.membership import Autoscaler
        if self.autoscaler is None:
            self.autoscaler = Autoscaler(self, self.conf,
                                         scale_up=scale_up,
                                         scale_down=scale_down,
                                         load_fn=load_fn)
            self.autoscaler.start()
        return self.autoscaler

    # -- message handling ------------------------------------------------

    def _handle(self, conn: Connection, msg: RpcMsg) -> Optional[RpcMsg]:
        # wire-shaped mutations are op-logged VERBATIM and re-applied
        # through this same dispatch on replay: the fence floors / epoch
        # guards inside the handlers are the idempotency story, so the
        # log needs no semantic understanding of the frames it carries
        if (self.oplog is not None and not self._replaying
                and isinstance(msg, (HelloMsg, M.JoinMsg, M.PublishMsg,
                                     M.MergedPublishMsg,
                                     M.TieredPublishMsg,
                                     M.ShardBatchMsg))):
            from sparkrdma_tpu.shuffle.ha import OP_WIRE
            return self._ha_apply(OP_WIRE, msg.encode(),
                                  lambda: self._dispatch(conn, msg))
        return self._dispatch(conn, msg)

    def _dispatch(self, conn: Optional[Connection],
                  msg: RpcMsg) -> Optional[RpcMsg]:
        if isinstance(msg, HelloMsg):
            self._on_hello(msg.manager_id)
            return None
        if isinstance(msg, M.JoinMsg):
            self._on_hello(msg.manager_id, explicit_join=True)
            return None
        if isinstance(msg, M.StandbyHelloMsg):
            self._on_standby_hello(msg)
            return None
        if isinstance(msg, M.PublishMsg):
            return self._on_publish(msg)
        if isinstance(msg, M.FetchTableReq):
            return self._on_fetch_table(conn, msg)
        if isinstance(msg, M.FetchPlanReq):
            return self._on_fetch_plan(msg)
        if isinstance(msg, M.MergedPublishMsg):
            self._on_merged_publish(msg)
            return None
        if isinstance(msg, M.ShardBatchMsg):
            self._on_shard_batch(msg)
            return None
        if isinstance(msg, M.FetchMergedReq):
            return self._on_fetch_merged(msg)
        if isinstance(msg, M.TieredPublishMsg):
            self._on_tiered_publish(msg)
            return None
        if isinstance(msg, M.FetchTieredReq):
            return self._on_fetch_tiered(msg)
        if isinstance(msg, M.GetBroadcastReq):
            with self._broadcasts_lock:
                blob = self._broadcasts.get(msg.bcast_id)
            if blob is None:
                return M.GetBroadcastResp(msg.req_id, M.STATUS_ERROR, b"")
            return M.GetBroadcastResp(msg.req_id, M.STATUS_OK, blob)
        if isinstance(msg, M.PingMsg):
            return M.PongMsg(msg.req_id)
        log.warning("driver: unexpected %s", type(msg).__name__)
        return None

    def _on_hello(self, manager_id: ShuffleManagerId,
                  explicit_join: bool = False) -> None:
        """(scala/RdmaShuffleManager.scala:76-115). A JoinMsg routes
        here too (``explicit_join``) — the membership plane treats every
        hello as a join; the explicit frame just names the elastic
        intent for tracing/audit."""
        snapshot, states, epoch, is_new = self.membership.join(manager_id)
        if is_new and (explicit_join or self.membership.joins):
            self.tracer.instant("member.join", "member",
                                slot=len(snapshot) - 1, epoch=epoch,
                                explicit=int(explicit_join))
            log.info("driver: executor %s:%s JOINED as slot %d "
                     "(membership epoch %d)", manager_id.rpc_host,
                     manager_id.rpc_port, len(snapshot) - 1, epoch)
        # Broadcast the full ordered membership to everyone, async — the
        # driver connects out to each executor's control server — plus
        # the slot-state bump and the admission capacity rescale.
        self.publish_membership(snapshot, states, epoch)

    def _queue_announce(self, snapshot: List[ShuffleManagerId],
                        epoch: int) -> None:
        """Hand the broadcaster the newest snapshot; older queued ones are
        superseded (every snapshot is the full membership, so skipping
        intermediates loses nothing — executors order by epoch anyway)."""
        if self._replaying:
            return  # restore is silent; the takeover re-announce speaks
        with self._announce_cond:
            if (self._announce_pending is None
                    or epoch > self._announce_pending[1]):
                self._announce_pending = (snapshot, epoch)
            self._announce_cond.notify()

    def _queue_push(self, target, msg: RpcMsg) -> None:
        """Queue a metadata-plane push for the broadcaster thread:
        ``target=None`` broadcasts to every live member, a
        ShuffleManagerId directs one send (shard-entry forwards), and a
        raw ``(host, port)`` tuple directs one send to a non-member
        address (the standby replication stream). Best-effort by design
        — a lost push is backstopped by the fetch-failure invalidation
        path (or, for standbys, by the re-hello catch-up), so no retry
        ladder hangs off the publish handler. Suppressed during restore
        replay: the takeover re-announce is the authoritative
        broadcast."""
        if self._replaying:
            return
        with self._announce_cond:
            if self._announce_stop:
                return
            self._push_pending.append((target, msg))
            self._announce_cond.notify()

    def _broadcast_loop(self) -> None:
        while True:
            with self._announce_cond:
                while (self._announce_pending is None
                       and not self._push_pending
                       and not self._announce_stop):
                    # 1s deadline: stop() notifies under the lock, but a
                    # lost wake must cost one re-check, not a hung
                    # broadcaster at teardown
                    self._announce_cond.wait(timeout=1.0)
                if self._announce_stop:
                    return
                snapshot_epoch = self._announce_pending
                self._announce_pending = None
                pushes, self._push_pending = self._push_pending, []
            try:
                if snapshot_epoch is not None:
                    self._broadcast(*snapshot_epoch)
            except Exception:  # noqa: BLE001 — a bad snapshot must cost one
                # broadcast, not the whole announce plane (the single
                # long-lived thread would otherwise die silently)
                log.exception("driver: announce broadcast (epoch %d) failed",
                              snapshot_epoch[1])
            for target, msg in pushes:
                try:
                    self._send_push(target, msg)
                except Exception:  # noqa: BLE001 — same survival contract
                    log.exception("driver: metadata push failed")

    def _send_push(self, target, msg: RpcMsg) -> None:
        if isinstance(target, tuple):  # standby replication stream
            try:
                self._clients.get(*target).send(msg)
            except TransportError as e:
                # one attempt, like every push: a dead standby re-syncs
                # through its next StandbyHello catch-up
                log.debug("driver: standby push %s to %s:%s failed: %s",
                          type(msg).__name__, target[0], target[1], e)
            return
        members = self.membership.members()
        targets = ([target] if target is not None
                   else [m for m in members if m != TOMBSTONE])
        for m in targets:
            if self._announce_stop:
                return
            if m == TOMBSTONE:
                continue
            try:
                self._clients.get(m.rpc_host, m.rpc_port).send(msg)
            except TransportError as e:
                # one attempt only: the peer may be mid-death (the very
                # event some pushes announce); its reducers heal via the
                # fetch-failure invalidation backstop
                log.debug("driver: push %s to %s:%s failed: %s",
                          type(msg).__name__, m.rpc_host, m.rpc_port, e)

    def _broadcast(self, members: List[ShuffleManagerId], epoch: int) -> None:
        announce = AnnounceMsg(members, epoch)
        lost: List[ShuffleManagerId] = []
        for m in members:
            if m == TOMBSTONE:
                continue
            if self._announce_stop:
                # stop() raced us: bail before minting fresh connections the
                # just-run close_all() would never see
                return
            # Two attempts: a failed send on a stale cached connection is
            # not evidence of peer death — retry on a fresh connection and
            # only declare the peer lost if that also fails (a transient
            # blip must not permanently tombstone a live executor).
            delivered = False
            for attempt in range(2):
                conn = None
                try:
                    conn = self._clients.get(m.rpc_host, m.rpc_port)
                    conn.send(announce)
                    delivered = True
                    break
                except TransportError as e:
                    log.warning("driver: announce to %s:%s failed "
                                "(attempt %d): %s", m.rpc_host, m.rpc_port,
                                attempt + 1, e)
                    if conn is not None:
                        conn.close()  # drop the stale connection
            if not delivered:
                lost.append(m)
        # Failure detection: an unreachable executor is treated as lost and
        # tombstoned so fetchers fail fast (the reference reacts to
        # SparkListenerBlockManagerRemoved the same way,
        # scala/RdmaShuffleManager.scala:155-165). remove_member no-ops on
        # already-tombstoned slots, so this converges.
        for m in lost:
            log.warning("driver: marking unreachable executor %s:%s as lost",
                        m.rpc_host, m.rpc_port)
            self.remove_member(m)

    def _on_publish(self, msg: M.PublishMsg,
                    forward_shard: bool = True) -> Optional[RpcMsg]:
        # Publish is one-sided in the reference (RDMA WRITE into the table,
        # scala/RdmaShuffleManager.scala:410-412) — no remote reply; problems
        # are only observable driver-side, so log rather than ack.
        from sparkrdma_tpu.shuffle.map_output import _MAP_ENTRY, MAP_ENTRY_SIZE
        with self._tables_lock:
            table = self._tables.get(msg.shuffle_id)
            self._touch_locked(msg.shuffle_id)
        if table is None:
            log.warning("driver: publish for unknown shuffle %d", msg.shuffle_id)
            return None
        if not 0 <= msg.map_id < table.num_maps:
            log.warning("driver: publish with bad map_id %d for shuffle %d",
                        msg.map_id, msg.shuffle_id)
            return None
        if len(msg.entry) != MAP_ENTRY_SIZE:
            log.warning("driver: bad publish entry size %d for shuffle %d "
                        "map %d", len(msg.entry), msg.shuffle_id, msg.map_id)
            return None
        token, exec_index = _MAP_ENTRY.unpack(msg.entry)
        old = table.entry(msg.map_id)
        try:
            accepted = table.publish(msg.map_id, token, exec_index,
                                     fence=msg.fence)
        except (ValueError, IndexError) as e:
            log.warning("driver: bad publish for shuffle %d map %d: %s",
                        msg.shuffle_id, msg.map_id, e)
            return None
        if not accepted:
            # a zombie speculative attempt's late publish: the committed
            # winner's location stays the one served
            self.fenced_publishes += 1
            log.warning("driver: FENCED stale publish for shuffle %d map "
                        "%d (exec %d fence %d)", msg.shuffle_id, msg.map_id,
                        exec_index, msg.fence)
            return None
        # adaptive planning: an APPLIED publish carries its per-partition
        # sizes into the histogram — positionally, so a repair publish
        # overwrites the dead attempt's row exactly like the table entry
        if msg.lengths is not None:
            with self._tables_lock:
                hist = self._size_hists.get(msg.shuffle_id)
            if hist is not None:
                hist.add(msg.map_id, msg.lengths)
        # epoch semantics: a publish that OVERWROTE a live entry is a
        # REPAIR (re-execution after loss or corrupt output, elastic
        # rejoin under new tokens) — bump + push so epoch-validated
        # caches refresh. First-time publishes and identical republishes
        # move no state reducers could have cached against.
        epoch = self.epoch_of(msg.shuffle_id) or 1
        if old is not None and old != (token, exec_index):
            # merged segments holding the REPLACED attempt's bytes are
            # conservative casualties: a corrupt-output repair may have
            # rewritten content, so the directory drops every entry
            # covering this map BEFORE the bump pushes the invalidation
            with self._tables_lock:
                directory = self._merged.get(msg.shuffle_id)
                if directory is not None and directory.drop_map(msg.map_id):
                    log.info("driver: merged entries covering shuffle %d "
                             "map %d dropped (repair publish)",
                             msg.shuffle_id, msg.map_id)
                # cold blobs carrying the replaced attempt's bytes are
                # the same conservative casualty: a blob uploaded (or
                # still uploading) from the superseded segment must
                # never resolve — its entry dies here and a LATE
                # publish of it lands against this pruned state, where
                # the reducer's resolve-order already prefers the
                # repaired hot copy (modelcheck tier_vs_replan)
                tiered = self._tiered.get(msg.shuffle_id)
                if tiered is not None and tiered.drop_map(msg.map_id):
                    log.info("driver: tiered entries covering shuffle %d "
                             "map %d dropped (repair publish)",
                             msg.shuffle_id, msg.map_id)
                # and close the mid-upload window: a tiered publish of
                # this map arriving AFTER this prune is stale by
                # construction (its upload read the replaced bytes)
                self._tiered_superseded.add((msg.shuffle_id, msg.map_id))
            epoch = self.bump_epoch(msg.shuffle_id,
                                    reason="repair publish") or epoch
        # push-merge: the LAST publish completes the map stage — tell
        # merge targets to quiesce, seal, and publish their segments
        if (self.conf.push_merge
                and table.num_published == table.num_maps):
            self.finalize_merge(msg.shuffle_id)
        # sharded driver state: the fence CAS above is the driver's
        # authority — only surviving publishes are forwarded into the
        # owning shard host's replica (one directed positional write,
        # the reference's table WRITE re-aimed at a shard host).
        # ``forward_shard=False`` on the batch-convergence path: the
        # record came FROM the owner, whose replica already holds it.
        with self._tables_lock:
            shard_map_v = self._shard_maps.get(msg.shuffle_id)
        if shard_map_v is not None and forward_shard:
            shard_map = shard_map_v[0]
            members = self.membership.members()
            slot = shard_map.slot_of_map(msg.map_id)
            if slot < len(members) and members[slot] != TOMBSTONE:
                self._queue_push(members[slot], M.ShardEntryMsg(
                    msg.shuffle_id, epoch, msg.map_id, table.num_maps,
                    msg.entry))
        # push: answer any long-poller this publish satisfies (the write
        # above happens-before the waiter scan; _on_fetch_table re-checks
        # the count inside the same lock, so no wakeup can be lost)
        ready = []
        with self._waiters_lock:
            pending = self._waiters.get(msg.shuffle_id)
            if pending:
                n = table.num_published
                still = [w for w in pending if w[2] > n]
                ready = [w for w in pending if w[2] <= n]
                if still:
                    self._waiters[msg.shuffle_id] = still
                else:
                    self._waiters.pop(msg.shuffle_id, None)
        if ready:
            count, table_bytes = table.num_published, table.to_bytes()
            for conn, req_id, _, _ in ready:
                self._answer_waiter(conn, M.FetchTableResp(
                    req_id, count, table_bytes, epoch))
        return None

    def _on_shard_batch(self, msg: "M.ShardBatchMsg") -> None:
        """Batch convergence from a shard OWNER (shard_ownership mode):
        replay each owner-applied write through the normal publish /
        merged-publish path. The fence CAS and the directory's zombie
        guard make the echo idempotent, which is exactly what keeps the
        driver-visible table byte-identical to the unsharded path —
        the owner accelerated the write, it never forked the state."""
        self.shard_batches += 1
        for map_id, fence, entry, lengths in msg.records:
            self._on_publish(
                M.PublishMsg(msg.shuffle_id, map_id, entry, fence=fence,
                             lengths=lengths),
                forward_shard=False)
        for blob in msg.blobs:
            try:
                inner = M.MergedPublishMsg.from_payload(blob)
            except (struct.error, ValueError, IndexError) as e:
                log.warning("driver: undecodable merged blob in shard "
                            "batch for shuffle %d: %s", msg.shuffle_id, e)
                continue
            self._on_merged_publish(inner)

    def _on_fetch_table(self, conn: Connection,
                        msg: M.FetchTableReq) -> Optional[RpcMsg]:
        with self._tables_lock:
            table = self._tables.get(msg.shuffle_id)
            epoch = self._epochs.get(msg.shuffle_id, 0)
            self._touch_locked(msg.shuffle_id)
        if table is None:
            return M.FetchTableResp(msg.req_id, -1, b"", M.EPOCH_DEAD)
        with self._waiters_lock:
            n = table.num_published
            if n >= msg.min_published or msg.timeout_ms <= 0:
                return M.FetchTableResp(msg.req_id, n, table.to_bytes(),
                                        epoch)
            deadline = time.monotonic() + msg.timeout_ms / 1000
            waiter = (conn, msg.req_id, msg.min_published, deadline)
            self._waiters.setdefault(msg.shuffle_id, []).append(waiter)
        # unregister-race re-check: unregister_shuffle pops the table
        # (tables lock) and THEN wakes waiters (waiters lock) — a poll
        # that read the table before the pop but registered after the
        # wake would sit out its whole deadline for a shuffle that is
        # already gone. Re-reading the registry after registration
        # closes the window: whoever pops the waiter (us here, or the
        # unregister that raced in between) answers it, exactly once.
        with self._tables_lock:
            gone = msg.shuffle_id not in self._tables
        if gone:
            with self._waiters_lock:
                pending = self._waiters.get(msg.shuffle_id, [])
                mine = waiter in pending
                if mine:
                    pending.remove(waiter)
                    if not pending:
                        self._waiters.pop(msg.shuffle_id, None)
            if mine:
                return M.FetchTableResp(msg.req_id, -1, b"", M.EPOCH_DEAD)
        return None  # answered later by a publish or the sweeper

    def _answer_waiter(self, conn: Connection, resp: RpcMsg) -> None:
        try:
            conn.send(resp)
        except TransportError as e:
            log.warning("driver: long-poll answer failed: %s", e)

    def _sweep_waiters(self) -> None:
        """Expire long-polls at their deadline with the partial table."""
        while not self._announce_stop:
            time.sleep(0.05)
            now = time.monotonic()
            expired = []  # [(sid, table, [waiter, ...])]
            with self._waiters_lock:
                for sid, pending in list(self._waiters.items()):
                    live = [w for w in pending if w[3] > now]
                    dead = [w for w in pending if w[3] <= now]
                    if dead:
                        with self._tables_lock:
                            table = self._tables.get(sid)
                            epoch = self._epochs.get(sid, M.EPOCH_DEAD)
                        expired.append((table, epoch, dead))
                        if live:
                            self._waiters[sid] = live
                        else:
                            self._waiters.pop(sid, None)
            for table, epoch, dead in expired:
                if table is None:
                    count, table_bytes = -1, b""
                else:
                    count, table_bytes = table.num_published, table.to_bytes()
                for conn, req_id, _, _ in dead:
                    self._answer_waiter(conn, M.FetchTableResp(
                        req_id, count, table_bytes, epoch))

    def stop(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        # the lease loop keys off _lease_lost too: setting it here lets
        # a clean stop release the renew thread within one period
        self._lease_lost.set()
        with self._announce_cond:
            self._announce_stop = True
            self._announce_cond.notify()
        self._broadcaster.join(timeout=self.conf.teardown_timeout_ms / 1000)
        if self._lease_thread is not None:
            self._lease_thread.join(
                timeout=self.conf.teardown_timeout_ms / 1000)
        self._clients.close_all()
        self.server.stop()


class ByteCredits:
    """Per-connection serving window: logical response bytes the server
    may hold built-and-undelivered (the receiver-driven flow control of
    java/RdmaChannel.java:61-64, 744-787 — credits granted by the recv
    window, replenished by the reader's CreditReport on receipt).

    Parking is QUEUED, not blocking: a request that doesn't fit enqueues
    a resume callback and frees its serving thread, so one stalled
    connection can never head-of-line-block the shared serving pool.
    ``release`` re-admits parked requests FIFO with their reservation
    already taken. A single request larger than the whole window is
    charged the full window, so one oversized block can never deadlock.
    """

    def __init__(self, budget: int):
        self.budget = budget
        self._avail = budget
        self._lock = threading.Lock()
        self._parked_q: list = []  # [(need, deadline, resume, expire)]
        self.peak_reserved = 0  # audit: worst-case held bytes
        self.parked = 0         # audit: requests that had to wait

    def reserve_or_park(self, nbytes: int, deadline: float,
                        resume, expire) -> bool:
        """Atomically reserve (returns True) or enqueue the continuation
        (returns False). The availability check and the park happen under
        ONE lock acquisition — with a separate check-then-park, a
        ``release`` landing in the gap could drain the window and never
        wake the request (lost wakeup: the request, and behind the FIFO
        gate every later one, would sit parked against a fully-available
        window until the sweeper failed them). ``resume()`` fires (off
        this thread) once the reservation has been taken on the request's
        behalf; ``expire()`` fires if the deadline passes first (swept by
        the endpoint)."""
        need = min(nbytes, self.budget)
        with self._lock:
            # FIFO fairness: never jump a parked queue
            if not self._parked_q and self._avail >= need:
                self._avail -= need
                self.peak_reserved = max(self.peak_reserved,
                                         self.budget - self._avail)
                return True
            self._parked_q.append((need, deadline, resume, expire))
            self.parked += 1
        return False

    def release(self, nbytes: int) -> None:
        resumes = []
        with self._lock:
            self._avail = min(self.budget,
                              self._avail + min(nbytes, self.budget))
            while self._parked_q and self._avail >= self._parked_q[0][0]:
                need, _, resume, _ = self._parked_q.pop(0)
                self._avail -= need
                self.peak_reserved = max(self.peak_reserved,
                                         self.budget - self._avail)
                resumes.append(resume)
        for resume in resumes:
            resume()

    def expire_stale(self, now: float) -> list:
        """Pop parked entries past their deadline; returns their expire
        callbacks for the caller to run."""
        expired = []
        with self._lock:
            keep = []
            for item in self._parked_q:
                (expired if item[1] <= now else keep).append(item)
            self._parked_q = keep
        return [item[3] for item in expired]


class ExecutorEndpoint:
    """Control-plane executor: serves peers, talks to the driver."""

    def __init__(self, manager_id_host: str, executor: str,
                 driver_addr: Tuple[str, int],
                 data_source: Optional[ShuffleDataSource] = None,
                 conf: Optional[TpuShuffleConf] = None,
                 engine_port: int = 0, block_port: int = 0,
                 tracer=None):
        self.conf = conf or TpuShuffleConf()
        self.data_source = data_source
        self.tracer = tracer or trace_mod.NULL
        self.server = ControlServer(manager_id_host, self.conf.executor_port,
                                    self.conf, self._handle,
                                    name=f"exec-{executor}")
        self.manager_id = ShuffleManagerId(
            _ExecutorId(executor, manager_id_host, engine_port),
            self.server.host, self.server.port, block_port)
        self._driver_addr = driver_addr
        self._members: List[ShuffleManagerId] = []
        self._announce_epoch = -1
        self._members_event = threading.Event()
        self._members_lock = threading.Lock()
        self._clients = ConnectionCache(self.conf, on_message=self._handle)
        # the ONE driver channel (parallel/driver_client.py): every
        # driver-bound call site routes through it so a failover
        # re-points them all at once; a TakeoverMsg moves the pointer
        # forward-only under the incarnation comparison
        self.driver = DriverClient(self.conf, self._clients, driver_addr)
        # metadata plane (shuffle/location_plane.py): the epoch-validated
        # local cache of driver tables + block-location entries (the
        # warm-path zero-RPC store), and this executor's driver-table
        # shard replicas (fed by the driver's ShardEntryMsg forwards,
        # served to peers' FetchShardReq long-polls)
        from sparkrdma_tpu.shuffle.location_plane import (
            LocationPlane, ShardStore)
        self.location_plane = LocationPlane(
            enabled=bool(self.conf.location_epoch_cache))
        self.shard_store = ShardStore()
        self._shard_waiters: Dict[int, list] = {}
        self._shard_waiters_lock = threading.Lock()
        # partitioned metadata OWNERSHIP (shuffle/shard_plane.py): with
        # shard_ownership on, this executor may OWN map-ranges — run
        # their fence CAS, stream their per-shard op log to a standby,
        # and batch-converge applied writes into the driver table.
        # shard_owner doubles as the mode flag (None = replica mode).
        self.shard_owner = None
        self.shard_standby = None
        # pending owner->driver batches: (sid, shard) -> (gen, records,
        # merged blobs); flushed at shard_batch_entries or by the
        # flusher thread (bounded convergence lag)
        self._shard_batches: Dict[Tuple[int, int], tuple] = {}
        self._shard_batch_lock = threading.Lock()
        self._shard_flusher: Optional[threading.Thread] = None
        self._shard_flush_wake = threading.Event()
        # publisher-side republish backstop: direct-to-owner publishes
        # are remembered until the shuffle dies so a handoff can re-aim
        # them (fence floors make re-sends idempotent). This is what
        # turns "owner killed mid-publish" into a metadata re-send
        # instead of a map re-execution.
        self._republish: Dict[int, Dict[int, tuple]] = {}
        self._republish_lock = threading.Lock()
        if self.conf.shard_ownership and self.conf.metadata_shards > 0:
            from sparkrdma_tpu.shuffle.shard_plane import (
                ShardOwnerStore, ShardStandbyBuffer)
            self.shard_owner = ShardOwnerStore()
            self.shard_standby = ShardStandbyBuffer()
        # invalidation generation: a long-poll answered with a
        # PRE-invalidation table must not re-memoize after the
        # invalidation (stage recovery repaired the driver table; a stale
        # re-cache would pin dead-slot locations for every later reader).
        # One endpoint-wide counter: an invalidation of ANY shuffle skips
        # memoizing concurrently-in-flight polls — at worst one extra
        # table fetch later, and O(1) state instead of a per-shuffle-id
        # dict that grows forever
        self._table_gen = 0
        self._table_lock = threading.Lock()
        self.wire_bytes_in = 0  # compressed-on-the-wire fetch payload total
        self._wire_lock = threading.Lock()
        # wire codec (encryption/integrity hook, utils/codecs.py — the
        # scala/RdmaShuffleReader.scala:118-128 wrapStream analogue)
        from sparkrdma_tpu.utils import codecs as _codecs
        self._codec, self._codec_key = _codecs.resolve(self.conf)
        # task shipping (engine tasks run here when a runner is installed;
        # see sparkrdma_tpu/tasks.py)
        self._task_runner = None
        self._task_pool = None
        # push-merge (shuffle/push_merge.py): the manager installs a
        # MergeStore here when push_merge is on; pushes/finalizes run on
        # the serve pool (disk appends must never block a reader thread)
        self.merge_store = None
        # planned push (shuffle/pushed_store.py): the manager installs a
        # PushedInputStore here when planned_push is on; the fetcher
        # resolves it FIRST, before merged segments and per-map pull
        self.pushed_store = None
        # cold tier (shuffle/cold_tier.py): the manager installs a
        # TieringService here when cold_tier is on; finalized segments
        # tier asynchronously and the fetcher resolves the TIERED
        # location class LAST, before re-execution
        self.tiering = None
        # the planned pusher's plan hook (SegmentPusher.on_plan): called
        # when a ReducePlanMsg lands so submitted maps whose plan
        # arrived late (or re-planned) re-push to their planned slots
        self.on_plan_cb = None
        # tenancy (shuffle/tenancy.py): shuffle -> owning tenant, taught
        # by the driver's TenantMapMsg push and locally by the manager's
        # handle path; keys the serve loop's fair-share queue. The DRR
        # queue itself is created lazily with the serve pool.
        self._tenant_lock = threading.Lock()
        self._tenant_map: Dict[int, int] = {}
        self._serve_drr = None
        self.fair_served: Dict[int, int] = {}  # tenant -> serves (audit)
        # receiver-driven serving flow control: per-connection byte
        # windows + a serving pool so data responses build/park OFF the
        # reader thread (a parked reader could never receive the very
        # CreditReport that would unpark it)
        import weakref

        self._serve_pool = None
        self._serve_pool_lock = threading.Lock()
        self._park_sweeper = None
        self._conn_credits = weakref.WeakKeyDictionary()
        self._credits_lock = threading.Lock()
        self._credit_timeouts = 0
        # client side: logical sizes of in-flight credited fetches, keyed
        # by connection -> {req_id: size} — consulted when a response
        # arrives ORPHANED (its requester timed out) so its credits still
        # get reported and the server's window heals. Weak keys: entries
        # whose response never arrives (conn died post-timeout) die with
        # the connection instead of accumulating forever, and a recycled
        # id() can never alias a new connection's req_ids.
        self._fetch_credit_pending: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._fetch_credit_lock = threading.Lock()
        # connection pre-warming (reference pre-connects requestor
        # channels the moment a peer announces,
        # RdmaShuffleManager.scala:117-126): addresses this endpoint has
        # already dialed (or is dialing) ahead of any fetch
        self._prewarmed: set = set()
        self._prewarm_lock = threading.Lock()
        self._stopping = False
        # CreditReport sends ride a dedicated worker (started on first
        # use): the receipt-time settle runs on connection READER
        # threads, and a blocking sendall there — both TCP directions
        # full under sustained load — would stop the reader from
        # draining responses, stalling every in-flight fetch until
        # timeout instead of making progress
        self._credit_q: "queue.Queue" = queue.Queue()
        self._credit_worker: Optional[threading.Thread] = None
        self._credit_worker_lock = threading.Lock()
        self.prewarm_dials = 0  # audit: successful ahead-of-fetch dials
        # peer-health monitor: heartbeats go only to peers with fetch
        # interest registered (watch_peer), so an idle cluster sends no
        # health traffic; the thread starts lazily on first watch
        self._hb_lock = threading.Lock()
        self._hb_watch: Dict[int, Tuple[ShuffleManagerId, int]] = {}
        self._hb_misses: Dict[int, int] = {}
        self._hb_suspects: set = set()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_wake = threading.Event()
        # mid-job joiners announced by a MembershipBumpMsg before their
        # AnnounceMsg landed: slots to register with the monitor once
        # the member list can resolve them (guarded by _hb_lock)
        self._joiner_watch_pending: set = set()
        self.suspect_events = 0    # audit: peers declared suspect
        self.checksum_failures = 0  # audit: CRC32 mismatches on fetches

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Hello to the driver (scala/RdmaShuffleManager.scala:204-226).
        Routed through the retry envelope: a hello racing a driver
        failover re-dials the re-pointed primary, and fencing makes a
        duplicate hello (one per primary that saw it) idempotent."""
        self.driver.send(HelloMsg(self.manager_id))

    def join_cluster(self) -> None:
        """Explicit mid-job JOIN (parallel/membership.py): same
        membership append as the hello, but the driver traces the
        elastic event. An old driver without the frame would tear the
        connection — the hello already sent is the compatible greeting,
        so a lost/ignored join degrades to static-membership behavior."""
        self.driver.send(M.JoinMsg(self.manager_id))

    def driver_conn(self) -> Connection:
        return self.driver.conn()

    def stop(self) -> None:
        # flagged BEFORE close_all so a racing prewarm dial either sees
        # it (and closes its own connection) or inserts into the cache
        # before close_all drains it — no window where a fresh dial can
        # outlive this teardown
        # analysis: unguarded-ok(set-once monotonic flag; ordering vs close_all documented above)
        self._stopping = True
        self._hb_wake.set()  # ends the heartbeat monitor, if started
        self._shard_flush_wake.set()  # ends the shard-batch flusher
        if self._task_pool is not None:
            self._task_pool.shutdown(wait=False, cancel_futures=True)
        if self._serve_pool is not None:
            self._serve_pool.shutdown(wait=False, cancel_futures=True)
        self._clients.close_all()
        self.server.stop()
        self._credit_q.put(None)  # ends the credit worker, if started

    # -- membership ------------------------------------------------------

    def members(self) -> List[ShuffleManagerId]:
        with self._members_lock:
            return list(self._members)

    def wait_for_members(self, n: int, timeout: float = 10.0) -> List[ShuffleManagerId]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._members_lock:
                if len(self._members) >= n:
                    return list(self._members)
            self._members_event.wait(timeout=0.05)
            self._members_event.clear()
        raise TimeoutError(f"membership did not reach {n} "
                           f"(have {len(self.members())})")

    def exec_index(self, timeout: float = 0.0) -> int:
        """This executor's stable index in the membership order. With a
        timeout, waits for the driver's announce to arrive (publishers may
        race the hello/announce round trip)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._members_lock:
                for i, m in enumerate(self._members):
                    if m == self.manager_id:
                        return i
            if time.monotonic() >= deadline:
                raise KeyError("executor not yet announced")
            self._members_event.wait(timeout=0.05)
            self._members_event.clear()

    def member_at(self, index: int) -> ShuffleManagerId:
        with self._members_lock:
            m = self._members[index]
        if m == TOMBSTONE:
            raise DeadExecutorError(f"executor slot {index} was lost")
        return m

    # -- peer health (heartbeat monitor) ---------------------------------

    def note_tenant(self, shuffle_id: int, tenant: int) -> None:
        """Record the shuffle's owning tenant (push or handle path)."""
        with self._tenant_lock:
            self._tenant_map[shuffle_id] = int(tenant)

    def tenant_of(self, shuffle_id: int) -> int:
        """The shuffle's owning tenant; DEFAULT_TENANT when untaught
        (lost push => degraded fairness, never a correctness issue)."""
        with self._tenant_lock:
            return self._tenant_map.get(shuffle_id, 0)

    def watch_peer(self, exec_index: int, peer: ShuffleManagerId) -> None:
        """Register fetch interest in a peer: the monitor pings watched
        peers every ``heartbeat_interval_ms`` and declares one suspect
        after ``heartbeat_misses`` consecutive missed beats — failing its
        outstanding fetches promptly instead of letting them wait out a
        TCP timeout. Refcounted; pair with :meth:`unwatch_peer`."""
        if self.conf.heartbeat_interval_ms <= 0 or self._stopping:
            return
        with self._hb_lock:
            _, count = self._hb_watch.get(exec_index, (peer, 0))
            self._hb_watch[exec_index] = (peer, count + 1)
            if self._hb_thread is None:
                self._hb_thread = threading.Thread(
                    target=self._hb_loop, daemon=True,
                    name=f"hb-{self.manager_id.executor_id.executor}")
                self._hb_thread.start()

    def unwatch_peer(self, exec_index: int) -> None:
        with self._hb_lock:
            entry = self._hb_watch.get(exec_index)
            if entry is None:
                return
            peer, count = entry
            if count <= 1:
                self._hb_watch.pop(exec_index, None)
                self._hb_misses.pop(exec_index, None)
            else:
                self._hb_watch[exec_index] = (peer, count - 1)

    def peer_suspect(self, exec_index: int) -> bool:
        """True once the monitor has declared this slot dead: fetchers
        fail fast into FetchFailed (stage retry) instead of retrying."""
        with self._hb_lock:
            return exec_index in self._hb_suspects

    def declare_suspect(self, exec_index: int, peer: ShuffleManagerId,
                        reason: str) -> None:
        """The monitor's verdict (also callable by tests/engines that
        learned of a death out of band): mark the slot, then close the
        cached connections to the peer so every outstanding request on
        them fails NOW — ``_fail_pending`` turns a silent peer death into
        immediate TransportErrors for the whole in-flight window."""
        with self._hb_lock:
            if exec_index in self._hb_suspects:
                return
            self._hb_suspects.add(exec_index)
            self.suspect_events += 1
        log.warning("%s: peer slot %d (%s:%s) declared suspect: %s",
                    self.manager_id.executor_id.executor, exec_index,
                    peer.rpc_host, peer.rpc_port, reason)
        self.tracer.instant("peer.suspect", "fault", peer=exec_index,
                            reason=reason)
        self.tracer.counter("peer.suspects", self.suspect_events, "fault")
        self._clients.drop(peer.rpc_host, peer.rpc_port)
        if peer.block_port:
            self._clients.drop(peer.rpc_host, peer.block_port)

    def health_snapshot(self) -> dict:
        with self._hb_lock:
            return {
                "watched": {i: n for i, (_, n) in self._hb_watch.items()},
                "misses": dict(self._hb_misses),
                "suspects": sorted(self._hb_suspects),
                "suspect_events": self.suspect_events,
            }

    def _hb_loop(self) -> None:
        interval = self.conf.heartbeat_interval_ms / 1000
        while not self._stopping and not self.server.stopped:
            if self._hb_wake.wait(interval):
                return  # stop() woke us
            with self._hb_lock:
                targets = [(i, peer) for i, (peer, _) in
                           self._hb_watch.items()
                           if i not in self._hb_suspects]
            pings = []
            for i, peer in targets:
                if self._stopping:
                    return
                # peek, never dial: the monitor exists for peers the
                # fetch path is ALREADY talking to over a looks-alive
                # connection. Dialing here would stall the whole beat on
                # one unreachable peer's connect budget (and could mint a
                # fresh connection after stop()'s close_all); a missing
                # connection means the fetch path is dialing itself and
                # its own failure handling owns reachability.
                conn = self._clients.peek(peer.rpc_host, peer.rpc_port)
                if conn is None:
                    continue
                try:
                    pings.append((i, peer, conn.request_async(
                        M.PingMsg(conn.next_req_id()))))
                except TransportError:
                    self._hb_miss(i, peer, "send failed")
            # collect pongs within one interval so a silent peer costs
            # exactly one beat, not a stacked-timeout multiple of it
            deadline = time.monotonic() + interval
            for i, peer, fut in pings:
                try:
                    resp = await_response(
                        fut, max(0.001, deadline - time.monotonic()))
                    if not isinstance(resp, M.PongMsg):
                        # wrong echo counts as a miss, never kills the
                        # monitor thread
                        raise TransportError(
                            f"bad pong: {type(resp).__name__}")
                    with self._hb_lock:
                        self._hb_misses.pop(i, None)
                except (TimeoutError, TransportError):
                    # await_response cancelled the future on timeout, so
                    # a late pong lands on the unsolicited path harmlessly
                    self._hb_miss(i, peer, "missed beat")

    def _hb_miss(self, exec_index: int, peer: ShuffleManagerId,
                 kind: str) -> None:
        with self._hb_lock:
            n = self._hb_misses.get(exec_index, 0) + 1
            self._hb_misses[exec_index] = n
        if n >= self.conf.heartbeat_misses:
            self.declare_suspect(
                exec_index, peer,
                f"{n} consecutive missed heartbeats ({kind})")

    # -- elastic membership (parallel/membership.py) ---------------------

    def slot_draining(self, slot: int) -> bool:
        """True when the driver's pushed state vector marks the slot
        DRAINING: stop choosing it as a merge/overflow target. Unknown
        slots read LIVE (pre-elastic drivers never push states)."""
        return self.location_plane.slot_draining(slot)

    def _on_membership_bump(self, msg: "M.MembershipBumpMsg") -> None:
        """A pushed membership change: cache the slot-state vector
        (epoch-ordered) and register newly-LIVE joiners with the
        peer-health monitor — a mid-job joiner was otherwise never
        health-watched until some fetch took interest, so its loss was
        detected only by a failed fetch. The watch costs nothing until
        a connection to the joiner exists (the monitor peeks, never
        dials)."""
        joined = self.location_plane.note_membership(msg.epoch,
                                                     msg.slot_states)
        if not joined or self.conf.heartbeat_interval_ms <= 0:
            return
        with self._hb_lock:
            self._joiner_watch_pending.update(joined)
        self._watch_pending_joiners()

    def _watch_pending_joiners(self) -> None:
        """Resolve stashed joiner slots against the (possibly
        just-updated) member list and register them with the monitor.
        A bump can beat its announce — unresolvable slots stay stashed
        and the announce handler retries."""
        if self.conf.heartbeat_interval_ms <= 0 or self._stopping:
            return
        with self._hb_lock:
            pending = set(self._joiner_watch_pending)
        if not pending:
            return
        with self._members_lock:
            members = list(self._members)
        for slot in sorted(pending):
            if slot >= len(members):
                continue  # announce not here yet; retried on arrival
            peer = members[slot]
            if peer == TOMBSTONE or peer == self.manager_id:
                with self._hb_lock:
                    self._joiner_watch_pending.discard(slot)
                continue
            with self._hb_lock:
                self._joiner_watch_pending.discard(slot)
            # monitor-owned watch (never unwatched: the refcount is held
            # for the joiner's lifetime on this endpoint — suspects and
            # teardown end it, exactly like a long-lived fetch interest)
            self.watch_peer(slot, peer)

    def _on_drain(self, conn: Connection, msg: "M.DrainReq") -> None:
        """The drainee half of the graceful-drain protocol: make every
        row this executor is the last holder of — its own committed map
        outputs AND the merged-segment rows it hosts for other
        executors' maps — land on a surviving peer, then answer with
        the audit counts. Serving continues throughout — in-flight
        reads quiesce naturally; the driver only retires the slot after
        its coverage check passes."""
        deadline_ms = msg.deadline_ms or self.conf.drain_deadline_ms
        deadline = time.monotonic() + max(0.05, deadline_ms / 1000)
        status = M.STATUS_OK
        rows_pushed = 0
        bytes_pushed = 0
        try:
            status, rows_pushed, bytes_pushed = \
                self._drain_replicate(deadline)
        except Exception:  # noqa: BLE001 — dedicated thread; a broken
            # drain must still answer so the driver's deadline isn't
            # burned waiting on silence
            log.exception("drain replication pass failed")
            status = M.STATUS_ERROR
        log.info("%s: drain pass done (status %d, %d row(s) pushed, "
                 "%d byte(s))", self.manager_id.executor_id.executor,
                 status, rows_pushed, bytes_pushed)
        try:
            conn.send(M.DrainResp(msg.req_id, status, rows_pushed,
                                  bytes_pushed))
        except TransportError as e:
            log.warning("drain response lost (driver gone?): %s", e)

    def _drain_directory(self, shuffle_id: int, deadline: float,
                         expect_entries: bool):
        """The shuffle's merged directory for drain routing, waiting
        briefly (bounded by ``deadline``) for the map-stage finalize to
        land when this executor holds committed outputs but the
        directory is still empty — a drain racing the ordinary finalize
        would otherwise route rows blind and scatter coverage."""
        wait_until = min(deadline, time.monotonic() + 2.0)
        while True:
            directory = self.get_merged_directory(shuffle_id, fresh=True)
            if directory is not None and (len(directory)
                                          or not expect_entries):
                return directory
            if time.monotonic() >= wait_until:
                return directory
            time.sleep(0.05)

    def _drain_replicate(self, deadline: float) -> Tuple[int, int, int]:
        """Replicate everything only this executor holds, routing each
        (map, partition) row to the slot already holding that
        partition's WIDEST live merged entry. The routing is the load-
        bearing part: reducers (and recovery's ``merged_covering``)
        consume at most ONE merged entry per partition — the widest —
        so scattering drain rows across slots would build wide-but-
        incomplete entries that SHADOW the rows' actual coverage.
        Merging into the already-widest entry keeps one strictly
        growing segment per partition. Rows the widest surviving entry
        already covers are skipped outright, so a fleet whose
        background replication kept up pushes ZERO bytes here.

        Returns ``(status, rows_pushed, bytes_pushed)``."""
        src = self.data_source
        if (not self.conf.push_merge or src is None
                or not hasattr(src, "committed_outputs")):
            # nothing to replicate WITH: the driver's coverage check
            # decides (it will fall back to tombstone recovery)
            return M.STATUS_ERROR, 0, 0
        try:
            my = self.exec_index(timeout=1)
        except KeyError:
            my = -1
        with self._members_lock:
            members = list(self._members)
        # consult BOTH membership views: the announce list (tombstones)
        # and the pushed state vector (draining/dead) — back-to-back
        # drains race their retire announces, and whichever signal
        # arrives first must keep the just-retired slot out of the
        # routing pool
        _, states = self.location_plane.membership()
        candidates = [i for i, m in enumerate(members)
                      if m != TOMBSTONE and i != my
                      and not (i < len(states) and states[i] != 0)]
        if not candidates and self.tiering is None:
            # no live peers and no cold store: nowhere to put the rows.
            # With tiering installed the drain proceeds peer-less — the
            # scale-to-zero exit — and per-row fallback arbitrates.
            return M.STATUS_ERROR, 0, 0
        cand_set = set(candidates)
        directories: Dict[int, object] = {}

        def preferred(sid: int, partition: int):
            """(entry, slot): the widest surviving entry for the
            partition and its slot, or (None, deterministic fallback)."""
            directory = directories.get(sid)
            if directory is not None:
                for e in directory.entries(partition):
                    if e.slot in cand_set:
                        return e, e.slot
            if not candidates:
                return None, -1  # peer-less drain: tiering carries it
            return None, candidates[partition % len(candidates)]

        status = M.STATUS_OK
        rows_pushed = 0
        bytes_pushed = 0

        def push_row(sid: int, partition: int, map_id: int, fence: int,
                     data: bytes) -> bool:
            nonlocal rows_pushed, bytes_pushed, status
            for _attempt in range(3):
                if not candidates:
                    status = M.STATUS_ERROR
                    return False
                _, slot = preferred(sid, partition)
                try:
                    peer = self.member_at(slot)
                    resp = self.push_blocks(peer, sid, map_id, fence,
                                            M.PUSH_KIND_DRAIN, partition,
                                            [len(data)], data)
                except (DeadExecutorError, TransportError, TimeoutError,
                        IndexError) as e:
                    # the slot died since the candidate snapshot was
                    # taken — back-to-back drains race their retire
                    # announces against this pass. Drop it from the
                    # routing pool and re-route the row; the driver's
                    # coverage check still arbitrates the final truth.
                    log.warning("drain push of shuffle %d map %d p%d to "
                                "slot %d failed (%s); re-routing", sid,
                                map_id, partition, slot, e)
                    if slot in cand_set:
                        cand_set.discard(slot)
                        candidates.remove(slot)
                    continue
                if resp.status == M.STATUS_OK and any(resp.accepted
                                                      or b"\x01"):
                    rows_pushed += 1
                    bytes_pushed += len(data)
                return True
            status = M.STATUS_ERROR
            return False

        def route_row(sid: int, partition: int, map_id: int, fence: int,
                      data: bytes) -> bool:
            """Tier-first drain exit: an only-copy row goes to the cold
            store (one durable blob, no peer involved) when tiering is
            up; a store that is down or a dead shuffle falls back to
            the ordinary peer push — the drain never gets CHEAPER
            guarantees than it had before the cold tier existed."""
            nonlocal rows_pushed, bytes_pushed
            if self.tiering is not None:
                if self.tiering.tier_row(sid, partition, map_id, fence,
                                         data, map_id + 1):
                    rows_pushed += 1
                    bytes_pushed += len(data)
                    return True
                log.debug("drain tier of shuffle %d map %d p%d declined; "
                          "falling back to peer push", sid, map_id,
                          partition)
            return push_row(sid, partition, map_id, fence, data)

        own_sids = src.local_shuffles()
        hosted_sids = (self.merge_store.hosted_shuffles()
                       if self.merge_store is not None else [])
        for sid in sorted(set(own_sids) | set(hosted_sids)):
            directories[sid] = self._drain_directory(
                sid, deadline, expect_entries=sid in own_sids)
        # 1) own committed outputs: the rows that would RE-EXECUTE if
        # this slot died unreplicated
        for sid in own_sids:
            for m, lengths in sorted(src.committed_outputs(sid).items()):
                fence = src.committed_fence(sid, m)
                for p in range(len(lengths)):
                    if time.monotonic() > deadline:
                        log.warning("drain replication hit its deadline "
                                    "mid-pass (shuffle %d map %d p%d)",
                                    sid, m, p)
                        return M.STATUS_ERROR, rows_pushed, bytes_pushed
                    entry, _ = preferred(sid, p)
                    if entry is not None and entry.covers(m):
                        continue  # a surviving replica already has it
                    try:
                        data = src.local_blocks(sid, m, p, p + 1)
                    except Exception as e:  # noqa: BLE001 — corrupt/EIO:
                        # never replicate rot; recovery owns this map
                        log.warning("drain read of shuffle %d map %d "
                                    "p%d failed: %s", sid, m, p, e)
                        status = M.STATUS_ERROR
                        break
                    if data is None:
                        break  # superseded/unregistered mid-drain
                    route_row(sid, p, m, fence, data)
        # 2) hosted merged rows: replicas OTHER maps depend on that
        # would silently die with this slot. export_rows streams the
        # payloads (one row in memory at a time) — a target hosting
        # gigabytes of segments must not materialize them all at the
        # exact moment it is being decommissioned.
        if self.merge_store is not None:
            for sid, partition, map_id, fence, data in \
                    self.merge_store.export_rows():
                if time.monotonic() > deadline:
                    log.warning("drain handoff hit its deadline mid-pass "
                                "(shuffle %d p%d map %d)", sid, partition,
                                map_id)
                    return M.STATUS_ERROR, rows_pushed, bytes_pushed
                entry, _ = preferred(sid, partition)
                if entry is not None and entry.covers(map_id):
                    continue
                route_row(sid, partition, map_id, fence, data)
        return status, rows_pushed, bytes_pushed

    # -- connection pre-warming ------------------------------------------

    def _prewarm_peers(self) -> None:
        """Dial every newly-announced peer in the background so the first
        fetch of a shuffle pays zero handshake latency (the reference
        pre-connects on announce, RdmaShuffleManager.scala:117-126).

        Runs OFF the announce reader thread — dialing is bounded by the
        existing connect budget (``max_connection_attempts`` x
        ``connect_timeout_ms``, java/RdmaNode.java:283-353) and must not
        stall announce processing behind a slow peer. Warms the control
        port always, plus the native block-server port when the fetch
        path would actually use it (no wire compression/codec)."""
        with self._members_lock:
            members = list(self._members)
        warm_block = self._codec is None and not self.conf.wire_compress
        addrs = []
        for m in members:
            if m == TOMBSTONE or m == self.manager_id:
                continue
            addrs.append((m.rpc_host, m.rpc_port))
            if warm_block and m.block_port:
                addrs.append((m.rpc_host, m.block_port))
        with self._prewarm_lock:
            todo = [a for a in addrs if a not in self._prewarmed]
            self._prewarmed.update(todo)
        if not todo:
            return
        threading.Thread(target=self._prewarm_dial, args=(todo,),
                         daemon=True,
                         name=f"prewarm-"
                              f"{self.manager_id.executor_id.executor}"
                         ).start()

    def _prewarm_dial(self, addrs) -> None:
        for host, port in addrs:
            if self._stopping or self.server.stopped:
                return
            try:
                conn = self._clients.get(host, port)
                if self._stopping:
                    # stop() raced the dial: either close_all() drained
                    # the cache after our insert (conn already closed),
                    # or it ran before — then this close is ours to do,
                    # or the socket + reader thread outlive the endpoint
                    conn.close()
                    return
                self.prewarm_dials += 1
            except TransportError as e:
                # un-mark so the next announce retries; the lazy fetch
                # path stays the correctness backstop either way
                with self._prewarm_lock:
                    self._prewarmed.discard((host, port))
                log.debug("prewarm of %s:%s failed: %s", host, port, e)

    # -- serving peers ---------------------------------------------------

    def _handle(self, conn: Connection, msg: RpcMsg) -> Optional[RpcMsg]:
        if isinstance(msg, AnnounceMsg):
            with self._members_lock:
                # Total order by driver epoch: stale snapshots (racing
                # announce threads, reordered delivery) never overwrite a
                # newer tombstoned list.
                if msg.epoch > self._announce_epoch:
                    self._announce_epoch = msg.epoch
                    self._members = list(msg.manager_ids)
            self._members_event.set()
            if self.conf.pre_warm_connections:
                self._prewarm_peers()
            self._watch_pending_joiners()
            return None
        if isinstance(msg, M.MembershipBumpMsg):
            self._on_membership_bump(msg)
            return None
        if isinstance(msg, M.TakeoverMsg):
            # driver failover: re-point the driver channel, forward-only
            # under the incarnation comparison (a zombie's stale
            # broadcast loses). In-flight retry loops re-read the
            # address every attempt, so nothing else needs to notice.
            if self.driver.note_takeover(msg.incarnation, msg.host,
                                         msg.port):
                log.info("driver takeover observed: incarnation %d at "
                         "%s:%d", msg.incarnation, msg.host, msg.port)
            return None
        if isinstance(msg, M.DrainReq):
            # NOT the serve pool: the replication pass can run for up to
            # drain_deadline_ms and must not starve block serving —
            # same contract as the finalize handler
            threading.Thread(
                target=self._on_drain, args=(conn, msg), daemon=True,
                name=f"drain-{self.manager_id.executor_id.executor}"
            ).start()
            return None
        if isinstance(msg, M.EpochBumpMsg):
            self._on_epoch_bump(msg)
            return None
        if isinstance(msg, M.TenantMapMsg):
            self.note_tenant(msg.shuffle_id, msg.tenant)
            from sparkrdma_tpu.shuffle import dist_cache
            dist_cache.set_tenant(msg.shuffle_id, msg.tenant)
            src = self.data_source
            if src is not None and hasattr(src, "note_tenant"):
                src.note_tenant(msg.shuffle_id, msg.tenant)
            if self.merge_store is not None:
                # a fresh registration reusing a dropped id re-arms the
                # merge target (same FIFO channel as the unregister)
                self.merge_store.note_registered(msg.shuffle_id)
            if self.pushed_store is not None:
                self.pushed_store.note_registered(msg.shuffle_id)
            if self.tiering is not None:
                self.tiering.note_registered(msg.shuffle_id)
            self.location_plane.note_registered(msg.shuffle_id)
            return None
        if isinstance(msg, M.ReducePlanMsg):
            self._on_reduce_plan(msg)
            return None
        if isinstance(msg, M.ShardMapMsg):
            from sparkrdma_tpu.shuffle.location_plane import ShardMap
            # a pushed shard map is a registration signal: it re-arms a
            # dead id (same FIFO channel as the unregister push)
            self.location_plane.note_registered(msg.shuffle_id)
            if self.merge_store is not None:
                self.merge_store.note_registered(msg.shuffle_id)
            if self.pushed_store is not None:
                self.pushed_store.note_registered(msg.shuffle_id)
            if self.tiering is not None:
                self.tiering.note_registered(msg.shuffle_id)
            accepted = self.location_plane.put_shard_map(
                msg.shuffle_id, ShardMap(msg.num_maps, msg.shard_slots),
                msg.epoch)
            if accepted and self.shard_owner is not None:
                self._on_shard_assignment(msg.shuffle_id, msg.epoch)
            return None
        if isinstance(msg, M.ShardEntryMsg):
            self._on_shard_entry(msg)
            return None
        if isinstance(msg, M.FetchShardReq):
            return self._on_fetch_shard(conn, msg)
        if isinstance(msg, M.ShardPublishMsg):
            self._on_shard_publish(msg)
            return None
        if isinstance(msg, M.ShardMergedPublishMsg):
            self._on_shard_merged_publish(msg)
            return None
        if isinstance(msg, M.ShardOpMsg):
            if self.shard_standby is not None:
                self.shard_standby.ingest(msg.shuffle_id, msg.shard,
                                          msg.owner_gen, msg.seq,
                                          msg.kind, msg.blob)
            return None
        if isinstance(msg, M.ShardHandoffMsg):
            self._on_shard_handoff(msg)
            return None
        if isinstance(msg, M.FetchOutputReq):
            return self._on_fetch_output(msg)
        if isinstance(msg, M.FetchOutputsReq):
            return self._on_fetch_outputs(msg)
        if isinstance(msg, M.FetchBlocksReq):
            if not self.conf.sw_flow_control:
                return self._on_fetch_blocks(msg)
            self._serve_blocks_async(conn, msg)
            return None
        if isinstance(msg, M.PushBlocksReq):
            self._serve_async(self._on_push_blocks, conn, msg)
            return None
        if isinstance(msg, M.PushPlannedReq):
            self._serve_async(self._on_push_planned, conn, msg)
            return None
        if isinstance(msg, M.FinalizeSegmentsReq):
            # NOT the serve pool: the quiesce wait can hold a worker for
            # up to push_deadline_ms, and the pool is shared with
            # foreground block serving — finalize is once per (shuffle,
            # target), a dedicated short-lived thread is cheap
            threading.Thread(
                target=self._on_finalize_segments, args=(conn, msg),
                daemon=True,
                name=f"finalize-{self.manager_id.executor_id.executor}"
            ).start()
            return None
        if isinstance(msg, M.CreditReport):
            self._credits_of(conn).release(msg.consumed)
            return None
        if isinstance(msg, M.FetchBlocksResp):
            self._on_orphan_blocks_resp(conn, msg)
            return None
        if isinstance(msg, M.RunTaskReq):
            return self._on_run_task(conn, msg)
        if isinstance(msg, M.PingMsg):
            return M.PongMsg(msg.req_id)
        if isinstance(msg, M.PongMsg):
            return None  # pong landed after its ping's deadline: stale
        if isinstance(msg, (M.FetchOutputResp, M.FetchOutputsResp,
                            M.FetchTableResp, M.FetchShardResp,
                            M.FetchPlanResp, M.PushBlocksResp,
                            M.PushPlannedResp, M.FinalizeSegmentsResp,
                            M.FetchMergedResp, M.DrainResp)):
            # orphan of a cancelled/timed-out request (the fetcher
            # cancels whole read-ahead windows on failure); unlike block
            # responses these carry no credits, so dropping is complete
            log.debug("%s: stale %s (requester gave up)",
                      self.manager_id.executor_id.executor,
                      type(msg).__name__)
            return None
        log.warning("%s: unexpected %s", self.manager_id.executor_id.executor,
                    type(msg).__name__)
        return None

    # -- task shipping ---------------------------------------------------

    def set_task_runner(self, runner) -> None:
        """Install ``runner(payload bytes) -> (status, result bytes)``; it
        runs on a bounded worker pool (a task must never run on the
        connection's reader thread — it would block the control plane,
        including the publishes its own writes produce)."""
        from concurrent.futures import ThreadPoolExecutor

        self._task_runner = runner
        if self._task_pool is None:
            self._task_pool = ThreadPoolExecutor(
                max_workers=self.conf.task_threads,
                thread_name_prefix=f"task-{self.manager_id.executor_id.executor}")

    def _on_run_task(self, conn: Connection,
                     msg: M.RunTaskReq) -> Optional[RpcMsg]:
        runner = self._task_runner
        if runner is None or self._task_pool is None:
            return M.RunTaskResp(msg.req_id, M.TASK_NO_RUNNER, b"")

        def work():
            try:
                status, result = runner(msg.data)
            except BaseException as e:  # noqa: BLE001 — even SystemExit
                # from a shipped task must produce a response; a silent
                # swallow leaves the driver waiting out task_timeout_ms
                status, result = M.TASK_ERROR, repr(e).encode()
            try:
                conn.send(M.RunTaskResp(msg.req_id, status, result))
            except TransportError as e:
                log.warning("task response lost (driver gone?): %s", e)

        self._task_pool.submit(work)
        return None  # answered by the worker when the task finishes

    # -- metadata plane (epoch pushes + shard replicas) ------------------

    def _on_epoch_bump(self, msg: M.EpochBumpMsg) -> None:
        """A pushed invalidation: the shuffle's location state moved (or
        died). Epoch-validated caches — location views here, warm
        partition ranges in dist_cache — refresh on their next read
        instead of serving a dead executor's locations."""
        invalidated = self.location_plane.note_epoch(msg.shuffle_id,
                                                     msg.epoch)
        if self.pushed_store is not None and msg.epoch != M.EPOCH_DEAD:
            # a location-epoch ADVANCE names a recovery event: staged
            # pushed ranges conservatively drop (a corrupt-output repair
            # may rewrite bytes; re-pushes re-stage under new fences)
            self.pushed_store.on_location_epoch(msg.shuffle_id, msg.epoch)
        if msg.epoch == M.EPOCH_DEAD:
            self.shard_store.drop(msg.shuffle_id)
            self._expire_shard_waiters(msg.shuffle_id)
            if self.shard_owner is not None:
                # owned ranges, buffered op streams, unconverged batches
                # and the republish backstop all die with the shuffle
                self.shard_owner.drop(msg.shuffle_id)
                self.shard_standby.drop(msg.shuffle_id)
                with self._shard_batch_lock:
                    for k in [k for k in self._shard_batches
                              if k[0] == msg.shuffle_id]:
                        del self._shard_batches[k]
                with self._republish_lock:
                    self._republish.pop(msg.shuffle_id, None)
            if self.merge_store is not None:
                # merged segments + overflow blobs die with the shuffle
                self.merge_store.drop_shuffle(msg.shuffle_id)
            if self.pushed_store is not None:
                # staged pushed ranges die with the shuffle too
                self.pushed_store.drop_shuffle(msg.shuffle_id)
            if self.tiering is not None:
                # cold blobs reap through the same tombstone discipline:
                # an upload racing this death deletes its own blob and
                # skips the publish (modelcheck tier_vs_unregister)
                self.tiering.drop_shuffle(msg.shuffle_id)
            src = self.data_source
            if src is not None and hasattr(src, "remove_shuffle"):
                # shuffle TTL/GC: a driver-side unregister (explicit or
                # TTL sweep) reaps this executor's committed outputs
                # too — on the serve pool, never the reader thread
                # (remove_shuffle unlinks files). Idempotent with the
                # local manager.unregister_shuffle path.
                self._ensure_serve_pool().submit(
                    self._reap_shuffle_disk, src, msg.shuffle_id)
            # terminal: forget the tenant mapping too (a long-running
            # service churning TTL'd shuffles must not leak one dict
            # entry per dead shuffle; re-register re-teaches it)
            with self._tenant_lock:
                self._tenant_map.pop(msg.shuffle_id, None)
        from sparkrdma_tpu.shuffle import dist_cache
        dist_cache.on_epoch(msg.shuffle_id, msg.epoch)
        if invalidated:
            self.tracer.instant("meta.epoch_bump", "meta",
                                shuffle=msg.shuffle_id, epoch=msg.epoch)

    @staticmethod
    def _reap_shuffle_disk(src, shuffle_id: int) -> None:
        try:
            src.remove_shuffle(shuffle_id)
        except Exception:  # noqa: BLE001 — GC must never kill serving
            log.exception("GC reap of shuffle %d failed", shuffle_id)

    def _on_reduce_plan(self, msg: "M.ReducePlanMsg") -> None:
        """A pushed reduce plan (initial publish or mid-stage re-plan):
        cache it for cache-first resolution, and when it REPLACES an
        older epoch's plan invalidate plan-keyed warm state — a re-plan
        re-carves the reduce ranges, so warm bytes cached under the old
        carve-up must never serve (``dist_cache.on_plan_epoch``)."""
        from sparkrdma_tpu.shuffle.planner import ReducePlan
        try:
            plan = ReducePlan.from_bytes(msg.plan_bytes)
        except (struct.error, ValueError) as e:
            log.warning("%s: undecodable reduce plan push: %s",
                        self.manager_id.executor_id.executor, e)
            return
        # a pushed plan names a LIVE shuffle: like the other
        # registration pushes it re-arms a dead/dropped reused id (same
        # FIFO channel as the unregister push). Response-path plans
        # (get_reduce_plan's pull) deliberately don't.
        self.location_plane.note_registered(plan.shuffle_id)
        if self.merge_store is not None:
            self.merge_store.note_registered(plan.shuffle_id)
        if self.tiering is not None:
            self.tiering.note_registered(plan.shuffle_id)
        accepted = self.location_plane.put_plan(plan.shuffle_id, plan)
        if not accepted:
            return  # stale reordered push: must not touch warm state
        if self.pushed_store is not None:
            # adopt the plan epoch: staged ranges a re-plan orphaned are
            # released here (their new slots get the replayed pushes)
            self.pushed_store.on_plan(plan.shuffle_id, plan.plan_epoch)
        if self.on_plan_cb is not None:
            # the planned pusher replays submitted maps against the
            # fresh plan (late-arriving plan, or re-plan re-routing)
            try:
                self.on_plan_cb(plan.shuffle_id)
            except Exception:  # noqa: BLE001 — a replay failure must
                # not drop the plan push (maps stay pull-fetched)
                log.exception("planned-push replay for shuffle %d failed",
                              plan.shuffle_id)
        from sparkrdma_tpu.shuffle import dist_cache
        dist_cache.on_plan_epoch(plan.shuffle_id, plan.plan_epoch)
        if plan.plan_epoch > 1:
            self.tracer.instant("plan.replan", "plan",
                                shuffle=plan.shuffle_id,
                                epoch=plan.plan_epoch)

    def get_reduce_plan(self, shuffle_id: int, timeout: float = 5.0):
        """Cache-first ReducePlan resolution: the pushed plan in the
        location plane when present, else ONE pull from the driver
        (``FetchPlanReq`` — the lost-push backstop). Returns None when
        no plan exists (adaptive planning off, or the map stage hasn't
        completed): callers run the identity plan."""
        cached = self.location_plane.plan(shuffle_id)
        if cached is not None:
            return cached
        from sparkrdma_tpu.shuffle.planner import ReducePlan
        try:
            resp = self.driver.request(
                lambda c: M.FetchPlanReq(c.next_req_id(), shuffle_id),
                timeout=timeout)
        except (TransportError, TimeoutError) as e:
            log.debug("reduce-plan fetch for shuffle %d failed: %s",
                      shuffle_id, e)
            return None
        assert isinstance(resp, M.FetchPlanResp)
        if resp.status != M.STATUS_OK:
            return None
        plan = ReducePlan.from_bytes(resp.plan_bytes)
        if self.location_plane.put_plan(shuffle_id, plan):
            from sparkrdma_tpu.shuffle import dist_cache
            dist_cache.on_plan_epoch(shuffle_id, plan.plan_epoch)
        return plan

    def _on_shard_entry(self, msg: M.ShardEntryMsg) -> None:
        self.shard_store.apply(msg.shuffle_id, msg.epoch, msg.map_id,
                               msg.num_maps, msg.entry)
        # wake any shard long-poller this entry satisfies (push, not
        # client polling — the driver's waiter contract, at shard scale)
        ready = []
        with self._shard_waiters_lock:
            pending = self._shard_waiters.get(msg.shuffle_id)
            if pending:
                still = []
                for w in pending:
                    conn, req_id, lo, hi, min_pub, _deadline = w
                    n = self.shard_store.count_in(msg.shuffle_id, lo, hi)
                    if n is not None and n >= min_pub:
                        ready.append(w)
                    else:
                        still.append(w)
                if still:
                    self._shard_waiters[msg.shuffle_id] = still
                else:
                    self._shard_waiters.pop(msg.shuffle_id, None)
        for conn, req_id, lo, hi, _min_pub, _deadline in ready:
            self._answer_shard_waiter(msg.shuffle_id, conn, req_id, lo, hi)

    def _answer_shard_waiter(self, shuffle_id: int, conn: Connection,
                             req_id: int, lo: int, hi: int) -> None:
        res = self.shard_store.read_range(shuffle_id, lo, hi)
        if res is None:
            resp = M.FetchShardResp(req_id, -1, 0, b"")
        else:
            n, epoch, data = res
            resp = M.FetchShardResp(req_id, n, epoch, data)
        try:
            conn.send(resp)
        except TransportError as e:
            log.debug("shard long-poll answer failed: %s", e)

    def _on_fetch_shard(self, conn: Connection,
                        msg: M.FetchShardReq) -> Optional[RpcMsg]:
        """Serve one driver-table map-range out of this executor's shard
        replica — the fan-in distribution half of the sharded metadata
        plane. Long-poll semantics mirror the driver's table fetch:
        unsatisfiable requests park as waiters answered by the entry
        forward that satisfies them (or swept at deadline with the
        partial range)."""
        res = self.shard_store.read_range(msg.shuffle_id, msg.map_lo,
                                          msg.map_hi)
        if res is None:
            # no replica here (never assigned, or dropped): the client
            # falls back to the authoritative driver table
            return M.FetchShardResp(msg.req_id, -1, 0, b"")
        n, epoch, data = res
        if n >= msg.min_published or msg.timeout_ms <= 0:
            return M.FetchShardResp(msg.req_id, n, epoch, data)
        deadline = time.monotonic() + msg.timeout_ms / 1000
        with self._shard_waiters_lock:
            self._shard_waiters.setdefault(msg.shuffle_id, []).append(
                (conn, msg.req_id, msg.map_lo, msg.map_hi,
                 msg.min_published, deadline))
        self._ensure_park_sweeper()  # the shared sweeper expires these
        return None

    def _expire_shard_waiters(self, shuffle_id: Optional[int] = None,
                              now: Optional[float] = None) -> None:
        """Answer shard waiters that expired (``now``) or whose shuffle
        died (``shuffle_id``) with the partial range — the terminal
        status contract of the driver's sweeper, at shard scale."""
        expired = []
        with self._shard_waiters_lock:
            for sid, pending in list(self._shard_waiters.items()):
                if shuffle_id is not None and sid != shuffle_id:
                    continue
                if shuffle_id is not None:
                    dead, live = pending, []
                else:
                    dead = [w for w in pending if w[5] <= now]
                    live = [w for w in pending if w[5] > now]
                if dead:
                    expired.extend((sid, w) for w in dead)
                    if live:
                        self._shard_waiters[sid] = live
                    else:
                        self._shard_waiters.pop(sid, None)
        for sid, (conn, req_id, lo, hi, _min_pub, _dl) in expired:
            self._answer_shard_waiter(sid, conn, req_id, lo, hi)

    # -- partitioned metadata ownership (shuffle/shard_plane.py) ---------

    def _my_slot(self) -> int:
        """This executor's membership slot, or -1 pre-announce. The
        announce always precedes any shard assignment on the same FIFO
        driver channel, so a real owner resolves by the time an
        assignment can name it; -1 callers degrade to the driver path."""
        with self._members_lock:
            for i, m in enumerate(self._members):
                if m == self.manager_id:
                    return i
        return -1

    def _on_shard_assignment(self, shuffle_id: int, gen: int) -> None:
        """An accepted (generation-forward) shard assignment: adopt the
        ranges this slot now owns, seal + flush the ones it no longer
        does, and re-aim buffered publishes (the handoff backstop)."""
        smap = self.location_plane.shard_map(shuffle_id)
        me = self._my_slot()
        if smap is None or me < 0:
            return
        owned_now = {sh for sh in range(smap.num_shards)
                     if smap.shard_slots[sh] == me}
        for sh in self.shard_owner.owned_shards(shuffle_id):
            if sh not in owned_now and \
                    (self.shard_owner.gen_of(shuffle_id, sh) or 0) < gen:
                self.shard_owner.seal(shuffle_id, sh)
        for sh in owned_now:
            lo, hi = smap.range_of(sh)
            self.shard_owner.adopt(shuffle_id, sh, lo, hi,
                                   smap.num_maps, gen)
        # flush + republish OFF the driver reader thread: both dial
        # peers, and the reader must stay free to drain pushes
        self._ensure_serve_pool().submit(self._flush_shard_batches,
                                         shuffle_id)
        with self._republish_lock:
            buffered = bool(self._republish.get(shuffle_id))
        if buffered:
            self._ensure_serve_pool().submit(self._republish_shuffle,
                                             shuffle_id)

    def _on_shard_handoff(self, msg: "M.ShardHandoffMsg") -> None:
        """Ownership of (shuffle, shard) moved. Outgoing owner (alive —
        the drain case): seal NOW, later direct publishes bounce to the
        driver. Incoming owner: replay the standby buffer under the new
        generation — the records re-run the full owner apply (store +
        serve replica + stream + batch), so nothing the dead owner had
        logged is lost and the driver batch echo stays idempotent."""
        if self.shard_owner is None:
            return
        sid, shard = msg.shuffle_id, msg.shard
        me = self._my_slot()
        if me < 0:
            return
        if msg.old_slot == me:
            self.shard_owner.seal(sid, shard)
            self._ensure_serve_pool().submit(self._flush_shard_batches,
                                             sid)
        if msg.new_slot == me and self.shard_standby is not None:
            from sparkrdma_tpu.shuffle import ha
            records = self.shard_standby.take(sid, shard)
            for kind, blob in records:
                if kind == ha.SHARD_OP_PUBLISH:
                    map_id, fence, entry, lengths = \
                        ha.unpack_shard_publish(blob)
                    self._owner_publish(sid, map_id, entry, fence,
                                        msg.owner_gen, lengths)
                elif kind == ha.SHARD_OP_MERGED:
                    self._owner_merged(sid, shard, msg.owner_gen, blob)

    def _owner_publish(self, shuffle_id: int, map_id: int, entry: bytes,
                       fence: int, gen: int, lengths=None) -> bool:
        """Owner-side apply of one direct publish: fence CAS + log in
        the owner store (log-before-apply), serve-replica apply + waiter
        wake, op stream to the standby, batch toward the driver. False =
        not applied here (caller forwards to the driver); a FENCED
        zombie returns True — handled, deliberately not forwarded."""
        from sparkrdma_tpu.shuffle import shard_plane
        owner = self.shard_owner
        if owner is None:
            return False
        shard = owner.shard_for(shuffle_id, map_id)
        if shard is None:
            return False
        status, rec = owner.publish(shuffle_id, shard, map_id, entry,
                                    fence, gen, lengths)
        # analysis: epoch-eq-ok(FENCED is a write-path status code, not a version; exact match selects the handled-no-forward outcome)
        if status == shard_plane.FENCED:
            return True
        if status != shard_plane.APPLIED:
            return False
        smap = self.location_plane.shard_map(shuffle_id)
        num_maps = smap.num_maps if smap is not None else map_id + 1
        epoch = self.location_plane.known_epoch(shuffle_id) or 1
        self._on_shard_entry(M.ShardEntryMsg(shuffle_id, epoch, map_id,
                                             num_maps, entry))
        self._stream_shard_op(shuffle_id, shard, gen, rec)
        self._queue_shard_batch(shuffle_id, shard, gen,
                                record=(map_id, fence, entry, lengths))
        return True

    def _owner_merged(self, shuffle_id: int, shard: int, gen: int,
                      blob: bytes) -> bool:
        from sparkrdma_tpu.shuffle import shard_plane
        owner = self.shard_owner
        if owner is None:
            return False
        status, rec = owner.merged(shuffle_id, shard, gen, blob)
        if status != shard_plane.APPLIED:
            return False
        self._stream_shard_op(shuffle_id, shard, gen, rec)
        self._queue_shard_batch(shuffle_id, shard, gen, blob=blob)
        return True

    def _on_shard_publish(self, msg: "M.ShardPublishMsg") -> None:
        """A direct-to-owner publish (the one-hop write path). Not
        applicable here — stale map at the sender, sealed shard, a
        handoff won the race — forwards to the driver: the stale view
        costs one extra hop, never a lost entry."""
        if self._owner_publish(msg.shuffle_id, msg.map_id, msg.entry,
                               msg.fence, msg.owner_gen, msg.lengths):
            return
        try:
            self.driver.send(M.PublishMsg(msg.shuffle_id, msg.map_id,
                                          msg.entry, fence=msg.fence,
                                          lengths=msg.lengths))
        except TransportError as e:
            log.debug("non-owner publish forward for shuffle %d map %d "
                      "failed: %s", msg.shuffle_id, msg.map_id, e)

    def _on_shard_merged_publish(self,
                                 msg: "M.ShardMergedPublishMsg") -> None:
        if self._owner_merged(msg.shuffle_id, msg.shard, msg.owner_gen,
                              msg.blob):
            return
        try:
            inner = M.MergedPublishMsg.from_payload(msg.blob)
        except (struct.error, ValueError, IndexError) as e:
            log.warning("undecodable merged blob routed at shuffle %d "
                        "shard %d: %s", msg.shuffle_id, msg.shard, e)
            return
        try:
            self.driver.send(inner)
        except TransportError as e:
            log.debug("non-owner merged forward for shuffle %d failed: "
                      "%s", msg.shuffle_id, e)

    def _shard_standby_peer(self, shuffle_id: int, shard: int):
        """Deterministic standby for an owned shard: the NEXT shard's
        owner slot (wrapping) — a distinct live host whenever the
        assignment has more than one shard. None for single-shard maps
        (the driver batch is the only backstop there, which is the
        pre-ownership durability story)."""
        smap = self.location_plane.shard_map(shuffle_id)
        if smap is None or smap.num_shards < 2:
            return None
        slot = smap.shard_slots[(shard + 1) % smap.num_shards]
        if slot == self._my_slot():
            return None
        try:
            return self.member_at(slot)
        except (DeadExecutorError, IndexError):
            return None

    def _stream_shard_op(self, shuffle_id: int, shard: int, gen: int,
                         rec) -> None:
        peer = self._shard_standby_peer(shuffle_id, shard)
        if peer is None:
            return
        try:
            conn = self._clients.get(peer.rpc_host, peer.rpc_port)
            conn.send(M.ShardOpMsg(shuffle_id, shard, gen, rec.seq,
                                   rec.kind, rec.payload))
        except TransportError as e:
            # one-attempt like every push; the driver batch still
            # converges, so a lost stream record degrades failover
            # freshness, never correctness
            log.debug("shard op stream for shuffle %d shard %d failed: "
                      "%s", shuffle_id, shard, e)

    def _queue_shard_batch(self, shuffle_id: int, shard: int, gen: int,
                           record=None, blob=None) -> None:
        """Stage one applied write for driver convergence; flush at
        shard_batch_entries (the flusher thread drains partials)."""
        out = []
        with self._shard_batch_lock:
            key = (shuffle_id, shard)
            cur = self._shard_batches.get(key)
            if cur is None or cur[0] != gen:
                if cur is not None and (cur[1] or cur[2]):
                    out.append(M.ShardBatchMsg(shuffle_id, shard, cur[0],
                                               cur[1], cur[2]))
                cur = (gen, [], [])
                self._shard_batches[key] = cur
            if record is not None:
                cur[1].append(record)
            if blob is not None:
                cur[2].append(blob)
            if len(cur[1]) + len(cur[2]) >= self.conf.shard_batch_entries:
                out.append(M.ShardBatchMsg(shuffle_id, shard, gen,
                                           cur[1], cur[2]))
                del self._shard_batches[key]
        for m in out:
            try:
                self.driver.send(m)
            except TransportError as e:
                log.warning("shard batch for shuffle %d failed: %s",
                            shuffle_id, e)
        self._ensure_shard_flusher()

    def _flush_shard_batches(self,
                             shuffle_id: Optional[int] = None) -> None:
        with self._shard_batch_lock:
            keys = [k for k in self._shard_batches
                    if shuffle_id is None or k[0] == shuffle_id]
            out = []
            for k in keys:
                gen, recs, blobs = self._shard_batches.pop(k)
                if recs or blobs:
                    out.append(M.ShardBatchMsg(k[0], k[1], gen, recs,
                                               blobs))
        for m in out:
            try:
                self.driver.send(m)
            except TransportError as e:
                log.warning("shard batch flush for shuffle %d failed: %s",
                            m.shuffle_id, e)

    def _ensure_shard_flusher(self) -> None:
        if self._shard_flusher is not None or self._stopping:
            return
        with self._shard_batch_lock:
            if self._shard_flusher is not None:
                return
            t = threading.Thread(
                target=self._shard_flush_loop, daemon=True,
                name=f"shard-flush-{self.manager_id.executor_id.executor}")
            self._shard_flusher = t
        t.start()

    def _shard_flush_loop(self) -> None:
        # partial-batch drain every 10ms: convergence lag toward the
        # driver stays bounded even when publishes trickle in below the
        # batch threshold
        while not self._stopping:
            self._shard_flush_wake.wait(timeout=0.01)
            self._shard_flush_wake.clear()
            if self._stopping:
                return
            self._flush_shard_batches()

    def _send_owner_publish(self, shuffle_id: int, map_id: int,
                            entry: bytes, fence: int, lengths) -> bool:
        """Route a publish straight to its map-range OWNER — one hop,
        no driver round-trip ("RPC Considered Harmful": the destination
        is known ahead of time). Remembers the publish for handoff
        republish first, so no window exists where a dying owner is the
        only holder. False = caller sends the ordinary driver publish."""
        if self.shard_owner is None:
            return False
        smap_v = self.location_plane.shard_map_v(shuffle_id)
        if smap_v is None:
            return False
        smap, gen = smap_v
        with self._republish_lock:
            self._republish.setdefault(shuffle_id, {})[map_id] = (
                entry, fence, list(lengths) if lengths is not None
                else None)
        try:
            shard = smap.shard_of(map_id)
        except IndexError:
            return False
        slot = smap.shard_slots[shard]
        if slot == self._my_slot():
            return self._owner_publish(shuffle_id, map_id, entry, fence,
                                       gen, lengths)
        try:
            peer = self.member_at(slot)
            conn = self._clients.get(peer.rpc_host, peer.rpc_port)
            conn.send(M.ShardPublishMsg(shuffle_id, map_id, entry,
                                        fence, gen, lengths))
            return True
        except (DeadExecutorError, IndexError, TransportError) as e:
            log.debug("direct publish for shuffle %d map %d fell back "
                      "to the driver: %s", shuffle_id, map_id, e)
            return False

    def _republish_shuffle(self, shuffle_id: int) -> None:
        """Handoff backstop: re-aim this publisher's remembered
        publishes at the (new) owners. Fence floors make duplicates
        no-ops; a publish that died in a killed owner's socket gets
        re-delivered — a metadata re-send, never a map re-execution."""
        with self._republish_lock:
            buffered = dict(self._republish.get(shuffle_id, {}))
        for map_id, (entry, fence, lengths) in buffered.items():
            if self._send_owner_publish(shuffle_id, map_id, entry, fence,
                                        lengths):
                continue
            try:
                self.driver.send(M.PublishMsg(shuffle_id, map_id, entry,
                                              fence=fence,
                                              lengths=lengths))
            except TransportError as e:
                log.debug("republish of shuffle %d map %d failed: %s",
                          shuffle_id, map_id, e)

    def _send_owner_merged(self, msg: "M.MergedPublishMsg") -> bool:
        """Route a merged-directory publish to the owner of shard
        ``partition % num_shards`` (deterministic spread — merged
        segments aren't map-range keyed, so any stable rule works).
        False = caller sends it to the driver directly."""
        if self.shard_owner is None:
            return False
        smap_v = self.location_plane.shard_map_v(msg.shuffle_id)
        if smap_v is None:
            return False
        smap, gen = smap_v
        shard = msg.partition_id % smap.num_shards
        blob = msg.payload()
        slot = smap.shard_slots[shard]
        if slot == self._my_slot():
            return self._owner_merged(msg.shuffle_id, shard, gen, blob)
        try:
            peer = self.member_at(slot)
            conn = self._clients.get(peer.rpc_host, peer.rpc_port)
            conn.send(M.ShardMergedPublishMsg(msg.shuffle_id, shard, gen,
                                              blob))
            return True
        except (DeadExecutorError, IndexError, TransportError) as e:
            log.debug("owner-routed merged publish for shuffle %d fell "
                      "back to the driver: %s", msg.shuffle_id, e)
            return False

    def _publish_merged(self, msg: "M.MergedPublishMsg") -> None:
        """The merge finalizer's publish callback: owner-routed in
        ownership mode, driver-direct otherwise. When the cold tier is
        on, the SAME descriptor also enqueues a background upload —
        the tiering service reads the sealed ranges back through the
        serve path and publishes the blob one-sided when it lands."""
        if self.tiering is not None:
            self.tiering.submit(msg)
        if self._send_owner_merged(msg):
            return
        self.driver.send(msg)

    def _publish_tiered(self, msg: "M.TieredPublishMsg") -> None:
        """The tiering service's publish callback: driver-direct and
        one-sided (the directory is HA-replicated driver-side)."""
        self.driver.send(msg)

    def _corrupt_served(self, shuffle_id: int, map_id: int,
                        detail: str) -> None:
        """Audit a serve that found at-rest corruption (the resolver
        already quarantined the output)."""
        self.tracer.instant("serve.corrupt", "fault", shuffle=shuffle_id,
                            map=map_id, detail=detail)
        log.error("%s: serving shuffle %d map %d found corrupt committed "
                  "output (%s); answering STATUS_CORRUPT so the reducer "
                  "re-executes the map",
                  self.manager_id.executor_id.executor, shuffle_id, map_id,
                  detail)

    def _on_fetch_output(self, msg: M.FetchOutputReq) -> RpcMsg:
        """Serve 16B location entries
        (scala/RdmaShuffleFetcherIterator.scala:293-315 analogue)."""
        if self.data_source is None:
            return M.FetchOutputResp(msg.req_id, M.STATUS_ERROR, b"")
        from sparkrdma_tpu.utils.integrity import CorruptOutputError
        try:
            table = self.data_source.get_output_table(msg.shuffle_id,
                                                      msg.map_id)
        except CorruptOutputError as e:
            self._corrupt_served(msg.shuffle_id, msg.map_id, str(e))
            return M.FetchOutputResp(msg.req_id, M.STATUS_CORRUPT, b"")
        except OSError as e:
            # transient disk error in the serve-time verify: answer the
            # retryable class — an unanswered request would burn the
            # requester's whole deadline instead of one backoff
            log.warning("location serve failed for shuffle %d map %d: %s",
                        msg.shuffle_id, msg.map_id, e)
            return M.FetchOutputResp(msg.req_id, M.STATUS_ERROR, b"")
        if table is None:
            return M.FetchOutputResp(msg.req_id, M.STATUS_UNKNOWN_MAP, b"")
        if not (0 <= msg.start_partition <= msg.end_partition <= table.num_partitions):
            return M.FetchOutputResp(msg.req_id, M.STATUS_BAD_RANGE, b"")
        return M.FetchOutputResp(msg.req_id, M.STATUS_OK,
                                 table.get_range(msg.start_partition, msg.end_partition))

    def _on_fetch_outputs(self, msg: M.FetchOutputsReq) -> RpcMsg:
        """Serve MANY maps' 16B location entries in one response (the
        batched metadata read of the coalesced dataplane). Per-map
        statuses answer each map authoritatively — one unpublished map
        doesn't hide the others' entries — while structural problems
        (no data source, a response past the payload cap) fail the whole
        request."""
        if self.data_source is None:
            return M.FetchOutputsResp(msg.req_id, M.STATUS_ERROR, [])
        from sparkrdma_tpu.shuffle.map_output import ENTRY_SIZE

        span = msg.end_partition - msg.start_partition
        if (msg.start_partition < 0 or span < 0
                or span * ENTRY_SIZE * max(1, len(msg.map_ids))
                > self._MAX_RESP_PAYLOAD):
            return M.FetchOutputsResp(msg.req_id, M.STATUS_BAD_RANGE, [])
        from sparkrdma_tpu.utils.integrity import CorruptOutputError
        records = []
        for map_id in msg.map_ids:
            try:
                table = self.data_source.get_output_table(msg.shuffle_id,
                                                          map_id)
            except CorruptOutputError as e:
                self._corrupt_served(msg.shuffle_id, map_id, str(e))
                records.append((map_id, M.STATUS_CORRUPT, b""))
                continue
            except OSError as e:
                log.warning("location serve failed for shuffle %d map %d: "
                            "%s", msg.shuffle_id, map_id, e)
                records.append((map_id, M.STATUS_ERROR, b""))
                continue
            if table is None:
                records.append((map_id, M.STATUS_UNKNOWN_MAP, b""))
            elif not (msg.start_partition <= msg.end_partition
                      <= table.num_partitions):
                records.append((map_id, M.STATUS_BAD_RANGE, b""))
            else:
                records.append((map_id, M.STATUS_OK, table.get_range(
                    msg.start_partition, msg.end_partition)))
        return M.FetchOutputsResp(msg.req_id, M.STATUS_OK, records)

    # Response-payload caps, mirroring the native server's kMaxRespPayload:
    # reject before reading so an oversized request can't build a frame the
    # client Reassembler drops (>1 GiB tears down the shared pipelined
    # connection) or that wraps the u32 frame length past 4 GiB. Multi-
    # block groups are client-capped at shuffle_read_block_size, so the cap
    # tracks that config (floor 256 MiB, matching the native server); a
    # group with at most one non-empty block (the fetcher's oversized-fetch
    # escape, shuffle/fetcher.py:291 — possibly with zero-length riders) is
    # allowed up to a Reassembler-safe bound.
    _MAX_RESP_PAYLOAD = 256 << 20
    _MAX_SINGLE_BLOCK = (1 << 30) - (1 << 20)

    def _credits_of(self, conn: Connection) -> ByteCredits:
        with self._credits_lock:
            credits = self._conn_credits.get(conn)
            if credits is None:
                credits = ByteCredits(self.conf.serve_credit_bytes)
                self._conn_credits[conn] = credits
            return credits

    def serve_stats(self) -> dict:
        """Audit view of the serving windows (tests assert a stalled
        consumer bounds server-held bytes; ops dashboards watch parking)."""
        with self._credits_lock:
            creds = list(self._conn_credits.values())
        return {
            "budget": self.conf.serve_credit_bytes,
            "peak_reserved": max((c.peak_reserved for c in creds),
                                 default=0),
            "parked": sum(c.parked for c in creds),
            "credit_timeouts": self._credit_timeouts,
        }

    def _ensure_serve_pool(self):
        if self._serve_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._serve_pool_lock:
                if self._serve_pool is None:
                    self._serve_pool = ThreadPoolExecutor(
                        max_workers=self.conf.serve_threads,
                        thread_name_prefix=(
                            f"serve-{self.manager_id.executor_id.executor}"))
        return self._serve_pool

    def _serve_async(self, handler, conn: Connection, msg: RpcMsg) -> None:
        """Run one disk-touching handler on the serve pool (push-merge
        appends/finalizes share the block-serving workers — a reader
        thread must never block on disk)."""

        def work():
            try:
                handler(conn, msg)
            except Exception:  # noqa: BLE001 — serving thread must not die
                log.exception("%s handler failed", type(msg).__name__)

        self._ensure_serve_pool().submit(work)

    def _ensure_serve_drr(self):
        if self._serve_drr is None:
            from sparkrdma_tpu.shuffle.tenancy import DeficitRoundRobin

            with self._serve_pool_lock:
                if self._serve_drr is None:
                    self._serve_drr = DeficitRoundRobin(
                        self.conf.fair_share_quantum_bytes)
        return self._serve_drr

    def _serve_blocks_async(self, conn: Connection,
                            msg: M.FetchBlocksReq) -> None:
        """Hand one data request to the serve pool — FIFO when fair
        share is off, else through the per-tenant DRR queue: requests
        queue under the OWNING tenant of the shuffle being served and
        each pool worker dispatches the next request by byte-cost
        deficit round robin, so one tenant's deep fan-in backlog cannot
        starve another tenant's small latency-sensitive fetch. With a
        single active tenant DRR order IS arrival order (= the FIFO
        path exactly)."""
        if not self.conf.fair_share_serving:
            self._ensure_serve_pool().submit(self._serve_blocks, conn, msg)
            return
        drr = self._ensure_serve_drr()
        cost = sum(length for _, _, length in msg.blocks)
        drr.push(self.tenant_of(msg.shuffle_id), cost, (conn, msg))
        self._ensure_serve_pool().submit(self._serve_next_fair)

    def _serve_next_fair(self) -> None:
        item = self._serve_drr.pop()
        if item is None:
            return  # a sibling worker drained the queue
        conn, msg = item
        tenant = self.tenant_of(msg.shuffle_id)
        with self._tenant_lock:
            self.fair_served[tenant] = self.fair_served.get(tenant, 0) + 1
        self.tracer.instant("tenant.serve", "tenant",
                            shuffle=msg.shuffle_id, tenant=tenant)
        self._serve_blocks(conn, msg)

    def _serve_blocks(self, conn: Connection, msg: M.FetchBlocksReq) -> None:
        """One data response under the connection's credit window: reserve
        the response's logical size BEFORE building it, send, and let the
        reader's CreditReport — sent on receipt — replenish. A request
        that doesn't fit parks as a QUEUED continuation (the serving
        thread is freed; a stalled connection can't head-of-line-block
        other connections' serving), expiring with STATUS_ERROR after the
        park timeout instead of growing server memory."""
        credits = self._credits_of(conn)
        total = sum(length for _, _, length in msg.blocks)

        def resume():  # reservation already taken by release()
            self._serve_pool.submit(self._serve_reserved, credits, conn,
                                    msg, total)

        def expire():
            self._credit_timeouts += 1
            log.warning("fetch parked past the credit window for %.1fs; "
                        "failing it (consumer stalled?)",
                        self.conf.connect_timeout_ms / 1000)
            try:
                conn.send(M.FetchBlocksResp(msg.req_id, M.STATUS_ERROR,
                                            b""))
            except TransportError:
                pass

        if credits.reserve_or_park(
                total, time.monotonic() + self.conf.connect_timeout_ms / 1000,
                resume, expire):
            self._serve_reserved(credits, conn, msg, total)
            return
        self._ensure_park_sweeper()

    def _serve_reserved(self, credits: ByteCredits, conn: Connection,
                        msg: M.FetchBlocksReq, total: int) -> None:
        try:
            resp = self._on_fetch_blocks(msg)
        except Exception:  # noqa: BLE001 — serving thread must not die
            credits.release(total)
            log.exception("block serving failed")
            return
        delivered = False
        try:
            conn.send(resp)
            delivered = True
        except TransportError:
            pass
        # non-OK responses carry no data (no report will come) and a dead
        # connection never reports: hand those credits straight back
        if resp.status != M.STATUS_OK or not delivered:
            credits.release(total)

    def _ensure_park_sweeper(self) -> None:
        with self._serve_pool_lock:
            if self._park_sweeper is None:
                self._park_sweeper = threading.Thread(
                    target=self._sweep_parked, daemon=True,
                    name=f"park-sweep-"
                         f"{self.manager_id.executor_id.executor}")
                self._park_sweeper.start()

    def _sweep_parked(self) -> None:
        while not self.server.stopped:
            time.sleep(0.2)
            now = time.monotonic()
            with self._credits_lock:
                creds = list(self._conn_credits.values())
            for credits in creds:
                for expire in credits.expire_stale(now):
                    try:
                        expire()
                    except Exception:  # noqa: BLE001 — sweeper must live
                        log.exception("park expiry callback failed")
            try:
                self._expire_shard_waiters(now=now)
            except Exception:  # noqa: BLE001 — sweeper must live
                log.exception("shard waiter expiry failed")

    def _on_fetch_blocks(self, msg: M.FetchBlocksReq) -> RpcMsg:
        """Serve a scatter data read (DCN fallback of the one-sided READ,
        scala/RdmaShuffleFetcherIterator.scala:119-180)."""
        if self.data_source is None:
            return M.FetchBlocksResp(msg.req_id, M.STATUS_ERROR, b"")
        total = sum(length for _, _, length in msg.blocks)
        nonempty = sum(1 for _, _, length in msg.blocks if length)
        cap = (self._MAX_SINGLE_BLOCK if nonempty <= 1
               else max(self._MAX_RESP_PAYLOAD,
                        self.conf.shuffle_read_block_size))
        if total > min(cap, self._MAX_SINGLE_BLOCK):
            return M.FetchBlocksResp(msg.req_id, M.STATUS_BAD_RANGE, b"")
        from sparkrdma_tpu.utils.integrity import CorruptOutputError
        parts = []
        for token, offset, length in msg.blocks:
            try:
                data = self.data_source.read_block(msg.shuffle_id, token,
                                                   offset, length)
            except CorruptOutputError as e:
                # the serve-time spot check caught at-rest rot: NEVER send
                # the torn bytes — answer CORRUPT (retryable) so the
                # reducer's envelope escalates into map re-execution
                self._corrupt_served(msg.shuffle_id, -1, str(e))
                return M.FetchBlocksResp(msg.req_id, M.STATUS_CORRUPT, b"")
            except OSError as e:
                # serve-time disk error (EIO on the mapped file): a
                # transient answer — the refetch may land on healthy media
                log.warning("serve-time read error for shuffle %d: %s",
                            msg.shuffle_id, e)
                return M.FetchBlocksResp(msg.req_id, M.STATUS_ERROR, b"")
            if data is None:
                return M.FetchBlocksResp(msg.req_id, M.STATUS_UNKNOWN_SHUFFLE, b"")
            parts.append(data)
        payload = b"".join(parts)
        flags = 0
        if self.conf.fetch_checksum and msg.blocks:
            # per-block CRC32 trailer, appended BEFORE compression/codec
            # so the check spans server read -> client consume (a zlib or
            # codec layer already fails loudly on ITS OWN wire bytes, but
            # says nothing about corruption before the encode). Blocks
            # whose range tiles the at-rest sidecar's attested ranges
            # reuse the committed CRCs (resolver.block_crc — the same
            # contract the native server's CRC table implements in C)
            # instead of re-hashing the bytes on every serve.
            import struct
            import zlib
            flags |= M.FLAG_CRC32
            attested = getattr(self.data_source, "block_crc", None)
            crcs = []
            for (token, offset, length), p in zip(msg.blocks, parts):
                crc = (attested(msg.shuffle_id, token, offset, length)
                       if attested is not None else None)
                crcs.append(zlib.crc32(p) if crc is None else crc)
            payload += struct.pack(f"<{len(parts)}I", *crcs)
        # DCN wire compression — the analogue of the engine-level shuffle
        # block compression the reference inherits from Spark's serializer
        # (scala/RdmaShuffleReader.scala:54-69 wraps streams the same way).
        if (self.conf.wire_compress
                and len(payload) >= self.conf.wire_compress_min):
            import zlib
            compressed = zlib.compress(payload, level=1)
            if len(compressed) < len(payload):
                # OR into flags: the CRC32 trailer (if any) rides inside
                # the compressed bytes and must stay flagged for the
                # reader to verify and strip after decompressing
                payload, flags = compressed, flags | M.FLAG_ZLIB
        if self._codec is not None:
            flags |= M.FLAG_WRAPPED
            payload = self._codec.wrap(payload, self._codec_key,
                                       _codec_aad(msg, flags))
        return M.FetchBlocksResp(msg.req_id, M.STATUS_OK, payload, flags)

    # -- push-merge serving + client calls (shuffle/push_merge.py) -------

    def _on_push_blocks(self, conn: Connection,
                        msg: "M.PushBlocksReq") -> None:
        store = self.merge_store
        if store is None:
            resp = M.PushBlocksResp(msg.req_id, M.STATUS_ERROR, 0, b"")
        elif msg.kind == M.PUSH_KIND_OVERFLOW:
            status, token = store.push_overflow(
                msg.shuffle_id, msg.map_id, msg.fence, msg.data)
            resp = M.PushBlocksResp(msg.req_id, status, token, b"")
        else:
            status, accepted = store.push(
                msg.shuffle_id, msg.map_id, msg.fence,
                msg.start_partition, msg.sizes, msg.data,
                reopen=msg.kind == M.PUSH_KIND_DRAIN)
            resp = M.PushBlocksResp(msg.req_id, status, 0, accepted)
        try:
            conn.send(resp)
        except TransportError as e:
            log.debug("push response lost: %s", e)

    def _on_push_planned(self, conn: Connection,
                         msg: "M.PushPlannedReq") -> None:
        store = self.pushed_store
        if store is None:
            # feature off here: FINALIZED stops the sender for good (a
            # mixed-version fleet degrades to pull, never errors)
            resp = M.PushPlannedResp(msg.req_id, M.STATUS_FINALIZED, b"")
        else:
            status, accepted = store.push(
                msg.shuffle_id, msg.map_id, msg.fence, msg.plan_epoch,
                msg.start_partition, msg.sizes, msg.data)
            resp = M.PushPlannedResp(msg.req_id, status, accepted)
        try:
            conn.send(resp)
        except TransportError as e:
            log.debug("planned-push response lost: %s", e)

    def push_planned(self, peer: ShuffleManagerId, shuffle_id: int,
                     map_id: int, fence: int, plan_epoch: int,
                     start_partition: int, sizes, data: bytes
                     ) -> "M.PushPlannedResp":
        """Client half of the planned-push protocol (SegmentPusher)."""
        conn = self._clients.get(peer.rpc_host, peer.rpc_port)
        resp = conn.request(
            M.PushPlannedReq(conn.next_req_id(), shuffle_id, map_id,
                             fence, plan_epoch, start_partition,
                             list(sizes), data),
            timeout=self.conf.resolved_request_deadline_s())
        assert isinstance(resp, M.PushPlannedResp)
        return resp

    def _on_finalize_segments(self, conn: Connection,
                              msg: "M.FinalizeSegmentsReq") -> None:
        """Seal one shuffle's segments. The broadcast form (req_id=0) is
        one-sided; an explicit request gets the finalized count back.
        A short idle-grace wait lets in-flight pushes land first — the
        finalize broadcast races the LAST map's pushes by construction
        (pushes are queued at commit, the broadcast at its publish)."""
        store = self.merge_store
        if store is None:
            if msg.req_id:
                try:
                    conn.send(M.FinalizeSegmentsResp(msg.req_id,
                                                     M.STATUS_ERROR, 0))
                except TransportError:
                    pass
            return
        grace = min(0.25, self.conf.push_deadline_ms / 1000)
        deadline = time.monotonic() + self.conf.push_deadline_ms / 1000
        # a target whose FIRST push is still in flight has no state yet
        # (idle_for = inf): give it the same grace before sealing, or
        # the broadcast racing the pusher's queue would tombstone the
        # shuffle with zero segments
        first_wait = time.monotonic() + grace
        while (store.idle_for(msg.shuffle_id) == float("inf")
               and time.monotonic() < first_wait):
            time.sleep(0.02)
        while (store.idle_for(msg.shuffle_id) < grace
               and time.monotonic() < deadline):
            time.sleep(0.02)
        try:
            count = store.finalize(
                msg.shuffle_id,
                self.exec_index(
                    timeout=self.conf.connect_timeout_ms / 1000),
                publish=self._publish_merged,
                tracer=self.tracer)
        except Exception:  # noqa: BLE001 — dedicated thread, must not
            # die silently; the shuffle just stays unfinalized here
            log.exception("merge finalize of shuffle %d failed",
                          msg.shuffle_id)
            count = 0
        if msg.req_id:
            try:
                conn.send(M.FinalizeSegmentsResp(msg.req_id, M.STATUS_OK,
                                                 count))
            except TransportError:
                pass

    def push_blocks(self, peer: ShuffleManagerId, shuffle_id: int,
                    map_id: int, fence: int, kind: int,
                    start_partition: int, sizes, data: bytes
                    ) -> "M.PushBlocksResp":
        """Client half of the push protocol (SegmentPusher/MergeClient)."""
        conn = self._clients.get(peer.rpc_host, peer.rpc_port)
        resp = conn.request(
            M.PushBlocksReq(conn.next_req_id(), shuffle_id, map_id, fence,
                            kind, start_partition, list(sizes), data),
            timeout=self.conf.resolved_request_deadline_s())
        assert isinstance(resp, M.PushBlocksResp)
        return resp

    def get_merged_directory(self, shuffle_id: int, metrics=None,
                             fresh: bool = False):
        """The shuffle's merged-segment directory, cache-first: the
        location plane's epoch-validated copy when current, else ONE
        pull from the driver (cached under the response epoch when
        non-empty — an empty directory re-pulls next stage, since
        finalize may land any moment). Returns a
        :class:`~sparkrdma_tpu.shuffle.push_merge.MergedDirectory` or
        None (driver unreachable / shuffle unknown / feature off)."""
        if not self.conf.push_merge:
            return None
        cached = None if fresh else self.location_plane.merged(shuffle_id)
        if cached is not None:
            return cached
        from sparkrdma_tpu.shuffle.push_merge import MergedDirectory
        try:
            if metrics is not None:
                metrics.record_metadata_rpc()
                metrics.record_request()
            resp = self.driver.request(
                lambda c: M.FetchMergedReq(c.next_req_id(), shuffle_id),
                timeout=self.conf.resolved_request_deadline_s())
        except (TransportError, TimeoutError) as e:
            log.debug("merged-directory fetch for shuffle %d failed: %s",
                      shuffle_id, e)
            return None
        assert isinstance(resp, M.FetchMergedResp)
        if resp.status != M.STATUS_OK:
            return None
        directory = MergedDirectory.from_bytes(resp.data)
        if len(directory):
            self.location_plane.put_merged(shuffle_id, directory,
                                           resp.epoch)
        return directory

    def get_tiered_directory(self, shuffle_id: int, metrics=None):
        """The shuffle's cold-tier directory: ONE pull from the driver
        per resolve (no cache — the tiered rung is the last resort
        before re-execution, consulted rarely and always wanting the
        freshest coverage). Returns a
        :class:`~sparkrdma_tpu.shuffle.cold_tier.TieredDirectory` or
        None (driver unreachable / shuffle unknown / feature off)."""
        if not self.conf.cold_tier:
            return None
        from sparkrdma_tpu.shuffle.cold_tier import TieredDirectory
        try:
            if metrics is not None:
                metrics.record_metadata_rpc()
                metrics.record_request()
            resp = self.driver.request(
                lambda c: M.FetchTieredReq(c.next_req_id(), shuffle_id),
                timeout=self.conf.resolved_request_deadline_s())
        except (TransportError, TimeoutError) as e:
            log.debug("tiered-directory fetch for shuffle %d failed: %s",
                      shuffle_id, e)
            return None
        assert isinstance(resp, M.FetchTieredResp)
        if resp.status != M.STATUS_OK:
            return None
        return TieredDirectory.from_bytes(resp.data)

    # -- client-side fetch calls (used by the fetcher iterator) ----------

    def publish_map_output(self, shuffle_id: int, map_id: int,
                           table_token: int, fence: int = 0,
                           lengths=None) -> None:
        """(scala/RdmaShuffleManager.scala:384-418). ``fence`` is the
        committing attempt's fencing token — the driver rejects a publish
        naming the same executor with an older fence, so a zombie
        speculative attempt can't clobber the winner's location.
        ``lengths`` (with ``adaptive_plan``) rides the publish so the
        driver's size histogram sees every committed output's
        per-partition bytes without an extra round trip."""
        entry = DriverTable.pack_entry(
            table_token,
            self.exec_index(timeout=self.conf.connect_timeout_ms / 1000))
        if self._send_owner_publish(shuffle_id, map_id, entry, fence,
                                    lengths):
            # landed at (or on) the owning shard host — the owner's
            # batch converges it into the driver table asynchronously
            return
        msg = M.PublishMsg(shuffle_id, map_id, entry, fence=fence,
                           lengths=lengths)
        # retry envelope: a publish racing a failover lands on the new
        # primary; the fence token makes the duplicate (one per primary
        # that received it) idempotent, so at-least-once is safe
        self.driver.send(msg)

    def get_driver_table(self, shuffle_id: int, expect_published: int,
                         timeout: Optional[float] = None,
                         metrics=None) -> DriverTable:
        """The table of :meth:`get_driver_table_v` (compat shape)."""
        return self.get_driver_table_v(shuffle_id, expect_published,
                                       timeout, metrics)[0]

    def get_driver_table_v(self, shuffle_id: int, expect_published: int,
                           timeout: Optional[float] = None,
                           metrics=None) -> Tuple[DriverTable, int]:
        """``(table, epoch)`` for one shuffle — warm path first.

        Warm: the location plane holds a complete epoch-current table —
        zero RPCs. Cold: with a shard map, one long-poll per SHARD HOST
        (fan-in spreads off the driver; any shard failure falls back);
        else the driver long-poll — the driver holds the response until
        the expected publishes have landed (push on publish, not client
        polling — the event-driven analogue of the reference's
        READ-once-after-known-complete,
        scala/RdmaShuffleManager.scala:341-376; wait budget
        partitionLocationFetchTimeout). Complete tables memoize into the
        plane under the response's epoch, unless an invalidation raced
        the poll. ``metrics`` (a fetcher's ReadMetrics) counts the
        metadata RPCs actually issued — a cache hit counts zero."""
        cached = self.location_plane.table(shuffle_id)
        if cached is not None and cached[0].num_published >= expect_published:
            return cached
        with self._table_lock:
            gen = self._table_gen
        tmo = (timeout if timeout is not None
               else self.conf.partition_location_fetch_timeout_ms / 1000)
        deadline = time.monotonic() + tmo
        shard_map = self.location_plane.shard_map(shuffle_id)
        if shard_map is not None:
            # the shard phase may spend at most HALF the budget: a shard
            # replica that never satisfies its long-poll (a lost forward
            # — pushes are one-attempt) must leave the authoritative
            # driver fallback real time, or one lost push would turn
            # every cold sync into a TimeoutError
            sharded = self._fetch_table_sharded(
                shuffle_id, shard_map, expect_published,
                deadline - tmo / 2, metrics)
            if sharded is not None:
                table, epoch = sharded
                if table.num_published == table.num_maps:
                    with self._table_lock:
                        if self._table_gen == gen:
                            self.location_plane.put_table(shuffle_id,
                                                          table, epoch)
                return table, epoch
            # fall through: shard host lost/lagging — the driver is
            # authoritative
            if metrics is not None:
                metrics.record_shard_fallback()
            self.tracer.instant("meta.shard_fallback", "meta",
                                shuffle=shuffle_id)
        while True:
            remaining = deadline - time.monotonic()
            if metrics is not None:
                metrics.record_metadata_rpc()
            resp = self.driver.request(
                lambda c: M.FetchTableReq(
                    c.next_req_id(), shuffle_id,
                    min_published=expect_published,
                    timeout_ms=max(1, int(remaining * 1000))),
                timeout=max(0.05, remaining) + 5.0,  # grace over the
                # server-side hold so the sweeper answers before we give up
                deadline_s=max(0.05, remaining))
            assert isinstance(resp, M.FetchTableResp)
            if resp.num_published >= expect_published:
                table = DriverTable.from_bytes(resp.table)
                if table.num_published == table.num_maps:
                    with self._table_lock:
                        # memoize only if no invalidation raced this poll
                        # (recovery may have repaired the driver table
                        # after our response was cut)
                        if self._table_gen == gen:
                            self.location_plane.put_table(
                                shuffle_id, table, resp.epoch)
                return table, resp.epoch
            if resp.num_published < 0:
                # driver doesn't know the shuffle (unregistered mid-poll or
                # never registered): re-arming would spin, fail now
                raise TimeoutError(
                    f"shuffle {shuffle_id} is not registered at the driver")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"shuffle {shuffle_id}: only {resp.num_published}/"
                    f"{expect_published} map outputs published")
            # partial answer before the deadline (sweeper raced a publish
            # burst): re-arm the long-poll for the remaining budget

    def _fetch_table_sharded(self, shuffle_id: int, shard_map,
                             expect_published: int, deadline: float,
                             metrics=None
                             ) -> Optional[Tuple[DriverTable, int]]:
        """Assemble the driver table from shard-host replicas: one
        long-poll per shard (contiguous map ranges concatenate back into
        the positional table). Returns None on ANY shard miss — dead
        host, no replica, lagging count — and the caller falls back to
        the authoritative driver. The assembled epoch is the MINIMUM
        across shards: a lagging replica must make the view look older,
        never newer, so a pushed bump still invalidates it."""
        parts: List[bytes] = []
        total = 0
        epoch: Optional[int] = None
        for shard in range(shard_map.num_shards):
            lo, hi = shard_map.range_of(shard)
            # distribute the completeness expectation: a full-table
            # expectation holds each shard for its whole range; anything
            # lower (recovery's expect=0 probes) reads what's there
            min_pub = (hi - lo) if expect_published >= shard_map.num_maps \
                else 0
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                peer = self.member_at(shard_map.shard_slots[shard])
                conn = self._clients.get(peer.rpc_host, peer.rpc_port)
                if metrics is not None:
                    metrics.record_metadata_rpc()
                resp = conn.request(
                    M.FetchShardReq(conn.next_req_id(), shuffle_id, lo, hi,
                                    min_published=min_pub,
                                    timeout_ms=max(1, int(remaining * 1000))),
                    timeout=max(0.05, remaining) + 5.0)
            except (DeadExecutorError, IndexError, TransportError,
                    TimeoutError) as e:
                log.debug("shard %d of shuffle %d unreadable (%s); driver "
                          "fallback", shard, shuffle_id, e)
                return None
            if not isinstance(resp, M.FetchShardResp) \
                    or resp.num_published < min_pub \
                    or len(resp.table) != (hi - lo) * MAP_ENTRY_SIZE:
                return None
            parts.append(resp.table)
            total += resp.num_published
            epoch = resp.epoch if epoch is None else min(epoch, resp.epoch)
        if total < expect_published:
            return None
        return DriverTable.from_bytes(b"".join(parts)), epoch or 0

    def invalidate_shuffle(self, shuffle_id: int) -> None:
        """Drop every cached location view of the shuffle (stage recovery
        repaired it, or the shuffle unregistered; ids can be reused by
        the engine). Bumps the generation so an in-flight long-poll
        answered with the pre-invalidation table cannot re-memoize it,
        and drops the worker-process shuffle cache (mesh results + warm
        partition ranges) — stale bytes must not serve after a map
        recomputes."""
        with self._table_lock:
            self._table_gen += 1
        self.location_plane.invalidate(shuffle_id)
        from sparkrdma_tpu.shuffle import dist_cache
        dist_cache.drop(shuffle_id)

    def _failed_fetch(self, exc: TransportError) -> AsyncFetch:
        """An AsyncFetch that already failed (the dial threw before a
        request existed): issue paths stay non-raising so EVERY transport
        failure — connect refusal included — surfaces at ``result()``,
        where the fetcher's one retry envelope owns the policy."""
        from concurrent.futures import Future

        fut: Future = Future()
        fut.set_exception(exc)
        return AsyncFetch(fut, self.conf.resolved_request_deadline_s(),
                          lambda resp: resp)

    def fetch_output_range_async(self, peer: ShuffleManagerId,
                                 shuffle_id: int, map_id: int, start: int,
                                 end: int) -> AsyncFetch:
        """Issue one block-location read without waiting for it: the
        fetcher's read-ahead window keeps several of these in flight per
        peer over the pipelined connection."""
        try:
            conn = self._clients.get(peer.rpc_host, peer.rpc_port)
        except TransportError as e:
            return self._failed_fetch(e)
        fut = conn.request_async(
            M.FetchOutputReq(conn.next_req_id(), shuffle_id, map_id,
                             start, end))

        def complete(resp):
            assert isinstance(resp, M.FetchOutputResp)
            if resp.status != M.STATUS_OK:
                # the owner answered authoritatively: it does not have the
                # map/range the driver table promised — a refetch re-fails
                # identically, only a recompute heals it. CORRUPT is the
                # retryable demotion of at-rest rot (the bounded refetch
                # re-fails fast, then escalates with a corrupt_output
                # verdict into map re-execution); ERROR is the transient
                # serving class (verify-time disk hiccup) — same
                # semantics as the blocks path
                raise FetchStatusError(
                    "fetch_output", resp.status,
                    retryable=resp.status in (M.STATUS_ERROR,
                                              M.STATUS_CORRUPT))
            return MapTaskOutput.locations_from_range(resp.entries)

        return AsyncFetch(fut, self.conf.resolved_request_deadline_s(),
                          complete)

    def fetch_output_range(self, peer: ShuffleManagerId, shuffle_id: int,
                           map_id: int, start: int, end: int):
        return self.fetch_output_range_async(peer, shuffle_id, map_id,
                                             start, end).result()

    # One batched-location response stays well under the serving payload
    # cap; the client chunks its map list so even a 100k-map shuffle with a
    # wide reduce range asks in a few bounded requests, not one huge one.
    _MAX_OUTPUTS_BATCH_BYTES = 4 << 20

    def outputs_batch_maps(self, start: int, end: int) -> int:
        """How many maps one FetchOutputsReq may carry for this reduce
        range (entry bytes bounded by ``_MAX_OUTPUTS_BATCH_BYTES``)."""
        from sparkrdma_tpu.shuffle.map_output import ENTRY_SIZE

        span_bytes = max(1, (end - start) * ENTRY_SIZE)
        return max(1, self._MAX_OUTPUTS_BATCH_BYTES // span_bytes)

    def fetch_outputs_async(self, peer: ShuffleManagerId, shuffle_id: int,
                            map_ids, start: int, end: int) -> AsyncFetch:
        """Issue ONE batched location read covering many maps of one peer
        (the metadata half of the coalesced dataplane). ``result()``
        returns ``{map_id: [BlockLocation, ...]}``; any per-map non-OK
        status raises a non-retryable :class:`FetchStatusError` carrying
        ``map_id`` so the fetcher blames the right map (the owner
        answered authoritatively — only a recompute heals it)."""
        map_ids = list(map_ids)
        try:
            conn = self._clients.get(peer.rpc_host, peer.rpc_port)
        except TransportError as e:
            return self._failed_fetch(e)
        fut = conn.request_async(
            M.FetchOutputsReq(conn.next_req_id(), shuffle_id, map_ids,
                              start, end))

        def complete(resp):
            assert isinstance(resp, M.FetchOutputsResp)
            if resp.status != M.STATUS_OK:
                raise FetchStatusError("fetch_outputs", resp.status,
                                       retryable=False)
            out = {}
            for map_id, mstatus, entries in resp.records:
                if mstatus != M.STATUS_OK:
                    err = FetchStatusError(
                        f"fetch_outputs map {map_id}", mstatus,
                        retryable=mstatus in (M.STATUS_ERROR,
                                              M.STATUS_CORRUPT))
                    err.map_id = map_id
                    raise err
                out[map_id] = MapTaskOutput.locations_from_range(entries)
            missing = [m for m in map_ids if m not in out]
            if missing:
                # a malformed/short reply must not silently truncate the
                # reduce input; treat like a lost response (refetchable)
                raise TransportError(
                    f"fetch_outputs reply missing maps {missing[:4]}"
                    f"{'...' if len(missing) > 4 else ''}")
            return out

        return AsyncFetch(fut, self.conf.resolved_request_deadline_s(),
                          complete)

    def fetch_outputs(self, peer: ShuffleManagerId, shuffle_id: int,
                      map_ids, start: int, end: int):
        return self.fetch_outputs_async(peer, shuffle_id, map_ids,
                                        start, end).result()

    def _register_credit(self, conn: Connection,
                         req: "M.FetchBlocksReq", credited: bool) -> bool:
        """Receipt-credit accounting, issue half: remember the request's
        logical size BEFORE it hits the wire. The pending entry is keyed
        by (conn, req_id) so a response that arrives ORPHANED — our wait
        timed out but the server's send succeeded — still gets its
        report from the unsolicited-message path instead of leaking
        window forever. Native block-server responses aren't credited
        (``credited=False`` there; that path has its own caps)."""
        if not (credited and self.conf.sw_flow_control):
            return False
        total = sum(length for _, _, length in req.blocks)
        with self._fetch_credit_lock:
            self._fetch_credit_pending.setdefault(conn, {})[req.req_id] = \
                total
        return True

    def _settle_credit(self, conn: Connection, req: "M.FetchBlocksReq",
                       resp: RpcMsg) -> None:
        """Receipt-credit accounting, completion half: on an OK response
        report the logical size so the server's serving window
        replenishes (the server freed its copy the moment we have
        ours)."""
        with self._fetch_credit_lock:
            pending = self._fetch_credit_pending.get(conn, {}).pop(
                req.req_id, None)
        if pending is not None and resp.status == M.STATUS_OK:
            self._queue_credit_report(conn, pending)

    def _queue_credit_report(self, conn: Connection, total: int) -> None:
        """Hand a CreditReport send to the dedicated worker so the
        callers — connection reader threads via the receipt-time settle
        and orphan paths — can never block in ``sendall`` when both TCP
        directions are full; a blocked reader would stop draining the
        very responses whose receipt replenishes the window."""
        if self._credit_worker is None:
            with self._credit_worker_lock:
                if self._credit_worker is None and not self._stopping:
                    self._credit_worker = threading.Thread(
                        target=self._credit_loop, daemon=True,
                        name=f"credit-"
                             f"{self.manager_id.executor_id.executor}")
                    self._credit_worker.start()
        self._credit_q.put((conn, total))

    def _credit_loop(self) -> None:
        while True:
            item = self._credit_q.get()
            if item is None:
                return
            conn, total = item
            try:
                conn.send(M.CreditReport(total))
            except TransportError:
                pass  # conn died post-response; server releases on its own

    def _drop_credit(self, conn: Connection,
                     req: "M.FetchBlocksReq") -> None:
        """The connection died mid-request: no orphan will ever arrive,
        and the server releases on its own failed send."""
        with self._fetch_credit_lock:
            self._fetch_credit_pending.get(conn, {}).pop(req.req_id, None)

    def _credited_request(self, conn: Connection,
                          req: "M.FetchBlocksReq", credited: bool) -> RpcMsg:
        """``conn.request`` with receipt-credit accounting (see
        ``_register_credit``/``_settle_credit``). A TIMEOUT leaves the
        pending entry in place on purpose — the orphan path owns it."""
        registered = self._register_credit(conn, req, credited)
        try:
            resp = conn.request(req)
        except TransportError:
            if registered:
                self._drop_credit(conn, req)
            raise
        if registered:
            self._settle_credit(conn, req, resp)
        return resp

    def _on_orphan_blocks_resp(self, conn: Connection,
                               msg: "M.FetchBlocksResp") -> None:
        """A data response whose requester gave up waiting: its Future is
        gone, but the server is still holding window for it — report the
        credits it carried."""
        with self._fetch_credit_lock:
            total = self._fetch_credit_pending.get(conn, {}).pop(
                msg.req_id, None)
        if total is not None and msg.status == M.STATUS_OK:
            self._queue_credit_report(conn, total)

    def fetch_blocks_async(self, peer: ShuffleManagerId, shuffle_id: int,
                           blocks) -> AsyncFetch:
        """Issue one grouped data fetch without waiting for it — the
        measured fetch fast path. The request multiplexes onto the shared
        pipelined connection by req_id; the returned handle's
        ``result()`` settles credits, handles the native-server size-cap
        retry, and decodes, all on the calling (peer fetch) thread.

        Prefers the peer's native block server when advertised: same wire
        protocol, no Python on the serving side. The native server
        doesn't compress or wrap, so when wire compression or a wire
        codec is configured stay on the control path which does."""
        blocks = list(blocks)
        port = (peer.block_port
                if peer.block_port and not self.conf.wire_compress
                and self._codec is None
                else peer.rpc_port)
        try:
            conn = self._clients.get(peer.rpc_host, port)
        except TransportError as e:
            return self._failed_fetch(e)
        req = M.FetchBlocksReq(conn.next_req_id(), shuffle_id, blocks)
        registered = self._register_credit(conn, req,
                                           credited=port == peer.rpc_port)
        fut = conn.request_async(req)
        if registered:
            # CreditReport ON RECEIPT (reader thread), not at completion:
            # a read-ahead window completes oldest-issued-first, but the
            # server may serve out of order — landed-but-uncompleted
            # responses must replenish the window immediately or a parked
            # older response could deadlock against its own window until
            # the park timeout. (The orphan path already reports from the
            # reader thread for the same reason.)
            def _on_wire(f) -> None:
                if f.cancelled():
                    return  # orphan path owns the pending entry
                exc = f.exception()
                if exc is not None:
                    if isinstance(exc, TransportError):
                        # dead connection: no orphan will ever arrive
                        self._drop_credit(conn, req)
                    return
                self._settle_credit(conn, req, f.result())

            fut.add_done_callback(_on_wire)

        def complete(resp):
            assert isinstance(resp, M.FetchBlocksResp)
            final_req = req
            if resp.status == M.STATUS_BAD_RANGE and port != peer.rpc_port:
                # only the size-cap case is worth retrying: the native
                # server enforces a stricter response-size cap than the
                # Python path. Other statuses (unknown token/shuffle)
                # would fail identically on the control connection —
                # retrying would just double the failure-path load during
                # an executor-loss storm
                rconn = self._clients.get(peer.rpc_host, peer.rpc_port)
                final_req = M.FetchBlocksReq(rconn.next_req_id(),
                                             shuffle_id, blocks)
                resp = self._credited_request(rconn, final_req,
                                              credited=True)
                assert isinstance(resp, M.FetchBlocksResp)
            if resp.status != M.STATUS_OK:
                # STATUS_ERROR is the transient class (credit-window
                # expiry under a stalled consumer, serving hiccup) — a
                # refetch usually heals it; STATUS_CORRUPT retries within
                # the same budget then escalates with a corrupt_output
                # verdict (at-rest rot heals only by re-execution);
                # unknown-token/shuffle and bad-range answers are
                # authoritative re-failures
                raise FetchStatusError(
                    "fetch_blocks", resp.status,
                    retryable=resp.status in (M.STATUS_ERROR,
                                              M.STATUS_CORRUPT))
            return self._decode_blocks_resp(final_req, resp)

        return AsyncFetch(fut, self.conf.resolved_request_deadline_s(),
                          complete)

    def fetch_blocks(self, peer: ShuffleManagerId, shuffle_id: int,
                     blocks) -> bytes:
        return self.fetch_blocks_async(peer, shuffle_id, blocks).result()

    def _decode_blocks_resp(self, req: "M.FetchBlocksReq",
                            resp: "M.FetchBlocksResp") -> bytes:
        with self._wire_lock:
            self.wire_bytes_in += len(resp.data)
        data = resp.data
        if self._codec is not None and not (resp.flags & M.FLAG_WRAPPED):
            # a stripped FLAG_WRAPPED must not downgrade the channel to
            # accepting unauthenticated bytes
            raise TransportError(
                "peer sent an unwrapped payload but wire_codec is "
                "configured (downgrade or peer config drift)")
        if resp.flags & M.FLAG_WRAPPED:
            from sparkrdma_tpu.utils.codecs import CodecError
            if self._codec is None:
                raise TransportError(
                    "peer wrapped the payload but no wire_codec is "
                    "configured here")
            try:
                data = self._codec.unwrap(data, self._codec_key,
                                          _codec_aad(req, resp.flags))
            except CodecError as e:
                raise TransportError(f"fetch_blocks unwrap failed: {e}") from e
        if resp.flags & M.FLAG_ZLIB:
            import zlib
            try:
                data = zlib.decompress(data)
            except zlib.error as e:
                # a wire bit-flip lands here on compressed payloads; the
                # retryable-checksum class routes it into the bounded
                # refetch path like an uncompressed CRC mismatch
                raise ChecksumError(
                    f"fetch_blocks payload failed to decompress: {e}") from e
        if resp.flags & M.FLAG_CRC32:
            data = self._verify_block_crcs(req, data)
        return data

    def _verify_block_crcs(self, req: "M.FetchBlocksReq",
                           data: bytes) -> bytes:
        """Check and strip the per-block CRC32 trailer. Block lengths come
        from the REQUEST (both sides derive the layout independently —
        the trailer can't lie about where blocks start). Raises the
        retryable :class:`ChecksumError`; every block is checked (not
        fail-fast) so the error carries the FULL list of bad block
        indices plus the stripped body — a vectored fetch salvages the
        clean sub-ranges and refetches only the corrupt ones, blaming the
        map that owns them."""
        import struct
        import zlib
        n = len(req.blocks)
        lengths = [length for _, _, length in req.blocks]
        body_len = len(data) - 4 * n
        if body_len != sum(lengths):
            self.checksum_failures += 1
            raise ChecksumError(
                f"fetch_blocks payload size mismatch: {body_len} data "
                f"bytes for {sum(lengths)} requested")
        crcs = struct.unpack_from(f"<{n}I", data, body_len)
        body = memoryview(data)[:body_len]
        bad = []
        pos = 0
        for i, length in enumerate(lengths):
            if zlib.crc32(body[pos:pos + length]) != crcs[i]:
                bad.append(i)
            pos += length
        if bad:
            self.checksum_failures += len(bad)
            raise ChecksumError(
                f"fetch_blocks blocks {bad[:8]}"
                f"{'...' if len(bad) > 8 else ''} of {n} failed CRC32 "
                f"(corruption in flight or at the server)",
                bad_blocks=bad, body=bytes(body))
        return bytes(body)
