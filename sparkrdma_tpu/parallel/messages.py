"""Control-plane message set beyond hello/announce.

The reference needs only two RPC types because everything else is one-sided
RDMA (scala/RdmaRpcMsg.scala:29-32). Without a NIC to do one-sided reads,
the TPU control plane carries those flows as explicit messages — but they
remain exactly the reference's three-level scheme:

* ``PublishMsg``      — the 12-byte driver-table entry WRITE at
                        ``map_id * MAP_ENTRY_SIZE``
                        (scala/RdmaShuffleManager.scala:384-418).
* ``FetchTableReq/Resp`` — the whole-driver-table READ, once per
                        (shuffle, executor) (scala/RdmaShuffleManager.scala:341-376).
* ``FetchOutputReq/Resp`` — the per-(map, reduce-range) block-location READ
                        of 16-byte entries out of the owning executor
                        (scala/RdmaShuffleFetcherIterator.scala:293-315).
* ``FetchOutputsReq/Resp`` — the batched form: ONE request returns the
                        16-byte location entries of MANY maps' output
                        tables for one reduce range — O(peers) instead of
                        O(maps) metadata round trips, the role the
                        reference's fetch-a-peer's-whole-address-table-once
                        plays (scala/RdmaShuffleManager.scala:341-376).
                        The per-map form stays as the mixed-version
                        fallback.
* ``FetchBlocksReq/Resp`` — the scatter data READ (DCN fallback path; on-mesh
                        traffic rides the ICI ragged all-to-all instead)
                        (scala/RdmaShuffleFetcherIterator.scala:119-180).
                        The block list may span different maps and buffer
                        tokens — one VECTORED request per coalesced window
                        of cross-map ranges; both the Python and native
                        servers gather the ranges in request order into a
                        single response with a per-sub-block CRC32 trailer.

The METADATA PLANE (shuffle/location_plane.py) adds the one-sided
publication frames that remove the request/reply cycle from warm-path
location resolution ("RPC Considered Harmful", PAPERS.md):

* ``EpochBumpMsg``     — driver -> executors push: shuffle S's location
                        state is now version E (or gone, E = EPOCH_DEAD).
                        Rides the same broadcast channel as announces, so
                        invalidation is pushed, never polled.
* ``ShardMapMsg``      — driver -> executors push at registerShuffle: the
                        map-range -> shard-host assignment, so a reducer
                        knows whom to ask without a driver round trip.
* ``ShardEntryMsg``    — driver -> shard host: one applied driver-table
                        entry forwarded into the host's shard replica (the
                        positional WRITE of the reference, re-aimed at a
                        shard host instead of the one driver table).
* ``FetchShardReq/Resp`` — reducer -> shard host: long-poll read of one
                        driver-table map-range out of the shard replica —
                        thousand-reducer fan-in spreads over shard hosts
                        instead of serializing on the driver endpoint.

All carry a ``req_id`` echo so clients can pipeline requests per connection
the way the reference pipelines work requests on a QP.
"""

from __future__ import annotations

import struct
from typing import List, Tuple

from sparkrdma_tpu.parallel.rpc_msg import RpcMsg, register

_QIII = struct.Struct("<qiii")
_QI = struct.Struct("<qi")
_Q = struct.Struct("<q")
_BLOCK = struct.Struct("<IQI")  # (buf token, offset, length)

# Native block-server request-frame geometry, mirrored from
# csrc/blockserver.cpp so Python-side request planning can be DERIVED from
# the C++ limit instead of hardcoding a constant that silently drifts
# (tests/test_fetch_coalesced.py greps the .cpp to keep them in lockstep):
#   kMaxReqFrame — hard cap on one inbound frame on the data port;
#   frame layout — [total:4][type:4][req_id:8][shuffle:4][count:4][blocks].
NATIVE_MAX_REQ_FRAME = 1 << 20          # csrc/blockserver.cpp kMaxReqFrame
BLOCKS_REQ_FIXED_BYTES = 8 + _QI.size + 4   # header + req_id/shuffle + count
BLOCK_WIRE_BYTES = _BLOCK.size          # one (buf, offset, length) range
# Response-frame fixed prefix (csrc/fetchclient.cpp kRespFixedBytes): the
# native CLIENT parses [total:4][type:4][req_id:8][status:4][flags:4]
# before scattering the payload into lease memory.
BLOCKS_RESP_FIXED_BYTES = 8 + _QI.size + 4  # header + req_id/status + flags


@register()
class PublishMsg(RpcMsg):
    """Executor -> driver: positional driver-table entry write.

    ``fence`` is the committing attempt's fencing token: the driver
    rejects a publish whose fence is older than the one already applied
    for the same (map, executor), so a zombie speculative attempt that
    commits late cannot clobber the winner's location entry. Appended
    after the fixed 12-byte entry; a fence-less (pre-fencing) payload
    decodes with fence 0, which never out-fences anything.

    ``lengths`` (adaptive reduce planning, shuffle/planner.py) is the
    map output's per-partition byte sizes — the u32 "length" column of
    its MapTaskOutput table, which the writer already has in hand at
    commit. Appended after the fence as ``count:u32 + u32[count]`` so
    the driver can aggregate a SizeHistogram without any extra round
    trip; omitted (count absent) when ``adaptive_plan`` is off, and a
    pre-planning payload decodes with ``lengths=None``."""

    ENTRY_BYTES = 12

    def __init__(self, shuffle_id: int, map_id: int, entry: bytes,
                 fence: int = 0, lengths=None):
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.entry = entry
        self.fence = fence
        self.lengths = list(lengths) if lengths is not None else None

    def payload(self) -> bytes:
        out = (struct.pack("<ii", self.shuffle_id, self.map_id)
               + self.entry + struct.pack("<q", self.fence))
        if self.lengths is not None:
            out += struct.pack(f"<I{len(self.lengths)}I",
                               len(self.lengths), *self.lengths)
        return out

    @classmethod
    def from_payload(cls, payload: bytes) -> "PublishMsg":
        shuffle_id, map_id = struct.unpack_from("<ii", payload, 0)
        entry = payload[8:8 + cls.ENTRY_BYTES]
        fence = 0
        lengths = None
        off = 8 + cls.ENTRY_BYTES
        if len(payload) >= off + 8:
            (fence,) = struct.unpack_from("<q", payload, off)
            off += 8
        if len(payload) >= off + 4:
            (n,) = struct.unpack_from("<I", payload, off)
            if len(payload) >= off + 4 + 4 * n:
                lengths = list(struct.unpack_from(f"<{n}I", payload,
                                                  off + 4))
        return cls(shuffle_id, map_id, entry, fence, lengths)


# Wire type 4 reserved — see rpc_msg.RESERVED_WIRE_IDS (was an ack;
# publish is one-sided like the reference's RDMA WRITE, so nothing acks).


@register()
class FetchTableReq(RpcMsg):
    """``min_published > 0`` turns the fetch into a long-poll: the driver
    holds the response until that many maps have published (or
    ``timeout_ms`` passes, answering with the partial table) — one
    request per reducer instead of a poll loop against the driver, the
    role the reference's known-complete one-sided READ plays
    (scala/RdmaShuffleManager.scala:341-376)."""

    def __init__(self, req_id: int, shuffle_id: int,
                 min_published: int = 0, timeout_ms: int = 0):
        self.req_id = req_id
        self.shuffle_id = shuffle_id
        self.min_published = min_published
        self.timeout_ms = timeout_ms

    def payload(self) -> bytes:
        return (_QI.pack(self.req_id, self.shuffle_id)
                + struct.pack("<ii", self.min_published, self.timeout_ms))

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchTableReq":
        req_id, shuffle_id = _QI.unpack_from(payload, 0)
        min_published, timeout_ms = struct.unpack_from("<ii", payload,
                                                       _QI.size)
        return cls(req_id, shuffle_id, min_published, timeout_ms)


@register()
class FetchTableResp(RpcMsg):
    """num_published lets clients poll until the maps they need have
    committed (client-side analogue of the reference's wait on
    partitionLocationFetchTimeout). ``epoch`` stamps the table bytes with
    the shuffle's location-state version (location_plane): a reducer
    caches the table under this epoch and serves later supersteps from
    the cache until an ``EpochBumpMsg`` invalidates it."""

    def __init__(self, req_id: int, num_published: int, table: bytes,
                 epoch: int = 0):
        self.req_id = req_id
        self.num_published = num_published
        self.table = table
        self.epoch = epoch

    def payload(self) -> bytes:
        return (_QI.pack(self.req_id, self.num_published)
                + _Q.pack(self.epoch) + self.table)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchTableResp":
        req_id, num_published = _QI.unpack_from(payload, 0)
        rest = payload[_QI.size:]
        # Mixed-version tolerance: a pre-metadata-plane peer sends no
        # epoch field. The table is whole MAP_ENTRY_SIZE (12-byte)
        # driver-table entries, so the i64 epoch's presence is decidable
        # from the length residue: 8 mod 12 when it leads, 0 mod 12 when
        # it does not. A legacy payload decodes with epoch 0, which
        # never validates a cache entry — staleness costs a re-sync,
        # never correctness.
        epoch = 0
        if len(rest) % PublishMsg.ENTRY_BYTES == _Q.size:
            (epoch,) = _Q.unpack_from(rest, 0)
            rest = rest[_Q.size:]
        return cls(req_id, num_published, bytes(rest), epoch)


@register()
class FetchOutputReq(RpcMsg):
    """Read 16B location entries [start, end) of one map's output table."""

    def __init__(self, req_id: int, shuffle_id: int, map_id: int,
                 start_partition: int, end_partition: int):
        self.req_id = req_id
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.start_partition = start_partition
        self.end_partition = end_partition

    def payload(self) -> bytes:
        return _QIII.pack(self.req_id, self.shuffle_id, self.map_id,
                          self.start_partition) + struct.pack("<i", self.end_partition)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchOutputReq":
        req_id, shuffle_id, map_id, start = _QIII.unpack_from(payload, 0)
        (end,) = struct.unpack_from("<i", payload, _QIII.size)
        return cls(req_id, shuffle_id, map_id, start, end)


@register()
class FetchOutputResp(RpcMsg):
    def __init__(self, req_id: int, status: int, entries: bytes):
        self.req_id = req_id
        self.status = status
        self.entries = entries

    def payload(self) -> bytes:
        return _QI.pack(self.req_id, self.status) + self.entries

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchOutputResp":
        req_id, status = _QI.unpack_from(payload, 0)
        return cls(req_id, status, payload[_QI.size:])


@register()
class FetchBlocksReq(RpcMsg):
    """Scatter-read: list of (buf token, offset, length) to pack in order."""

    def __init__(self, req_id: int, shuffle_id: int,
                 blocks: List[Tuple[int, int, int]]):
        self.req_id = req_id
        self.shuffle_id = shuffle_id
        self.blocks = list(blocks)

    def payload(self) -> bytes:
        head = _QI.pack(self.req_id, self.shuffle_id)
        body = b"".join(_BLOCK.pack(t, o, ln) for t, o, ln in self.blocks)
        return head + struct.pack("<I", len(self.blocks)) + body

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchBlocksReq":
        req_id, shuffle_id = _QI.unpack_from(payload, 0)
        off = _QI.size
        (n,) = struct.unpack_from("<I", payload, off)
        off += 4
        blocks = []
        for _ in range(n):
            t, o, ln = _BLOCK.unpack_from(payload, off)
            off += _BLOCK.size
            blocks.append((t, o, ln))
        return cls(req_id, shuffle_id, blocks)


FLAG_ZLIB = 1     # FetchBlocksResp.flags: payload is zlib-compressed
FLAG_WRAPPED = 2  # payload passed through the configured wire codec
                  # (utils/codecs.py; applied after compression, so
                  # readers unwrap first)
FLAG_CRC32 = 4    # the logical payload carries a trailer of one
                  # little-endian u32 CRC32 per requested block, appended
                  # BEFORE compression/codec so the check is end-to-end
                  # (server read -> client consume). Readers verify and
                  # strip; both the Python responder and the native block
                  # server (bs_set_checksum) set it, and a responder that
                  # can't checksum simply doesn't set the flag. Per-BLOCK
                  # granularity is what lets a vectored (cross-map) read
                  # isolate a corrupt sub-range to one map and refetch
                  # only the affected ranges.

_QII = struct.Struct("<qii")


@register()
class FetchBlocksResp(RpcMsg):
    def __init__(self, req_id: int, status: int, data: bytes, flags: int = 0):
        self.req_id = req_id
        self.status = status
        self.data = data
        self.flags = flags

    def payload(self) -> bytes:
        return _QII.pack(self.req_id, self.status, self.flags) + self.data

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchBlocksResp":
        req_id, status, flags = _QII.unpack_from(payload, 0)
        return cls(req_id, status, payload[_QII.size:], flags)


@register()
class RunTaskReq(RpcMsg):
    """Ship one serialized task to an executor (the role Spark's task
    scheduler plays for the reference: tasks arrive at executors with the
    shuffle handle in their closure, scala/RdmaUtils.scala:145-159).
    Payload is an opaque serialized descriptor (engine-defined)."""

    def __init__(self, req_id: int, payload: bytes):
        self.req_id = req_id
        self.data = payload

    def payload(self) -> bytes:
        return struct.pack("<q", self.req_id) + self.data

    @classmethod
    def from_payload(cls, payload: bytes) -> "RunTaskReq":
        (req_id,) = struct.unpack_from("<q", payload, 0)
        return cls(req_id, payload[8:])


@register()
class RunTaskResp(RpcMsg):
    """status: TASK_OK / TASK_ERROR / TASK_FETCH_FAILED; payload is the
    serialized result or error detail."""

    def __init__(self, req_id: int, status: int, payload: bytes):
        self.req_id = req_id
        self.status = status
        self.data = payload

    def payload(self) -> bytes:
        return struct.pack("<qi", self.req_id, self.status) + self.data

    @classmethod
    def from_payload(cls, payload: bytes) -> "RunTaskResp":
        req_id, status = struct.unpack_from("<qi", payload, 0)
        return cls(req_id, status, payload[12:])


@register()
class CreditReport(RpcMsg):
    """Reader -> server: ``consumed`` logical response bytes were drained
    by the consumer — replenish that much of this connection's serving
    credit window. The receiver-driven half of flow control: the server
    reserves a response's logical size from the window before building it
    and PARKS when the window is exhausted, so a stalled consumer bounds
    the server's queued response bytes instead of growing them
    (java/RdmaChannel.java:61-64, 744-787 — credits granted by recv queue
    depth, replenished by credit reports every recvDepth/8 reclaims)."""

    def __init__(self, consumed: int):
        self.consumed = consumed

    def payload(self) -> bytes:
        return _Q.pack(self.consumed)

    @classmethod
    def from_payload(cls, payload: bytes) -> "CreditReport":
        (consumed,) = _Q.unpack_from(payload, 0)
        return cls(consumed)


@register()
class GetBroadcastReq(RpcMsg):
    """Executor -> driver: fetch a broadcast blob by id (the delivery
    half of shared_vars.Broadcast — once per executor PROCESS, cached
    there, so N tasks cost one transfer like Spark's TorrentBroadcast
    costs one fetch per executor)."""

    def __init__(self, req_id: int, bcast_id: int):
        self.req_id = req_id
        self.bcast_id = bcast_id

    def payload(self) -> bytes:
        return struct.pack("<qq", self.req_id, self.bcast_id)

    @classmethod
    def from_payload(cls, payload: bytes) -> "GetBroadcastReq":
        req_id, bcast_id = struct.unpack_from("<qq", payload, 0)
        return cls(req_id, bcast_id)


@register()
class GetBroadcastResp(RpcMsg):
    """status STATUS_OK with the pickled blob, or STATUS_ERROR when the
    id is unknown (unpersisted or never registered)."""

    def __init__(self, req_id: int, status: int, data: bytes):
        self.req_id = req_id
        self.status = status
        self.data = data

    def payload(self) -> bytes:
        return struct.pack("<qi", self.req_id, self.status) + self.data

    @classmethod
    def from_payload(cls, payload: bytes) -> "GetBroadcastResp":
        req_id, status = struct.unpack_from("<qi", payload, 0)
        return cls(req_id, status, payload[12:])


@register()
class PingMsg(RpcMsg):
    """Peer-health probe (endpoint heartbeat monitor): carries a
    ``req_id`` so it rides the same ``request_async`` pipelining as
    fetches — a pong is just the echoed completion. Deliberately tiny:
    the monitor's cost must stay negligible next to data traffic."""

    def __init__(self, req_id: int):
        self.req_id = req_id

    def payload(self) -> bytes:
        return _Q.pack(self.req_id)

    @classmethod
    def from_payload(cls, payload: bytes) -> "PingMsg":
        (req_id,) = _Q.unpack_from(payload, 0)
        return cls(req_id)


@register()
class PongMsg(RpcMsg):
    """Echoed heartbeat completion."""

    def __init__(self, req_id: int):
        self.req_id = req_id

    def payload(self) -> bytes:
        return _Q.pack(self.req_id)

    @classmethod
    def from_payload(cls, payload: bytes) -> "PongMsg":
        (req_id,) = _Q.unpack_from(payload, 0)
        return cls(req_id)


@register()
class FetchOutputsReq(RpcMsg):
    """Batched block-location read: the 16B entries [start, end) of MANY
    maps' output tables in one round trip (one per (shuffle, peer) for
    reducers with coalesced reads on — the metadata half of the RPC-count
    reduction). ``map_ids`` is explicit rather than a range: a reducer
    only asks for the maps the driver table routed to this peer."""

    def __init__(self, req_id: int, shuffle_id: int, map_ids: List[int],
                 start_partition: int, end_partition: int):
        self.req_id = req_id
        self.shuffle_id = shuffle_id
        self.map_ids = list(map_ids)
        self.start_partition = start_partition
        self.end_partition = end_partition

    def payload(self) -> bytes:
        head = (_QIII.pack(self.req_id, self.shuffle_id,
                           self.start_partition, self.end_partition)
                + struct.pack("<I", len(self.map_ids)))
        return head + struct.pack(f"<{len(self.map_ids)}i", *self.map_ids)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchOutputsReq":
        req_id, shuffle_id, start, end = _QIII.unpack_from(payload, 0)
        (n,) = struct.unpack_from("<I", payload, _QIII.size)
        map_ids = list(struct.unpack_from(f"<{n}i", payload, _QIII.size + 4))
        return cls(req_id, shuffle_id, map_ids, start, end)


@register()
class FetchOutputsResp(RpcMsg):
    """Per-map records ``(map_id, status, entries)`` in request order.
    ``status`` is the overall verdict (a non-OK overall status carries no
    records); per-map statuses let one unknown map answer authoritatively
    without hiding the other maps' entries."""

    def __init__(self, req_id: int, status: int,
                 records: List[Tuple[int, int, bytes]]):
        self.req_id = req_id
        self.status = status
        self.records = list(records)

    def payload(self) -> bytes:
        out = [_QI.pack(self.req_id, self.status),
               struct.pack("<I", len(self.records))]
        for map_id, status, entries in self.records:
            out.append(struct.pack("<iiI", map_id, status, len(entries)))
            out.append(entries)
        return b"".join(out)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchOutputsResp":
        req_id, status = _QI.unpack_from(payload, 0)
        off = _QI.size
        (n,) = struct.unpack_from("<I", payload, off)
        off += 4
        records = []
        for _ in range(n):
            map_id, mstatus, nbytes = struct.unpack_from("<iiI", payload, off)
            off += 12
            records.append((map_id, mstatus, payload[off:off + nbytes]))
            off += nbytes
        return cls(req_id, status, records)


# Epoch sentinel: the shuffle is unregistered — caches drop their state
# entirely instead of re-validating against a version that will never
# exist again.
EPOCH_DEAD = -1


@register()
class EpochBumpMsg(RpcMsg):
    """Driver -> executors push: shuffle ``shuffle_id``'s location state
    is now version ``epoch`` (monotone per shuffle; ``EPOCH_DEAD`` =
    unregistered). Sent on the announce/broadcast channel whenever the
    driver table is REPAIRED (re-execution overwrote an entry), an
    executor is tombstoned, or the shuffle unregisters — the push that
    replaces cache-TTL polling (invalidation is an event, not a timer).
    One-sided like a publish: no reply, problems observable driver-side
    only; a lost push is backstopped by the fetch-failure path (a stale
    location fails its fetch, which invalidates the cache the hard
    way)."""

    def __init__(self, shuffle_id: int, epoch: int):
        self.shuffle_id = shuffle_id
        self.epoch = epoch

    def payload(self) -> bytes:
        return struct.pack("<iq", self.shuffle_id, self.epoch)

    @classmethod
    def from_payload(cls, payload: bytes) -> "EpochBumpMsg":
        shuffle_id, epoch = struct.unpack_from("<iq", payload, 0)
        return cls(shuffle_id, epoch)


@register()
class ShardMapMsg(RpcMsg):
    """Driver -> executors push at registerShuffle time: the map-range ->
    shard-host assignment for one shuffle (location_plane.ShardMap wire
    form). Reducers use it to aim cold-path table reads at shard hosts
    instead of the driver; executors that never receive it (late
    joiners) simply stay on the driver path — the shard plane is an
    optimization, the driver remains authoritative."""

    def __init__(self, shuffle_id: int, epoch: int, num_maps: int,
                 shard_slots: List[int]):
        self.shuffle_id = shuffle_id
        self.epoch = epoch
        self.num_maps = num_maps
        self.shard_slots = list(shard_slots)

    def payload(self) -> bytes:
        head = struct.pack("<iqiI", self.shuffle_id, self.epoch,
                           self.num_maps, len(self.shard_slots))
        return head + struct.pack(f"<{len(self.shard_slots)}i",
                                  *self.shard_slots)

    @classmethod
    def from_payload(cls, payload: bytes) -> "ShardMapMsg":
        shuffle_id, epoch, num_maps, n = struct.unpack_from("<iqiI",
                                                            payload, 0)
        slots = list(struct.unpack_from(f"<{n}i", payload, 20))
        return cls(shuffle_id, epoch, num_maps, slots)


@register()
class ShardEntryMsg(RpcMsg):
    """Driver -> shard host: one APPLIED driver-table entry forwarded
    into the host's shard replica (the driver stays the fencing
    authority — only publishes that survived the fence CAS are
    forwarded, so replicas can never serve a zombie attempt's
    location). One-sided, no reply; ``num_maps`` lets the replica answer
    shard completeness without ever having seen the ShardMapMsg."""

    def __init__(self, shuffle_id: int, epoch: int, map_id: int,
                 num_maps: int, entry: bytes):
        self.shuffle_id = shuffle_id
        self.epoch = epoch
        self.map_id = map_id
        self.num_maps = num_maps
        self.entry = entry

    def payload(self) -> bytes:
        return struct.pack("<iqii", self.shuffle_id, self.epoch,
                           self.map_id, self.num_maps) + self.entry

    @classmethod
    def from_payload(cls, payload: bytes) -> "ShardEntryMsg":
        shuffle_id, epoch, map_id, num_maps = struct.unpack_from(
            "<iqii", payload, 0)
        return cls(shuffle_id, epoch, map_id, num_maps, payload[20:])


@register()
class FetchShardReq(RpcMsg):
    """Reducer -> shard host: long-poll read of driver-table entries
    [map_lo, map_hi) out of the host's shard replica. Same long-poll
    contract as ``FetchTableReq`` (``min_published`` counts published
    maps WITHIN the range; ``timeout_ms`` bounds the hold) so a reducer
    syncs each shard with one request instead of polling — and the
    thousand-reducer fan-in lands on shard hosts, not the driver."""

    def __init__(self, req_id: int, shuffle_id: int, map_lo: int,
                 map_hi: int, min_published: int = 0, timeout_ms: int = 0):
        self.req_id = req_id
        self.shuffle_id = shuffle_id
        self.map_lo = map_lo
        self.map_hi = map_hi
        self.min_published = min_published
        self.timeout_ms = timeout_ms

    def payload(self) -> bytes:
        return (_QI.pack(self.req_id, self.shuffle_id)
                + struct.pack("<iiii", self.map_lo, self.map_hi,
                              self.min_published, self.timeout_ms))

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchShardReq":
        req_id, shuffle_id = _QI.unpack_from(payload, 0)
        map_lo, map_hi, min_published, timeout_ms = struct.unpack_from(
            "<iiii", payload, _QI.size)
        return cls(req_id, shuffle_id, map_lo, map_hi, min_published,
                   timeout_ms)


@register()
class FetchShardResp(RpcMsg):
    """``num_published`` counts published maps within the requested
    range (-1 = the host holds no replica for the shuffle — the client
    falls back to the driver); ``table`` is the range's MAP_ENTRY_SIZE
    entries in map order, UNPUBLISHED-filled where nothing has been
    forwarded yet; ``epoch`` stamps the replica's version."""

    def __init__(self, req_id: int, num_published: int, epoch: int,
                 table: bytes):
        self.req_id = req_id
        self.num_published = num_published
        self.epoch = epoch
        self.table = table

    def payload(self) -> bytes:
        return (_QI.pack(self.req_id, self.num_published)
                + _Q.pack(self.epoch) + self.table)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchShardResp":
        req_id, num_published = _QI.unpack_from(payload, 0)
        (epoch,) = _Q.unpack_from(payload, _QI.size)
        return cls(req_id, num_published, epoch,
                   payload[_QI.size + _Q.size:])


@register()
class ReducePlanMsg(RpcMsg):
    """Driver -> executors push: the shuffle's reduce plan (adaptive
    skew-aware planning, shuffle/planner.py) — an epoch-stamped,
    one-sided, driver-published artifact like the location tables it
    rides beside. Pushed at plan build and on every mid-stage re-plan
    (bumped ``plan_epoch``); reducers cache it in their LocationPlane
    and resolve cache-first. A lost push is backstopped by the pull
    path (``FetchPlanReq``). ``payload`` is ``ReducePlan.to_bytes()``."""

    def __init__(self, plan_bytes: bytes):
        self.plan_bytes = plan_bytes

    def payload(self) -> bytes:
        return self.plan_bytes

    @classmethod
    def from_payload(cls, payload: bytes) -> "ReducePlanMsg":
        return cls(payload)


@register()
class FetchPlanReq(RpcMsg):
    """Reducer -> driver: pull one shuffle's current reduce plan (the
    cold path / lost-push backstop of ``ReducePlanMsg``)."""

    def __init__(self, req_id: int, shuffle_id: int):
        self.req_id = req_id
        self.shuffle_id = shuffle_id

    def payload(self) -> bytes:
        return _QI.pack(self.req_id, self.shuffle_id)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchPlanReq":
        req_id, shuffle_id = _QI.unpack_from(payload, 0)
        return cls(req_id, shuffle_id)


@register()
class FetchPlanResp(RpcMsg):
    """``STATUS_OK`` with the plan bytes; ``STATUS_ERROR`` when the
    driver holds no plan (adaptive planning off, or the map stage has
    not completed) — the reducer falls back to the identity plan;
    ``STATUS_UNKNOWN_SHUFFLE`` when the shuffle is unregistered."""

    def __init__(self, req_id: int, status: int, plan_bytes: bytes):
        self.req_id = req_id
        self.status = status
        self.plan_bytes = plan_bytes

    def payload(self) -> bytes:
        return _QI.pack(self.req_id, self.status) + self.plan_bytes

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchPlanResp":
        req_id, status = _QI.unpack_from(payload, 0)
        return cls(req_id, status, payload[_QI.size:])


# -- push-merge dataplane (shuffle/push_merge.py) -------------------------
#
# Magnet-style background merge: committed map outputs are PUSHED to K
# peer executors chosen by partition-range, each appending into a
# per-(shuffle, partition) segment file with a per-block CRC+fence
# ledger; finalized segments publish one-sided into the driver's merged
# directory and are served by the EXISTING block server (one vectored
# read per partition, no extra server CPU in the read path — the
# one-sided discipline of "RPC Considered Harmful"), with pushes riding
# the same line-rate framing as every other data frame (Tiara,
# PAPERS.md). Reducers resolve merged-segment-first and fall back
# per-map; recovery re-points to a replica instead of re-executing.

PUSH_KIND_MERGE = 0     # per-partition blocks into merged segments
PUSH_KIND_OVERFLOW = 1  # tiered-spill overflow blob (fetched back at merge)
PUSH_KIND_DRAIN = 2     # drain re-push: like MERGE, but may REOPEN an
#                         already-finalized segment (the driver
#                         re-finalizes after the drainee's DrainResp)
PUSH_KIND_PLANNED = 3   # planned push: reduce inputs to their PLANNED
#                         reducer slot (PushPlannedReq, plan-epoch
#                         fenced), not to a merge-range peer


@register()
class PushBlocksReq(RpcMsg):
    """Executor -> merge target: one committed map's per-partition blocks
    for a contiguous partition range (``kind=PUSH_KIND_MERGE``), or one
    opaque spill-overflow blob (``kind=PUSH_KIND_OVERFLOW`` — tiered
    spill overflowing to a peer on ENOSPC; ``sizes`` then carries the
    blob's per-partition layout so the writer can fetch ranges back).
    ``fence`` is the committing attempt's fencing token: the target's
    ledger rejects a push whose fence is older than one already applied
    for the same map, and a newer fence supersedes the stale blocks
    (excluded from the finalized ranges). ``data`` is the concatenation
    of the ``sizes`` segments in partition order."""

    def __init__(self, req_id: int, shuffle_id: int, map_id: int,
                 fence: int, kind: int, start_partition: int,
                 sizes: List[int], data: bytes):
        self.req_id = req_id
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.fence = fence
        self.kind = kind
        self.start_partition = start_partition
        self.sizes = list(sizes)
        self.data = data

    def payload(self) -> bytes:
        head = (struct.pack("<qiiq", self.req_id, self.shuffle_id,
                            self.map_id, self.fence)
                + struct.pack("<iiI", self.kind, self.start_partition,
                              len(self.sizes))
                + struct.pack(f"<{len(self.sizes)}I", *self.sizes))
        return head + self.data

    @classmethod
    def from_payload(cls, payload: bytes) -> "PushBlocksReq":
        req_id, shuffle_id, map_id, fence = struct.unpack_from("<qiiq",
                                                               payload, 0)
        kind, start, n = struct.unpack_from("<iiI", payload, 24)
        sizes = list(struct.unpack_from(f"<{n}I", payload, 36))
        return cls(req_id, shuffle_id, map_id, fence, kind, start, sizes,
                   payload[36 + 4 * n:])


@register()
class PushBlocksResp(RpcMsg):
    """Merge target's verdict: ``accepted`` is one byte per pushed
    partition (1 = appended into the segment ledger, 0 = rejected —
    stale fence, finalized shuffle, or a segment at
    ``merge_segment_max_bytes``). For overflow pushes ``token`` names
    the stored blob in the target's serving token space so the writer
    fetches it back over the ordinary data plane."""

    def __init__(self, req_id: int, status: int, token: int,
                 accepted: bytes):
        self.req_id = req_id
        self.status = status
        self.token = token
        self.accepted = accepted

    def payload(self) -> bytes:
        return (struct.pack("<qiq", self.req_id, self.status, self.token)
                + self.accepted)

    @classmethod
    def from_payload(cls, payload: bytes) -> "PushBlocksResp":
        req_id, status, token = struct.unpack_from("<qiq", payload, 0)
        return cls(req_id, status, token, payload[20:])


@register()
class PushPlannedReq(RpcMsg):
    """Executor -> PLANNED reducer slot: one committed map's bytes for
    the contiguous partition range the receiver's plan task owns, pushed
    during the map stage so the reduce stage starts with the inputs
    already local. Double-fenced: ``fence`` is the committing attempt's
    fencing token (a newer attempt's push supersedes a stale one for the
    same ``(partition, map)``, exactly the merge-ledger discipline) and
    ``plan_epoch`` stamps the ReducePlan the sender routed by — the
    receiving PushedInputStore rejects pushes older than its plan epoch
    and releases every staged range stamped older when a re-plan lands,
    so a mid-stage re-plan supersedes stale pushes and orphaned tasks
    re-pull. ``data`` is the concatenation of the ``sizes`` segments in
    partition order starting at ``start_partition``."""

    def __init__(self, req_id: int, shuffle_id: int, map_id: int,
                 fence: int, plan_epoch: int, start_partition: int,
                 sizes: List[int], data: bytes):
        self.req_id = req_id
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.fence = fence
        self.plan_epoch = plan_epoch
        self.start_partition = start_partition
        self.sizes = list(sizes)
        self.data = data

    def payload(self) -> bytes:
        head = (struct.pack("<qiiqq", self.req_id, self.shuffle_id,
                            self.map_id, self.fence, self.plan_epoch)
                + struct.pack("<iI", self.start_partition,
                              len(self.sizes))
                + struct.pack(f"<{len(self.sizes)}I", *self.sizes))
        return head + self.data

    @classmethod
    def from_payload(cls, payload: bytes) -> "PushPlannedReq":
        (req_id, shuffle_id, map_id, fence,
         plan_epoch) = struct.unpack_from("<qiiqq", payload, 0)
        start, n = struct.unpack_from("<iI", payload, 32)
        sizes = list(struct.unpack_from(f"<{n}I", payload, 40))
        return cls(req_id, shuffle_id, map_id, fence, plan_epoch, start,
                   sizes, payload[40 + 4 * n:])


@register()
class PushPlannedResp(RpcMsg):
    """Planned-push verdict: ``accepted`` is one byte per pushed
    partition (1 = staged in the PushedInputStore, 0 = rejected — stale
    plan epoch, stale attempt fence, over-budget shed, or dead/unknown
    shuffle). Rejection is never an error for the sender: the range
    simply stays a hole the reducer fills over the merged/per-map
    dataplanes."""

    def __init__(self, req_id: int, status: int, accepted: bytes):
        self.req_id = req_id
        self.status = status
        self.accepted = accepted

    def payload(self) -> bytes:
        return _QI.pack(self.req_id, self.status) + self.accepted

    @classmethod
    def from_payload(cls, payload: bytes) -> "PushPlannedResp":
        req_id, status = _QI.unpack_from(payload, 0)
        return cls(req_id, status, payload[_QI.size:])


@register()
class FinalizeSegmentsReq(RpcMsg):
    """Driver -> executors (broadcast on the announce channel at
    map-stage completion, ``req_id=0`` — one-sided, no reply) or an
    explicit request (``req_id>0``): stop accepting pushes for the
    shuffle once the push channel quiesces, seal every per-partition
    segment, and publish the results into the driver's merged
    directory."""

    def __init__(self, req_id: int, shuffle_id: int):
        self.req_id = req_id
        self.shuffle_id = shuffle_id

    def payload(self) -> bytes:
        return _QI.pack(self.req_id, self.shuffle_id)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FinalizeSegmentsReq":
        req_id, shuffle_id = _QI.unpack_from(payload, 0)
        return cls(req_id, shuffle_id)


@register()
class FinalizeSegmentsResp(RpcMsg):
    """``finalized`` counts the segments this target sealed+published."""

    def __init__(self, req_id: int, status: int, finalized: int):
        self.req_id = req_id
        self.status = status
        self.finalized = finalized

    def payload(self) -> bytes:
        return struct.pack("<qii", self.req_id, self.status,
                           self.finalized)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FinalizeSegmentsResp":
        req_id, status, finalized = struct.unpack_from("<qii", payload, 0)
        return cls(req_id, status, finalized)


@register()
class MergedPublishMsg(RpcMsg):
    """Merge target -> driver: one finalized merged segment, one-sided
    like ``PublishMsg`` (no ack — the driver's directory is repaired by
    later finalize rounds, and a lost publish only costs coverage).
    ``covered`` is a bitmap over the shuffle's map space (bit m set =
    the segment holds map m's bytes for this partition, under the
    newest fence the ledger saw); ``ranges`` the byte ranges of the
    segment file that survived fence supersession (usually one
    ``[0, nbytes)`` range); ``crc32`` the CRC32 of those ranges
    concatenated, verified REDUCER-side after the fetch so at-rest rot
    on the replica degrades to per-map fetch, never to wrong bytes."""

    def __init__(self, shuffle_id: int, partition_id: int,
                 exec_index: int, token: int, nbytes: int, crc32: int,
                 covered: bytes, ranges: List[Tuple[int, int]]):
        self.shuffle_id = shuffle_id
        self.partition_id = partition_id
        self.exec_index = exec_index
        self.token = token
        self.nbytes = nbytes
        self.crc32 = crc32
        self.covered = covered
        self.ranges = [(int(o), int(ln)) for o, ln in ranges]

    def payload(self) -> bytes:
        head = (struct.pack("<iii", self.shuffle_id, self.partition_id,
                            self.exec_index)
                + struct.pack("<qqI", self.token, self.nbytes, self.crc32)
                + struct.pack("<II", len(self.covered), len(self.ranges)))
        body = self.covered + b"".join(
            struct.pack("<QI", o, ln) for o, ln in self.ranges)
        return head + body

    @classmethod
    def from_payload(cls, payload: bytes) -> "MergedPublishMsg":
        shuffle_id, partition_id, exec_index = struct.unpack_from(
            "<iii", payload, 0)
        token, nbytes, crc = struct.unpack_from("<qqI", payload, 12)
        ncov, nranges = struct.unpack_from("<II", payload, 32)
        off = 40
        covered = payload[off:off + ncov]
        off += ncov
        ranges = []
        for _ in range(nranges):
            o, ln = struct.unpack_from("<QI", payload, off)
            ranges.append((o, ln))
            off += 12
        return cls(shuffle_id, partition_id, exec_index, token, nbytes,
                   crc, covered, ranges)


@register()
class FetchMergedReq(RpcMsg):
    """Reducer -> driver: pull one shuffle's merged-segment directory
    (cache-first in the location plane under the location epoch; this
    is the cold path / lost-coverage backstop)."""

    def __init__(self, req_id: int, shuffle_id: int):
        self.req_id = req_id
        self.shuffle_id = shuffle_id

    def payload(self) -> bytes:
        return _QI.pack(self.req_id, self.shuffle_id)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchMergedReq":
        req_id, shuffle_id = _QI.unpack_from(payload, 0)
        return cls(req_id, shuffle_id)


@register()
class FetchMergedResp(RpcMsg):
    """``data`` is ``MergedDirectory.to_bytes()`` (possibly empty —
    nothing finalized yet); ``epoch`` stamps it with the shuffle's
    location-state version so the plane's cache validity rule applies
    unchanged. ``STATUS_UNKNOWN_SHUFFLE`` when unregistered."""

    def __init__(self, req_id: int, status: int, epoch: int, data: bytes):
        self.req_id = req_id
        self.status = status
        self.epoch = epoch
        self.data = data

    def payload(self) -> bytes:
        return (_QI.pack(self.req_id, self.status) + _Q.pack(self.epoch)
                + self.data)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchMergedResp":
        req_id, status = _QI.unpack_from(payload, 0)
        (epoch,) = _Q.unpack_from(payload, _QI.size)
        return cls(req_id, status, epoch, payload[_QI.size + _Q.size:])


@register()
class TieredPublishMsg(RpcMsg):
    """Tiering executor -> driver: one cold-tier blob, one-sided like
    ``MergedPublishMsg`` (no ack — a lost publish only costs cold
    coverage; the hot copy still serves). ``blob_key`` names the blob
    in the configured store, ``covered`` is the map-space bitmap the
    blob's bytes carry for ``partition_id``, ``crc32`` the CRC32 over
    the WHOLE blob, verified reducer-side on restore so at-rest rot in
    the cold store degrades to the next resolve rung, never to wrong
    bytes. ``nbytes`` is u64: object stores hold blobs bigger than any
    one segment file. The directory it lands in is HA-replicated
    through the op log (shuffle/ha.py), so cold locations survive
    driver failover too."""

    def __init__(self, shuffle_id: int, partition_id: int, blob_key: str,
                 nbytes: int, crc32: int, covered: bytes):
        self.shuffle_id = shuffle_id
        self.partition_id = partition_id
        self.blob_key = blob_key
        self.nbytes = nbytes
        self.crc32 = crc32
        self.covered = covered

    def payload(self) -> bytes:
        key = self.blob_key.encode("utf-8")
        return (struct.pack("<ii", self.shuffle_id, self.partition_id)
                + struct.pack("<QI", self.nbytes, self.crc32)
                + struct.pack("<II", len(key), len(self.covered))
                + key + self.covered)

    @classmethod
    def from_payload(cls, payload: bytes) -> "TieredPublishMsg":
        shuffle_id, partition_id = struct.unpack_from("<ii", payload, 0)
        nbytes, crc = struct.unpack_from("<QI", payload, 8)
        nkey, ncov = struct.unpack_from("<II", payload, 20)
        off = 28
        key = payload[off:off + nkey].decode("utf-8")
        off += nkey
        covered = payload[off:off + ncov]
        return cls(shuffle_id, partition_id, key, nbytes, crc, covered)


@register()
class FetchTieredReq(RpcMsg):
    """Reducer -> driver: pull one shuffle's cold-tier directory (the
    LAST resolve rung — consulted only when pushed staging, merged
    replicas, and per-map owners have all degraded)."""

    def __init__(self, req_id: int, shuffle_id: int):
        self.req_id = req_id
        self.shuffle_id = shuffle_id

    def payload(self) -> bytes:
        return _QI.pack(self.req_id, self.shuffle_id)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchTieredReq":
        req_id, shuffle_id = _QI.unpack_from(payload, 0)
        return cls(req_id, shuffle_id)


@register()
class FetchTieredResp(RpcMsg):
    """``data`` is ``TieredDirectory.to_bytes()`` (possibly empty —
    nothing tiered yet); ``epoch`` stamps it with the shuffle's
    location-state version. ``STATUS_UNKNOWN_SHUFFLE`` + ``EPOCH_DEAD``
    when unregistered."""

    def __init__(self, req_id: int, status: int, epoch: int, data: bytes):
        self.req_id = req_id
        self.status = status
        self.epoch = epoch
        self.data = data

    def payload(self) -> bytes:
        return (_QI.pack(self.req_id, self.status) + _Q.pack(self.epoch)
                + self.data)

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchTieredResp":
        req_id, status = _QI.unpack_from(payload, 0)
        (epoch,) = _Q.unpack_from(payload, _QI.size)
        return cls(req_id, status, epoch, payload[_QI.size + _Q.size:])


@register()
class TenantMapMsg(RpcMsg):
    """Driver -> executors push at registerShuffle time: shuffle
    ``shuffle_id`` belongs to tenant ``tenant`` (and expires
    ``ttl_ms`` after registration; 0 = no TTL). Executors key their
    serve-path fair-share queues, cache charging, and quota ledgers by
    it. One-sided like every push on the announce channel: a lost push
    (or a late-joining executor) degrades that executor's view of the
    shuffle to DEFAULT_TENANT — a fairness approximation, never a
    correctness problem, and the local writer/reader path re-teaches
    the mapping from the handle on first use."""

    def __init__(self, shuffle_id: int, tenant: int, ttl_ms: int):
        self.shuffle_id = shuffle_id
        self.tenant = tenant
        self.ttl_ms = ttl_ms

    def payload(self) -> bytes:
        return struct.pack("<iiq", self.shuffle_id, self.tenant,
                           self.ttl_ms)

    @classmethod
    def from_payload(cls, payload: bytes) -> "TenantMapMsg":
        shuffle_id, tenant, ttl_ms = struct.unpack_from("<iiq", payload, 0)
        return cls(shuffle_id, tenant, ttl_ms)


# -- elastic membership (parallel/membership.py) ---------------------------
#
# The membership plane's wire half: explicit mid-job joins, the pushed
# slot-state vector, and the graceful-drain request/response. All four
# frames are ADDITIVE — a pre-elastic peer that never sends or receives
# them sees exactly the static-membership protocol (announce-only), which
# is the documented mixed-version degrade.

@register()
class JoinMsg(RpcMsg):
    """Executor -> driver: an explicit mid-job JOIN. Same membership
    append as a HelloMsg (which remains the startup greeting and the
    legacy join), but names the intent so the driver traces the elastic
    event and bumps capacity hints immediately. ``flags`` is reserved
    (0); a pre-elastic payload without it decodes to 0."""

    FLAGS_NONE = 0

    def __init__(self, manager_id, flags: int = 0):
        self.manager_id = manager_id
        self.flags = flags

    def payload(self) -> bytes:
        return self.manager_id.serialize() + struct.pack("<I", self.flags)

    @classmethod
    def from_payload(cls, payload: bytes) -> "JoinMsg":
        from sparkrdma_tpu.utils.ids import ShuffleManagerId
        mid, off = ShuffleManagerId.deserialize(payload)
        flags = 0
        if len(payload) >= off + 4:
            (flags,) = struct.unpack_from("<I", payload, off)
        return cls(mid, flags)


@register()
class MembershipBumpMsg(RpcMsg):
    """Driver -> all executors: the membership plane moved — epoch
    ``epoch`` with per-slot states ``slot_states`` (``SLOT_LIVE`` /
    ``SLOT_DRAINING`` / ``SLOT_DEAD``, one byte per announce slot).
    Rides the same broadcast channel as announces; receivers keep the
    highest epoch. Pushers stop choosing DRAINING slots as merge
    targets, fetch planners stop placing work there, and the health
    monitor registers newly-LIVE joiners. An epoch-only legacy payload
    (or a peer that drops the frame entirely) decodes to an empty state
    vector = every announced slot treated LIVE — the static-membership
    behavior."""

    def __init__(self, epoch: int, slot_states: List[int]):
        self.epoch = epoch
        self.slot_states = [int(s) for s in slot_states]

    def payload(self) -> bytes:
        return (_Q.pack(self.epoch)
                + struct.pack("<I", len(self.slot_states))
                + bytes(s & 0xFF for s in self.slot_states))

    @classmethod
    def from_payload(cls, payload: bytes) -> "MembershipBumpMsg":
        (epoch,) = _Q.unpack_from(payload, 0)
        states: List[int] = []
        if len(payload) >= _Q.size + 4:
            (n,) = struct.unpack_from("<I", payload, _Q.size)
            states = list(payload[_Q.size + 4:_Q.size + 4 + n])
        return cls(epoch, states)


@register()
class DrainReq(RpcMsg):
    """Driver -> drainee: replicate everything you own, you are being
    decommissioned. The drainee re-pushes its committed map outputs
    (``PUSH_KIND_DRAIN`` — ledger fences dedupe whatever background
    push-merge already delivered) and hands off the merged-segment rows
    it hosts for OTHER executors' maps, then answers ``DrainResp``.
    ``deadline_ms`` bounds the drainee-side work; a pre-elastic payload
    without it decodes to 0 = the receiver's configured
    ``drain_deadline_ms``."""

    def __init__(self, req_id: int, slot: int, deadline_ms: int = 0):
        self.req_id = req_id
        self.slot = slot
        self.deadline_ms = deadline_ms

    def payload(self) -> bytes:
        return _QI.pack(self.req_id, self.slot) + struct.pack(
            "<q", self.deadline_ms)

    @classmethod
    def from_payload(cls, payload: bytes) -> "DrainReq":
        req_id, slot = _QI.unpack_from(payload, 0)
        deadline_ms = 0
        if len(payload) >= _QI.size + 8:
            (deadline_ms,) = struct.unpack_from("<q", payload, _QI.size)
        return cls(req_id, slot, deadline_ms)


@register()
class DrainResp(RpcMsg):
    """Drainee -> driver: the replication pass finished. ``STATUS_OK``
    means every committed output was (re-)pushed and hosted segments
    handed off within the deadline; ``STATUS_ERROR`` means a partial or
    impossible drain (push-merge off, pusher dead) — the driver's
    coverage check decides whether existing replicas suffice or the
    drain falls back to tombstone recovery either way. ``maps_pushed``
    and ``bytes_pushed`` are the audit counters the drain result
    reports."""

    def __init__(self, req_id: int, status: int, maps_pushed: int,
                 bytes_pushed: int):
        self.req_id = req_id
        self.status = status
        self.maps_pushed = maps_pushed
        self.bytes_pushed = bytes_pushed

    def payload(self) -> bytes:
        return _QI.pack(self.req_id, self.status) + struct.pack(
            "<qq", self.maps_pushed, self.bytes_pushed)

    @classmethod
    def from_payload(cls, payload: bytes) -> "DrainResp":
        req_id, status = _QI.unpack_from(payload, 0)
        maps_pushed, bytes_pushed = struct.unpack_from(
            "<qq", payload, _QI.size)
        return cls(req_id, status, maps_pushed, bytes_pushed)


# Status codes shared by responses.
STATUS_OK = 0
STATUS_UNKNOWN_SHUFFLE = 1
STATUS_UNKNOWN_MAP = 2
STATUS_BAD_RANGE = 3
STATUS_ERROR = 4
# the committed output failed its at-rest CRC verification: retryable on
# the wire (the retry envelope escalates it to FetchFailed with a
# corrupt_output verdict, and recovery re-executes the producing map)
STATUS_CORRUPT = 5
# push-merge: the shuffle's segments are sealed on this target — the
# pusher stops pushing it (authoritative, not retryable; the map simply
# stays per-map-fetched)
STATUS_FINALIZED = 6

# RunTaskResp statuses.
TASK_OK = 0
TASK_ERROR = 1
TASK_FETCH_FAILED = 2
TASK_NO_RUNNER = 3


# ---------------------------------------------------------------------------
#                         driver HA: op-log replication + lease takeover
#                         (shuffle/ha.py; one-sided pushes on the
#                         announce channel, never request/reply)

def _pack_str(s: str) -> bytes:
    raw = s.encode("utf-8")
    return struct.pack("<H", len(raw)) + raw


def _unpack_str(payload: bytes, off: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from("<H", payload, off)
    off += 2
    return payload[off:off + n].decode("utf-8"), off + n


@register()
class OpLogAppendMsg(RpcMsg):
    """Primary -> standbys: one replicated op-log record, stamped
    ``(incarnation, seq)`` (monotone; receivers accept only strictly
    forward stamps, which fences a zombie primary's appends). ``kind``
    is the ha.OP_* discriminator; ``blob`` is the op payload — for
    OP_WIRE, the encoded driver-bound frame itself, replayed through
    the same handler whose fence floors make the second application a
    no-op."""

    def __init__(self, incarnation: int, seq: int, kind: int,
                 blob: bytes):
        self.incarnation = incarnation
        self.seq = seq
        self.kind = kind
        self.blob = blob

    def payload(self) -> bytes:
        return struct.pack("<IQI", self.incarnation, self.seq,
                           self.kind) + self.blob

    @classmethod
    def from_payload(cls, payload: bytes) -> "OpLogAppendMsg":
        incarnation, seq, kind = struct.unpack_from("<IQI", payload, 0)
        return cls(incarnation, seq, kind, bytes(payload[16:]))


@register()
class SnapshotMsg(RpcMsg):
    """Primary -> standby: the full control-plane snapshot taken at
    ``(incarnation, seq)`` (ha.encode_snapshot envelope). Sent once at
    subscribe time (and after compactions) so a cold standby catches up
    from the snapshot plus the op tail instead of an unbounded log."""

    def __init__(self, incarnation: int, seq: int, blob: bytes):
        self.incarnation = incarnation
        self.seq = seq
        self.blob = blob

    def payload(self) -> bytes:
        return struct.pack("<IQ", self.incarnation, self.seq) + self.blob

    @classmethod
    def from_payload(cls, payload: bytes) -> "SnapshotMsg":
        incarnation, seq = struct.unpack_from("<IQ", payload, 0)
        return cls(incarnation, seq, bytes(payload[12:]))


@register()
class StandbyHelloMsg(RpcMsg):
    """Standby -> primary: subscribe to the replication stream. ``name``
    is the standby's lease-holder identity, ``host``/``port`` the
    address its catch-up server listens on (the primary pushes
    SnapshotMsg + OpLogAppendMsg there), ``last_seq`` the newest seq it
    already holds so a resubscribe after a blip replays only the gap."""

    def __init__(self, name: str, host: str, port: int, last_seq: int):
        self.name = name
        self.host = host
        self.port = port
        self.last_seq = last_seq

    def payload(self) -> bytes:
        return (_pack_str(self.name) + _pack_str(self.host)
                + struct.pack("<IQ", self.port, self.last_seq))

    @classmethod
    def from_payload(cls, payload: bytes) -> "StandbyHelloMsg":
        name, off = _unpack_str(payload, 0)
        host, off = _unpack_str(payload, off)
        port, last_seq = struct.unpack_from("<IQ", payload, off)
        return cls(name, host, port, last_seq)


@register()
class TakeoverMsg(RpcMsg):
    """New primary -> executors: the driver lease moved — incarnation
    ``incarnation`` now answers at ``host:port``. Executors observe a
    failover as one more membership-style bump: re-point the
    DriverClient (forward-only on incarnation, so a late replay of an
    older takeover cannot re-point backwards) and let the in-flight
    retry envelopes re-send against the new address. The authoritative
    state re-broadcast (announce + epoch bumps + plans) rides the same
    channel right behind this frame."""

    def __init__(self, incarnation: int, host: str, port: int):
        self.incarnation = incarnation
        self.host = host
        self.port = port

    def payload(self) -> bytes:
        return struct.pack("<I", self.incarnation) + _pack_str(
            self.host) + struct.pack("<I", self.port)

    @classmethod
    def from_payload(cls, payload: bytes) -> "TakeoverMsg":
        (incarnation,) = struct.unpack_from("<I", payload, 0)
        host, off = _unpack_str(payload, 4)
        (port,) = struct.unpack_from("<I", payload, off)
        return cls(incarnation, host, port)


@register()
class ShardPublishMsg(RpcMsg):
    """Executor -> shard OWNER: direct positional table write for a map
    in the owner's range (shard_ownership mode). Same body as
    PublishMsg — 12-byte entry, attempt fence, optional per-partition
    lengths — plus ``owner_gen``, the composed ownership generation
    (driver incarnation in the high 32 bits, per-incarnation handoff
    seq below) the sender believes holds the range. An owner that has
    sealed the shard, moved to a newer generation, or never owned the
    range forwards the publish to the driver instead of applying it,
    so a stale sender costs one extra hop, never a lost entry."""

    ENTRY_BYTES = 12

    def __init__(self, shuffle_id: int, map_id: int, entry: bytes,
                 fence: int = 0, owner_gen: int = 0, lengths=None):
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.entry = entry
        self.fence = fence
        self.owner_gen = owner_gen
        self.lengths = list(lengths) if lengths is not None else None

    def payload(self) -> bytes:
        out = (struct.pack("<ii", self.shuffle_id, self.map_id)
               + self.entry
               + struct.pack("<qq", self.fence, self.owner_gen))
        if self.lengths is not None:
            out += struct.pack(f"<I{len(self.lengths)}I",
                               len(self.lengths), *self.lengths)
        return out

    @classmethod
    def from_payload(cls, payload: bytes) -> "ShardPublishMsg":
        shuffle_id, map_id = struct.unpack_from("<ii", payload, 0)
        entry = payload[8:8 + cls.ENTRY_BYTES]
        off = 8 + cls.ENTRY_BYTES
        fence, owner_gen = struct.unpack_from("<qq", payload, off)
        off += 16
        lengths = None
        if len(payload) >= off + 4:
            (n,) = struct.unpack_from("<I", payload, off)
            if len(payload) >= off + 4 + 4 * n:
                lengths = list(struct.unpack_from(f"<{n}I", payload,
                                                  off + 4))
        return cls(shuffle_id, map_id, entry, fence, owner_gen, lengths)


@register()
class ShardMergedPublishMsg(RpcMsg):
    """Executor -> shard OWNER: a merged-directory publish routed to
    the owner of shard ``partition % num_shards`` instead of the
    driver. ``blob`` is the inner MergedPublishMsg payload verbatim —
    the owner logs it opaquely and batch-forwards it, so the driver's
    zombie/fence checks still run exactly once, on the same bytes."""

    def __init__(self, shuffle_id: int, shard: int, owner_gen: int,
                 blob: bytes):
        self.shuffle_id = shuffle_id
        self.shard = shard
        self.owner_gen = owner_gen
        self.blob = blob

    def payload(self) -> bytes:
        return struct.pack("<iiq", self.shuffle_id, self.shard,
                           self.owner_gen) + self.blob

    @classmethod
    def from_payload(cls, payload: bytes) -> "ShardMergedPublishMsg":
        shuffle_id, shard, owner_gen = struct.unpack_from(
            "<iiq", payload, 0)
        return cls(shuffle_id, shard, owner_gen, bytes(payload[16:]))


@register()
class ShardBatchMsg(RpcMsg):
    """Shard owner -> driver: batch convergence of writes the owner
    already applied and logged. ``records`` are
    ``(map_id, fence, entry[, lengths])`` publishes (3-tuples
    normalize to ``lengths=None``); ``blobs`` are opaque
    MergedPublishMsg payloads. The driver replays each through its
    normal publish path — the fence CAS makes the echo idempotent —
    which is what keeps the driver table byte-identical to the
    unsharded path."""

    def __init__(self, shuffle_id: int, shard: int, owner_gen: int,
                 records, blobs=None):
        self.shuffle_id = shuffle_id
        self.shard = shard
        self.owner_gen = owner_gen
        self.records = [
            (r[0], r[1], bytes(r[2]),
             list(r[3]) if len(r) > 3 and r[3] is not None else None)
            for r in records
        ]
        self.blobs = [bytes(b) for b in (blobs or [])]

    def payload(self) -> bytes:
        out = [struct.pack("<iiqI", self.shuffle_id, self.shard,
                           self.owner_gen, len(self.records))]
        for map_id, fence, entry, lengths in self.records:
            out.append(struct.pack("<iqI", map_id, fence, len(entry)))
            out.append(entry)
            if lengths is None:
                out.append(struct.pack("<i", -1))
            else:
                out.append(struct.pack(f"<i{len(lengths)}I",
                                       len(lengths), *lengths))
        out.append(struct.pack("<I", len(self.blobs)))
        for b in self.blobs:
            out.append(struct.pack("<I", len(b)))
            out.append(b)
        return b"".join(out)

    @classmethod
    def from_payload(cls, payload: bytes) -> "ShardBatchMsg":
        shuffle_id, shard, owner_gen, nrec = struct.unpack_from(
            "<iiqI", payload, 0)
        off = 20
        records = []
        for _ in range(nrec):
            map_id, fence, elen = struct.unpack_from("<iqI", payload,
                                                     off)
            off += 16
            entry = bytes(payload[off:off + elen])
            off += elen
            (nlen,) = struct.unpack_from("<i", payload, off)
            off += 4
            lengths = None
            if nlen >= 0:
                lengths = list(struct.unpack_from(f"<{nlen}I", payload,
                                                  off))
                off += 4 * nlen
            records.append((map_id, fence, entry, lengths))
        (nblob,) = struct.unpack_from("<I", payload, off)
        off += 4
        blobs = []
        for _ in range(nblob):
            (blen,) = struct.unpack_from("<I", payload, off)
            off += 4
            blobs.append(bytes(payload[off:off + blen]))
            off += blen
        return cls(shuffle_id, shard, owner_gen, records, blobs)


@register()
class ShardOpMsg(RpcMsg):
    """Shard owner -> its standby: one per-shard op-log record, stamped
    ``(owner_gen, seq)`` — the sharded twin of OpLogAppendMsg, with
    the ownership generation where the driver stream has its
    incarnation. Forward-only on ``(owner_gen, seq)`` at the receiver,
    so a sealed owner's stragglers cannot land behind a handoff."""

    def __init__(self, shuffle_id: int, shard: int, owner_gen: int,
                 seq: int, kind: int, blob: bytes):
        self.shuffle_id = shuffle_id
        self.shard = shard
        self.owner_gen = owner_gen
        self.seq = seq
        self.kind = kind
        self.blob = blob

    def payload(self) -> bytes:
        return struct.pack("<iiqQI", self.shuffle_id, self.shard,
                           self.owner_gen, self.seq,
                           self.kind) + self.blob

    @classmethod
    def from_payload(cls, payload: bytes) -> "ShardOpMsg":
        shuffle_id, shard, owner_gen, seq, kind = struct.unpack_from(
            "<iiqQI", payload, 0)
        return cls(shuffle_id, shard, owner_gen, seq, kind,
                   bytes(payload[28:]))


@register()
class ShardHandoffMsg(RpcMsg):
    """Driver -> executors: ownership of ``(shuffle_id, shard)`` moved
    to ``new_slot`` at generation ``owner_gen``. The outgoing owner (if
    alive — the drain case) seals its log segment and flushes; the
    incoming owner replays its standby buffer for the shard; everyone
    else re-aims buffered republishes. Rides the announce channel right
    behind the refreshed ShardMapMsg, so FIFO ordering gives the new
    owner its assignment before the replay trigger."""

    def __init__(self, shuffle_id: int, shard: int, owner_gen: int,
                 new_slot: int, old_slot: int):
        self.shuffle_id = shuffle_id
        self.shard = shard
        self.owner_gen = owner_gen
        self.new_slot = new_slot
        self.old_slot = old_slot

    def payload(self) -> bytes:
        return struct.pack("<iiqii", self.shuffle_id, self.shard,
                           self.owner_gen, self.new_slot, self.old_slot)

    @classmethod
    def from_payload(cls, payload: bytes) -> "ShardHandoffMsg":
        shuffle_id, shard, owner_gen, new_slot, old_slot = \
            struct.unpack_from("<iiqii", payload, 0)
        return cls(shuffle_id, shard, owner_gen, new_slot, old_slot)
