"""Multi-host deployment: the exchange over a global (cross-process) mesh.

The reference scales multi-node by giving every executor a verbs endpoint
and letting the NICs carry the M×R traffic (java/RdmaNode.java;
README.md:11-31 — 5-7 worker clusters). The TPU-native equivalent is a
**global ``jax.sharding.Mesh`` spanning hosts**: ``jax.distributed``
bootstraps the process group, XLA routes collectives over ICI within a
slice and DCN between hosts, and the same jitted exchange step from
``parallel.exchange`` runs unchanged — SPMD does not care where shards
live.

Division of labor (mirrors the reference exactly):
* **data plane**: the ragged all-to-all over the global mesh (XLA-routed,
  host CPUs idle — the remote-CPU-bypass invariant);
* **control plane**: ``parallel.endpoints`` hello/announce + driver tables
  over TCP (DCN) — in the reference these are the only two RPCs too.

For the driver's multi-chip dry runs and CI, the same code path is
exercised with multiple *processes of CPU devices* on one machine
(``tests/test_multihost.py`` spawns a 2-process × 4-device cluster) —
the process-boundary behavior (global array assembly, cross-process
collectives) is identical to a real multi-host TPU pod.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def init_multihost(coordinator_address: str, num_processes: int,
                   process_id: int,
                   local_device_count: Optional[int] = None,
                   platform: Optional[str] = None) -> None:
    """Join the distributed runtime. Call before any jax computation.

    On a real TPU pod each process owns its host's chips and
    ``local_device_count``/``platform`` stay None; CI passes
    ``local_device_count=K, platform='cpu'`` to emulate hosts with virtual
    devices.
    """
    import os

    if local_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_device_count}").strip()
    import jax

    if platform is not None:
        jax.config.update("jax_platforms", platform)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh(axis_name: str = "shuffle"):
    """One-axis mesh over every device in the cluster."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis_name,))


def shard_local_rows(mesh, axis_name: str, local_rows: np.ndarray,
                     global_rows: int):
    """Assemble this process's rows into the global sharded array."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis_name))
    return jax.make_array_from_process_local_data(
        sharding, local_rows, (global_rows,) + local_rows.shape[1:])


def run_multihost_terasort(mesh, axis_name: str, rows_per_device: int,
                           payload_words: int = 4, seed: int = 0,
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """One TeraSort round over the global mesh; returns this process's
    local sorted shards + counts (addressable output only — remote shards
    belong to other processes)."""
    import jax

    from sparkrdma_tpu.models.terasort import TeraSortConfig, generate_rows, make_terasort_step

    n_global = mesh.devices.size
    n_local = len(jax.local_devices())
    process_id = jax.process_index()
    cfg = TeraSortConfig(rows_per_device=rows_per_device,
                         payload_words=payload_words, out_factor=2)
    # each process generates ONLY its slice (O(local) memory/time) with a
    # process-disjoint deterministic seed
    local_slice = generate_rows(cfg, n_local,
                                seed=seed * 100_003 + process_id)
    rows_global = shard_local_rows(mesh, axis_name, local_slice,
                                   n_global * rows_per_device)
    step = make_terasort_step(mesh, axis_name, cfg)
    out, counts, overflowed = jax.block_until_ready(step(rows_global))
    local_out = np.concatenate(
        [np.asarray(s.data) for s in out.addressable_shards])
    local_counts = np.concatenate(
        [np.asarray(s.data) for s in counts.addressable_shards])
    if any(bool(np.asarray(s.data).any()) for s in overflowed.addressable_shards):
        raise OverflowError("terasort receive overflow on this host")
    return local_out, local_counts
