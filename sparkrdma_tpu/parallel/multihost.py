"""Multi-host deployment: the exchange over a global (cross-process) mesh.

The reference scales multi-node by giving every executor a verbs endpoint
and letting the NICs carry the M×R traffic (java/RdmaNode.java;
README.md:11-31 — 5-7 worker clusters). The TPU-native equivalent is a
**global ``jax.sharding.Mesh`` spanning hosts**: ``jax.distributed``
bootstraps the process group, XLA routes collectives over ICI within a
slice and DCN between hosts, and the same jitted exchange step from
``parallel.exchange`` runs unchanged — SPMD does not care where shards
live.

Division of labor (mirrors the reference exactly):
* **data plane**: the ragged all-to-all over the global mesh (XLA-routed,
  host CPUs idle — the remote-CPU-bypass invariant);
* **control plane**: ``parallel.endpoints`` hello/announce + driver tables
  over TCP (DCN) — in the reference these are the only two RPCs too.

For the driver's multi-chip dry runs and CI, the same code path is
exercised with multiple *processes of CPU devices* on one machine
(``tests/test_multihost.py`` spawns a 2-process × 4-device cluster) —
the process-boundary behavior (global array assembly, cross-process
collectives) is identical to a real multi-host TPU pod.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger(__name__)


def init_multihost(coordinator_address: str, num_processes: int,
                   process_id: int,
                   local_device_count: Optional[int] = None,
                   platform: Optional[str] = None) -> None:
    """Join the distributed runtime. Call before any jax computation.

    On a real TPU pod each process owns its host's chips and
    ``local_device_count``/``platform`` stay None; CI passes
    ``local_device_count=K, platform='cpu'`` to emulate hosts with virtual
    devices.
    """
    import os

    if local_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_device_count}").strip()
    import jax

    if platform is not None:
        jax.config.update("jax_platforms", platform)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh(axis_name: str = "shuffle"):
    """One-axis mesh over every device in the cluster."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis_name,))


def shard_local_rows(mesh, axis_name: str, local_rows: np.ndarray,
                     global_rows: int):
    """Assemble this process's rows into the global sharded array."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis_name))
    return jax.make_array_from_process_local_data(
        sharding, local_rows, (global_rows,) + local_rows.shape[1:])


def run_multihost_mesh_reduce(managers: Sequence, handle, mesh,
                              axis_name: str = "shuffle",
                              impl: str = "auto", out_factor: int = 2,
                              sort_by_key: bool = True,
                              rows_per_round: int = 0):
    """Cross-process mesh reduce: committed spills on N hosts -> ONE
    global-mesh exchange — the reference's whole multi-node pipeline
    (README.md:11-31: map outputs on every node's disks, NICs carry the
    MxR redistribution) with the global collective as the data plane.

    Each process stages the spills its LOCAL executors own according to
    the driver table (so a map recomputed or speculated onto another host
    stages exactly once, table-owner-wins — the same single-owner contract
    the TCP fetch path reads by), assembles the global sharded arrays with
    ``make_array_from_process_local_data``, and the same jitted exchange
    step every other path uses redistributes rows to their partition's
    owner device. SPMD: every process must call this collectively.

    ``managers``: this process's executor-role ``TpuShuffleManager`` s.
    Returns this process's ADDRESSABLE results: a list of
    ``(keys u64[*], payload u8[*, W], partition_ids i64[*])`` per local
    mesh device (remote shards belong to their own processes).

    ``rows_per_round > 0`` bounds DEVICE memory: the exchange runs in R
    rounds of at most ``rows_per_round`` rows per device per round (R is
    agreed group-wide from the same metadata allgather, so every process
    enters the same number of collectives; one compile serves all
    rounds). Host staging is unchanged — what streaming bounds is the
    device-resident working set, the discipline
    ``run_mesh_reduce_streamed`` applies in-process.
    """
    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkrdma_tpu.parallel import exchange as exchange_mod
    from sparkrdma_tpu.parallel.exchange import make_shuffle_exchange
    from sparkrdma_tpu.shuffle.mesh_service import (
        _rows_to_u32,
        _u32_to_rows,
        device_row_words,
    )
    from sparkrdma_tpu.shuffle.writer import decode_rows

    n_global = mesh.devices.size
    local_mesh_devices = [d for d in mesh.devices.flat
                          if d.process_index == jax.process_index()]
    n_local = len(local_mesh_devices)
    if n_local == 0:
        raise ValueError("this process owns no devices of the mesh")
    partitioner = handle.partitioner.build(handle.num_partitions)

    # 1. the driver table names each map's owner slot; stage local ones
    endpoint_mgr = next((m for m in managers if m.executor is not None),
                        None)
    if endpoint_mgr is None:
        # failing BEFORE the collective: a silent StopIteration here would
        # leave every peer hung in the allgather
        raise ValueError("managers must include at least one executor role")
    table = endpoint_mgr.executor.get_driver_table(
        handle.shuffle_id, expect_published=handle.num_maps)
    # exec_index with a wait budget: the hello/announce is async, and a
    # KeyError here would kill this process before the collective and
    # strand every peer in the allgather
    by_slot = {m.executor.exec_index(timeout=5): m for m in managers
               if m.executor is not None and m.resolver is not None}
    all_keys, all_payloads = [], []
    staged = np.zeros(handle.num_maps, dtype=np.int64)
    for m in range(handle.num_maps):
        entry = table.entry(m)
        if entry is None:
            raise RuntimeError(f"map {m} unpublished in driver table")
        owner = by_slot.get(entry[1])
        if owner is None:
            continue  # another process's map (checked globally below)
        from sparkrdma_tpu.utils.integrity import CorruptOutputError
        try:
            raw = owner.resolver.local_blocks(handle.shuffle_id, m, 0,
                                              handle.num_partitions)
        except (CorruptOutputError, OSError) as e:
            # corrupt/unreadable at staging time: same treatment as a
            # disposed output — unstaged, so the consistent completeness
            # check below owns the failure on every process
            raw = None
            log.warning("map %d unreadable at staging time (%s); leaving "
                        "unstaged", m, e)
        if raw is None:
            # disposed mid-staging (dying executor): leave it unstaged —
            # the POST-allgather completeness check raises the retryable
            # FetchFailedError on EVERY process consistently; raising here
            # would strand the peers in the collective
            continue
        k, p = decode_rows(raw, handle.row_payload_bytes)
        staged[m] = 1
        all_keys.append(k)
        all_payloads.append(p)
    keys = (np.concatenate(all_keys) if all_keys
            else np.zeros(0, dtype=np.uint64))
    payload = (np.concatenate(all_payloads) if all_payloads
               else np.zeros((0, handle.row_payload_bytes), dtype=np.uint8))
    rows = _rows_to_u32(keys, payload)
    dest = np.asarray(partitioner(keys), dtype=np.int32) % n_global

    # cross-slice accounting: the per-host seams ARE the topology's DCN
    # links (parallel/topology.py) — tally the bytes this process sends
    # across them so multi-host rounds report cross_slice_bytes the same
    # way the in-process hierarchical exchange does
    from sparkrdma_tpu.parallel import topology as topology_mod

    topo = topology_mod.detect_topology(mesh)
    if not topo.is_flat and len(dest):
        dev_slice = topo.device_slices()
        my_pos = next(i for i, d in enumerate(mesh.devices.flat)
                      if d.process_index == jax.process_index())
        crossing = int((dev_slice[dest] != dev_slice[my_pos]).sum())
        if crossing:
            topology_mod.record_cross_slice(crossing * rows.shape[1] * 4)

    # 2. one tiny host-side allgather carries ALL the cross-host metadata:
    # per-process (row total, mesh-device count) for capacity agreement,
    # plus the staged-map bitmap for global completeness
    meta = multihost_utils.process_allgather(np.concatenate(
        [np.array([len(rows), n_local], dtype=np.int64), staged]))
    meta = meta.reshape(-1, 2 + handle.num_maps)
    # processes may own different device counts: everyone takes the max of
    # per-process ceil(rows_i / n_local_i) so the global shape agrees
    cap = max(1, int(max(-(-int(r) // max(1, int(nl)))
                         for r, nl in meta[:, :2])))
    rounds = 1
    round_order = None
    if rows_per_round > 0 and cap > rows_per_round:
        # bounded device rounds: same derivation on every process from
        # the shared metadata, so the group agrees on R with no extra
        # collective
        rounds = -(-cap // rows_per_round)
        # staged rows are key-sorted per map (the writer's spill order),
        # so CONTIGUOUS slices concentrate each round on few destination
        # devices and overflow the per-round receive budget. Assign each
        # destination's rows evenly across rounds instead — monotone
        # within a destination (round = floor(j*R/m_d)), so per-dest
        # order is preserved — and pad cap by the ±1-per-dest rounding.
        counts_d = np.bincount(dest, minlength=n_global) \
            if len(dest) else np.zeros(n_global, np.int64)
        grouped = np.argsort(dest, kind="stable") if len(dest) else \
            np.zeros(0, np.int64)
        starts = np.r_[0, np.cumsum(counts_d)[:-1]]
        within = (np.arange(len(grouped), dtype=np.int64)
                  - np.repeat(starts, counts_d))
        m_rep = np.repeat(np.maximum(counts_d, 1), counts_d)
        round_of = (within * rounds) // m_rep
        round_order = [grouped[round_of == r] for r in range(rounds)]
        # pad slack for the ±1-per-destination rounding: derived from the
        # ALLGATHERED device counts — every process must compute the same
        # global array shape, and local n_local values differ
        min_nl = max(1, int(meta[:, 1].min()))
        cap = rows_per_round + -(-n_global // min_nl)
    staged_global = meta[:, 2:].sum(axis=0)
    unstaged = np.flatnonzero(staged_global == 0)
    if len(unstaged):
        from sparkrdma_tpu.shuffle.fetcher import FetchFailedError

        m = int(unstaged[0])
        entry = table.entry(m)
        raise FetchFailedError(
            handle.shuffle_id, m, entry[1] if entry else -1,
            "map output staged by no process (owner died, spill disposed "
            "mid-staging, or its managers not passed in) — raised on all "
            "processes; recompute and re-enter collectively")

    width = device_row_words(handle.row_payload_bytes)
    sharding = NamedSharding(mesh, P(axis_name))
    # 3. the shared jitted exchange over the GLOBAL mesh — one compile
    # serves every round (shapes are identical by construction)
    exchange = make_shuffle_exchange(mesh, axis_name, impl=impl,
                                     out_factor=out_factor)
    per_round = n_local * cap
    got_rows: list = [[] for _ in range(n_local)]
    for r in range(rounds):
        if round_order is not None:
            idx = round_order[r]
            if len(idx) > per_round:  # ±1-per-dest rounding blew the pad
                raise OverflowError(
                    f"round {r} holds {len(idx)} rows > send budget "
                    f"{per_round}; raise rows_per_round")
            chunk, cdest = rows[idx], dest[idx]
        else:
            chunk = rows[r * per_round:(r + 1) * per_round]
            cdest = dest[r * per_round:(r + 1) * per_round]
        rows_p = np.zeros((per_round, width), dtype=np.uint32)
        rows_p[:len(chunk)] = chunk
        dest_p = np.full(per_round, -1, dtype=np.int32)
        dest_p[:len(chunk)] = cdest
        rows_g = jax.make_array_from_process_local_data(
            sharding, rows_p, (n_global * cap, width))
        dest_g = jax.make_array_from_process_local_data(
            sharding, dest_p, (n_global * cap,))
        received, counts, _, overflowed = jax.block_until_ready(
            exchange(rows_g, dest_g))
        recv_by_dev = {s.device: np.asarray(s.data)
                       for s in received.addressable_shards}
        counts_by_dev = {s.device: np.asarray(s.data)
                         for s in counts.addressable_shards}
        of_by_dev = {s.device: np.asarray(s.data)
                     for s in overflowed.addressable_shards}
        for i, dev in enumerate(local_mesh_devices):
            got = recv_by_dev[dev].reshape(-1, width)
            cnt = counts_by_dev[dev].reshape(-1)
            total = int(cnt.sum())
            if of_by_dev[dev].any():
                raise OverflowError(
                    "multihost mesh reduce receive overflow; raise "
                    "out_factor or lower rows_per_round skew exposure")
            got_rows[i].append(got[:total].copy())
    exchange_mod.record_exchange(int(meta[:, 0].sum()))

    # 4. assemble this process's addressable results across rounds
    results = []
    for segs in got_rows:
        allrows = (np.concatenate(segs) if segs
                   else np.zeros((0, width), np.uint32))
        k, p = _u32_to_rows(allrows, handle.row_payload_bytes)
        parts = np.asarray(partitioner(k), dtype=np.int64)
        if sort_by_key:
            order = np.argsort(k, kind="stable")
            k, p, parts = k[order], p[order], parts[order]
        results.append((k, p, parts))
    return results


def run_multihost_terasort(mesh, axis_name: str, rows_per_device: int,
                           payload_words: int = 4, seed: int = 0,
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """One TeraSort round over the global mesh; returns this process's
    local sorted shards + counts (addressable output only — remote shards
    belong to other processes)."""
    import jax

    from sparkrdma_tpu.models.terasort import TeraSortConfig, generate_rows, make_terasort_step

    n_global = mesh.devices.size
    n_local = len(jax.local_devices())
    process_id = jax.process_index()
    cfg = TeraSortConfig(rows_per_device=rows_per_device,
                         payload_words=payload_words, out_factor=2)
    # each process generates ONLY its slice (O(local) memory/time) with a
    # process-disjoint deterministic seed
    local_slice = generate_rows(cfg, n_local,
                                seed=seed * 100_003 + process_id)
    rows_global = shard_local_rows(mesh, axis_name, local_slice,
                                   n_global * rows_per_device)
    step = make_terasort_step(mesh, axis_name, cfg)
    out, counts, overflowed = jax.block_until_ready(step(rows_global))
    local_out = np.concatenate(
        [np.asarray(s.data) for s in out.addressable_shards])
    local_counts = np.concatenate(
        [np.asarray(s.data) for s in counts.addressable_shards])
    if any(bool(np.asarray(s.data).any()) for s in overflowed.addressable_shards):
        raise OverflowError("terasort receive overflow on this host")
    return local_out, local_counts
