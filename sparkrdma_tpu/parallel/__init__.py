from sparkrdma_tpu.parallel.rpc_msg import (  # noqa: F401
    AnnounceMsg,
    HelloMsg,
    RpcMsg,
    decode_message,
    segments,
    Reassembler,
)
