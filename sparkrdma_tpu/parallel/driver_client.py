"""DriverClient: the executor's single, failover-aware driver channel.

Before driver HA, every executor-side component dialed the driver
through its own scattered ``ConnectionCache`` call sites (endpoints,
manager, fetcher, recovery), each with its own error story — a dead
driver connection surfaced as whatever the nearest caller did with a
``TransportError``: a burned fetch retry, a tombstoned live peer, or a
hung publish. This module centralizes the driver channel so failover is
ONE behavior everywhere:

* the driver's address is a mutable, forward-only pointer: a
  ``TakeoverMsg`` re-points it under a higher ``driver_incarnation``
  (stale re-points from a zombie's queued broadcast lose the comparison
  and are dropped, the same keep-highest rule every epoch receiver
  already applies);
* sends and requests retry ``TransportError`` against the CURRENT
  address under the existing backoff envelope
  (:class:`~sparkrdma_tpu.parallel.transport.Backoff`), bounded by
  ``request_deadline_ms`` — sized to ride through a
  ``driver_lease_ms`` failover window;
* exhaustion raises :class:`DriverUnreachableError`, a RETRYABLE
  verdict the fetch/recovery layers classify as "driver down", which
  must never tombstone a live peer or burn the per-peer fetch budget
  (the peers are fine; only the control plane is electing).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional, Tuple

from sparkrdma_tpu.parallel.transport import (Backoff, Connection,
                                              ConnectionCache,
                                              TransportError)
from sparkrdma_tpu.parallel.rpc_msg import RpcMsg

log = logging.getLogger("sparkrdma_tpu.driver_client")


class DriverUnreachableError(TransportError):
    """The driver did not answer within the deadline envelope — distinct
    from a PEER failure by construction: peers are reached directly, the
    driver only through :class:`DriverClient`. Retryable: a standby may
    be mid-takeover, and the next attempt may land on the re-pointed
    primary."""

    retryable = True


class DriverClient:
    """The one channel to the (current) driver.

    ``note_takeover`` is called from the executor's message handler when
    a ``TakeoverMsg`` lands; in-flight retry loops re-read the address
    every attempt, so a failover mid-retry converges without any caller
    cooperation.
    """

    def __init__(self, conf, clients: ConnectionCache,
                 addr: Tuple[str, int]):
        self._conf = conf
        self._clients = clients
        self._lock = threading.Lock()
        self._addr: Tuple[str, int] = (addr[0], int(addr[1]))
        self._incarnation = 0
        self.failovers_observed = 0  # audit: accepted re-points
        self.retried_sends = 0       # audit: attempts past the first

    @property
    def addr(self) -> Tuple[str, int]:
        with self._lock:
            return self._addr

    @property
    def incarnation(self) -> int:
        with self._lock:
            return self._incarnation

    def note_takeover(self, incarnation: int, host: str,
                      port: int) -> bool:
        """Re-point the driver address, forward-only: only a strictly
        higher incarnation wins, so a zombie primary's stale broadcast
        (or a reordered duplicate) can never re-point executors at a
        deposed driver. Returns True iff the pointer moved."""
        with self._lock:
            if incarnation <= self._incarnation:
                return False
            self._incarnation = incarnation
            self._addr = (host, int(port))
            self.failovers_observed += 1
            return True

    def conn(self) -> Connection:
        """The raw cached connection to the current address (compat for
        call sites that manage their own retries)."""
        return self._clients.get(*self.addr)

    # -- deadline-bounded retry envelope ---------------------------------

    def send(self, msg: RpcMsg,
             deadline_s: Optional[float] = None) -> None:
        """Fire-and-forget with the retry envelope: a publish/hello/sync
        racing a failover re-dials the re-pointed primary instead of
        dying with the old connection."""
        self._with_retry(lambda conn: conn.send(msg), deadline_s)

    def request(self, build: Callable[[Connection], RpcMsg],
                timeout: Optional[float] = None,
                deadline_s: Optional[float] = None) -> RpcMsg:
        """Request/response with the retry envelope. ``build`` mints the
        message against the attempt's connection so every attempt
        carries a FRESH req_id — re-sending a stale id against a new
        primary could orphan-match another waiter's response. Only
        ``TransportError`` is retried; a ``TimeoutError`` means the
        driver is reachable but slow, which the caller's own long-poll
        logic owns."""
        return self._with_retry(
            lambda conn: conn.request(build(conn), timeout=timeout),
            deadline_s)

    def _with_retry(self, fn: Callable[[Connection], object],
                    deadline_s: Optional[float]):
        budget = (deadline_s if deadline_s is not None
                  else self._conf.resolved_request_deadline_s())
        deadline = time.monotonic() + budget
        backoff = Backoff.from_conf(self._conf)
        attempt = 0
        last: Optional[TransportError] = None
        while True:
            addr = self.addr
            conn = None
            try:
                conn = self._clients.get(*addr)
                return fn(conn)
            except TransportError as e:
                last = e
                if conn is not None:
                    conn.close()  # force a re-dial (possibly re-pointed)
                log.debug("driver %s:%s attempt %d failed: %s", addr[0],
                          addr[1], attempt + 1, e)
            if time.monotonic() >= deadline:
                raise DriverUnreachableError(
                    f"driver {addr[0]}:{addr[1]} unreachable after "
                    f"{attempt + 1} attempts over {budget:.1f}s"
                ) from last
            self.retried_sends += 1
            backoff.sleep(attempt)
            attempt += 1
