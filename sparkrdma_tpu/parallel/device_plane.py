"""The unified exchange dataplane: one interface, two implementations,
a cost model choosing per stage.

The reference has exactly one accelerated dataplane (one-sided READs);
this framework grew two — the HOST dataplane (writer -> resolver ->
fetcher over the control plane, `shuffle/fetcher.py`) and the DEVICE
dataplane (ragged/chunked/ring ICI collectives, `parallel/exchange.py`).
Until now the choice was a config flag (`mesh_impl` / a mesh being
configured at all) and the device path still round-tripped rows through
host staging for the reduce-side sort. This module makes the ICI
all-to-all the *primary* dataplane for on-mesh stages:

* ``Exchange`` — the interface both planes implement: ``supports()``
  (can this plane carry the stage at all) and ``plan()`` (what would it
  cost / how would it run). The engine asks the COST MODEL
  (``select_dataplane``), not a flag.
* ``make_fused_step`` — the ``shard_map``-fused partition + exchange +
  local-sort step, generalized from ``models/terasort.py``'s
  ``make_terasort_step`` into a reusable op: rows are grouped to their
  destination device, exchanged over ICI (ragged all-to-all by default,
  dense/gather/ring fallbacks — `parallel/exchange.py`), and key-sorted
  on the receiving device, so partitions never leave HBM between the
  map output and the sorted reduce input. One-pass, no materialized
  intermediates — the redistribution-plan recipe of "Memory-efficient
  array redistribution through portable collective communication"
  (PAPERS.md).
* ``run_fused_exchange`` — the host driver: bounded rounds auto-sized
  from the HBM byte budget (replacing the static ``mesh_rows_per_round``
  knob), DOUBLE-BUFFERED so round ``k+1``'s collective is dispatched
  while round ``k``'s device sort runs and its results drain
  (``exchange.round`` spans + ``exchange.overlap`` instants prove the
  overlap in the trace).

Overflow (per-pair skew past the dense slot, or a receive past the
capacity headroom) raises ``OverflowError``; the ENGINE degrades exactly
the overflowing stage to the host dataplane instead of failing the job
(`engine.py` catches it and re-serves the stage through the fetcher).

Multi-slice topologies (``parallel/topology.py``) add a THIRD plan kind:
**hierarchical** — the fused ICI step runs per slice over its sub-mesh
(bulk bytes stay on ICI), and only the slice-crossing residue moves over
the host/DCN channel, re-homed into its destination slice's next round
(local regroup -> cross-slice move -> local regroup: the factored
redistribution of "Memory-efficient array redistribution through
portable collective communication", PAPERS.md — no full intermediate is
ever materialized). ``select_dataplane`` scores the candidates by the
two-level link cost ``intra_bytes/ici_bw + inter_bytes/dcn_bw`` instead
of a residency boolean; a single-slice (degenerate) topology reproduces
the flat selector bit-for-bit. One slice's overflow (or a collective
failure under a lost device) degrades ONLY that slice's residue to
host-side serving, byte-identically — the other slices stay on ICI.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from sparkrdma_tpu.parallel import topology as topology_mod
from sparkrdma_tpu.utils import trace as trace_mod

DEVICE_PLANE = "device"
HOST_PLANE = "host"
HIERARCHICAL_PLANE = "hierarchical"


def _resolve_plan_impl(mesh, impl: str, axis_name: str) -> str:
    """The shared transport resolution (``exchange.resolve_transport``):
    ring transports pass through verbatim, everything else goes through
    the per-mesh probe — one helper so the override arm and the plane
    planners can't drift apart."""
    from sparkrdma_tpu.parallel.exchange import resolve_transport

    return resolve_transport(mesh, impl, axis_name)


def stage_to_device(arr: np.ndarray, sharding):
    """One staged round's host->device upload, donation-friendly: when
    the runtime supports aliasing (jax >= 0.4.31), the host staging
    buffer — a BufferPool lease the native fetch engine already landed
    wire bytes in, or the round's freshly-padded block, never touched
    again after dispatch — may back the device array directly instead of
    being copied. Backends that can't alias (or older runtimes without
    the parameter) transfer exactly as before; results are identical
    either way."""
    import jax

    try:
        return jax.device_put(arr, sharding, may_alias=True)
    except TypeError:  # runtime predates may_alias
        return jax.device_put(arr, sharding)


# one-time latch for the mesh_rows_per_round deprecation (engine ctor
# arg or conf key): the knob still pins round sizes for mixed-version
# configs, but auto-sizing from device_hbm_budget is the supported path
_rows_knob_warned = False


def warn_mesh_rows_deprecated(source: str = "mesh_rows_per_round") -> None:
    """Emit the one-per-process deprecation warning for the legacy
    static round-size knob; later calls are silent."""
    global _rows_knob_warned
    if _rows_knob_warned:
        return
    _rows_knob_warned = True
    import warnings

    warnings.warn(
        f"{source} is deprecated: rounds auto-size from device_hbm_budget"
        " (docs/CONFIG.md 'Device exchange'); the pinned value is still"
        " honored for mixed-version configs", DeprecationWarning,
        stacklevel=3)

# conservative per-device HBM footprint of one fused round, in row
# multiples: the input buffer + its destination-grouped copy (2 x cap)
# plus the receive buffer + its sorted copy (2 x out_factor x cap). The
# cost model sizes rounds so this fits the configured budget.
def _footprint_rows(row_bytes: int, out_factor: int) -> int:
    return row_bytes * (2 + 2 * out_factor)


@dataclass(frozen=True)
class StageProfile:
    """What the cost model knows about one stage's exchange.

    ``est_bytes``: committed map-output bytes across the stage (the
    driver/resolvers know this exactly at stage boundary — the same
    size column the adaptive planner consumes). ``row_bytes``: the
    device row stride. ``resident``: whether the stage's inputs can be
    staged straight into this process's HBM (in-process executors; a
    remote-only stage can't ride the local mesh). ``out_factor``:
    receive headroom the runner will allocate.

    ``intra_bytes`` / ``inter_bytes`` decompose ``est_bytes`` BY LINK
    for multi-slice topologies: bytes whose destination stays in the
    producing map's home slice vs. bytes that must cross the DCN seam.
    ``-1`` = unknown — the cost model falls back to the topology's
    uniform-destination estimate; flat topologies never look at them.
    """

    est_bytes: int
    row_bytes: int
    resident: bool = True
    out_factor: int = 2
    intra_bytes: int = -1
    inter_bytes: int = -1


@dataclass(frozen=True)
class ExchangePlan:
    """One stage's dataplane decision: which plane, which transport,
    and (device plane) the auto-sized round bound. ``rows_per_round``
    0 = one shot; ``reason`` is the cost model's audit trail (surfaced
    on the ``exchange.select`` trace instant). ``topology`` rides along
    on HIERARCHICAL plans — the runner needs the slice bounds the plan
    was scored against (None on flat plans); hierarchical ``impl`` is
    the RAW transport ask (``"auto"`` re-probes per sub-mesh — the
    opcode a cross-slice mesh rejects may compile per slice)."""

    plane: str
    impl: str = ""
    rows_per_round: int = 0
    reason: str = ""
    topology: Optional[topology_mod.Topology] = None


class Exchange:
    """The one interface both dataplanes implement.

    ``supports`` answers "can this plane carry the stage at all";
    ``plan`` answers "how would it run" (None = it shouldn't). The
    cost model (`select_dataplane`) composes the implementations; the
    engine only ever sees the resulting ``ExchangePlan``.
    """

    name: str = ""

    def supports(self, mesh, axis_name: str,
                 profile: StageProfile) -> Tuple[bool, str]:
        raise NotImplementedError

    def plan(self, mesh, axis_name: str, profile: StageProfile, *,
             impl: str = "auto",
             hbm_budget: int = 64 << 20) -> Optional[ExchangePlan]:
        raise NotImplementedError


class DeviceExchange(Exchange):
    """The ICI collective dataplane (fused partition+exchange+sort)."""

    name = DEVICE_PLANE

    def supports(self, mesh, axis_name, profile):
        if mesh is None:
            return False, "no mesh configured"
        if not profile.resident:
            return False, "stage inputs not resident to this process"
        return True, ""

    def plan(self, mesh, axis_name, profile, *, impl="auto",
             hbm_budget=64 << 20):
        ok, why = self.supports(mesh, axis_name, profile)
        if not ok:
            return None
        resolved = _resolve_plan_impl(mesh, impl, axis_name)
        n = mesh.shape[axis_name]
        rows_cap = auto_rows_per_round(profile.row_bytes, hbm_budget,
                                       profile.out_factor)
        if rows_cap < 1:
            return None  # budget can't hold even one row per device
        per_dev_rows = -(-max(0, profile.est_bytes)
                         // max(1, profile.row_bytes) // n) or 1
        if per_dev_rows <= rows_cap:
            return ExchangePlan(
                DEVICE_PLANE, resolved, 0,
                f"fits budget one-shot ({per_dev_rows} rows/dev <= "
                f"{rows_cap} cap)")
        return ExchangePlan(
            DEVICE_PLANE, resolved, rows_cap,
            f"chunked: {per_dev_rows} rows/dev over {rows_cap}-row "
            "budget rounds")


class HostExchange(Exchange):
    """The host dataplane (writer -> resolver -> fetcher): always
    available — it is the fallback plane, the mixed-version plane, and
    the off-mesh plane. The engine serves it through the ordinary
    ``getReader`` path with all its retry/CRC machinery."""

    name = HOST_PLANE

    def supports(self, mesh, axis_name, profile):
        return True, ""

    def plan(self, mesh, axis_name, profile, *, impl="auto",
             hbm_budget=64 << 20):
        return ExchangePlan(HOST_PLANE, "", 0, "host dataplane")


def auto_rows_per_round(row_bytes: int, hbm_budget: int,
                        out_factor: int = 2) -> int:
    """Rows per device per fused round that keep the round's footprint
    (input + grouped copy + receive + sorted copy) inside
    ``hbm_budget`` — the auto-sizing that replaces the static
    ``mesh_rows_per_round`` knob."""
    return max(0, int(hbm_budget) // _footprint_rows(max(1, row_bytes),
                                                     max(1, out_factor)))


_PLANES = (DeviceExchange(), HostExchange())


def select_dataplane(mesh, axis_name: str, profile: StageProfile, *,
                     impl: str = "auto", hbm_budget: int = 64 << 20,
                     override: str = "auto",
                     topology: Optional[topology_mod.Topology] = None,
                     ) -> ExchangePlan:
    """The per-stage cost model: device plane when the stage is mesh-
    resident and its bytes fit the HBM budget's round sizing, host
    plane otherwise. ``override`` short-circuits: ``"device"`` /
    ``"host"`` force a plane (the old ``mesh_impl``-flag behavior,
    kept as the escape hatch); ``"auto"`` asks the cost model.

    ``topology``: the mesh's two-level description. On a MULTI-slice
    topology a stage that would ride the device plane is scored by the
    two-level link cost instead of a residency boolean: the flat
    collective routes EVERY byte through the DCN-priced inter-slice
    fabric (a cross-slice all-to-all is lock-stepped on its slowest
    links, and the native ragged opcode doesn't span slices at all),
    while the hierarchical plan keeps the intra-slice bulk on ICI and
    pays DCN only for the slice-crossing residue —
    ``intra/ici_bw + inter/dcn_bw``. None or a single-slice topology
    reproduces the flat selector bit-for-bit."""
    if override not in ("auto", DEVICE_PLANE, HOST_PLANE):
        # a typo'd escape hatch must not silently ride the cost model
        # (same rule as make_fused_step's sort_mode)
        raise ValueError(f"unknown dataplane override {override!r} "
                         "(expected 'auto', 'device' or 'host')")
    if override == HOST_PLANE:
        return ExchangePlan(HOST_PLANE, "", 0, "forced by override")
    device, host = _PLANES
    if override == DEVICE_PLANE:
        ok, why = device.supports(mesh, axis_name, profile)
        if not ok:
            # forcing a plane that declared itself unable to carry the
            # stage (no mesh, non-resident inputs) is a caller error —
            # silently running host under a "device" ask would be worse
            raise ValueError(f"dataplane override 'device': {why}")
        dev = device.plan(mesh, axis_name, profile, impl=impl,
                          hbm_budget=hbm_budget)
        if dev is not None:
            return dev
        # supported but the budget can't hold a row: run minimum rounds
        # rather than silently switching planes under an explicit ask
        return ExchangePlan(DEVICE_PLANE, _resolve_plan_impl(
            mesh, impl, axis_name), 1,
            "forced by override (budget below one row)")
    dev = device.plan(mesh, axis_name, profile, impl=impl,
                      hbm_budget=hbm_budget)
    if dev is None:
        # HostExchange.plan always returns a plan — it is the fallback
        # plane by contract (no "no plane volunteered" tail needed)
        return host.plan(mesh, axis_name, profile, impl=impl,
                         hbm_budget=hbm_budget)
    if (topology is not None and not topology.is_flat
            and dev.rows_per_round == 0):
        # one-shot plans only: the hierarchical runner stages the whole
        # stage host-side before factoring it (the same whole-stage
        # contract the one-shot fused path has); a CHUNKED plan means
        # the stage outgrew that contract, and the flat chunked device
        # plan keeps its streamed bounded-staging discipline
        est = max(0, profile.est_bytes)
        intra, inter = profile.intra_bytes, profile.inter_bytes
        if intra < 0 or inter < 0:
            # no per-link byte decomposition published for this stage:
            # fall back to the uniform-destination estimate
            inter = int(est * topology.uniform_inter_fraction())
            intra = est - inter
        hier_s = topology.link_seconds(intra, inter)
        flat_s = topology.link_seconds(0, intra + inter)
        if hier_s < flat_s:
            # the plan carries the RAW transport ask, not the global
            # mesh's resolution: the native ragged opcode that a
            # cross-slice mesh rejects may well compile on each
            # single-slice sub-mesh, so "auto" must re-probe per
            # sub-mesh inside the runner (make_fused_step)
            return ExchangePlan(
                HIERARCHICAL_PLANE, impl, 0,
                f"two-level: {topology.num_slices} slices, "
                f"{intra >> 20}MiB intra@{topology.ici_gbps:g}GB/s + "
                f"{inter >> 20}MiB inter@{topology.dcn_gbps:g}GB/s = "
                f"{hier_s:.4f}s vs flat {flat_s:.4f}s",
                topology=topology)
    return dev


# ---------------------------------------------------------------------------
# the fused step: partition + exchange + local sort, one shard_map program
# ---------------------------------------------------------------------------

def _local_sort(rows, keys, sort_mode: str, write_back_keys: bool):
    """One local sort of full rows by (pre-masked) keys. The three
    strategies and their trade-offs are documented on
    ``models.terasort.TeraSortConfig.sort_mode`` (gather is
    latency-bound, the sorts bandwidth-bound; bench A/Bs them).

    ``keys`` is a TUPLE of u32 key vectors, most significant first —
    one entry for single-word keys (TeraSort), two for the u64 packed
    ``[lo, hi]`` row layout the mesh shuffle service moves (x64 is
    disabled in this runtime, so multi-word keys sort as multiple u32
    operands instead of one u64). ``write_back_keys`` overwrites
    column 0 with the sorted key (single-word layouts only — padding
    rows get their sentinel visible in the key column, the terasort
    contract)."""
    import jax
    import jax.numpy as jnp

    if sort_mode == "multisort":
        cols = tuple(rows[:, j] for j in range(rows.shape[1]))
        # is_stable: all three modes must order duplicate keys
        # identically (gather is stable via its iota tiebreak)
        out = jax.lax.sort(keys + cols, num_keys=len(keys),
                           is_stable=True)
        sorted_keys = out[0]
        sorted_rows = jnp.stack(out[len(keys):], axis=1)
    elif sort_mode == "colsort":
        # identical keys in every lane + a STABLE sort => every column
        # receives the same permutation, so rows stay intact without a
        # gather and without per-column operands. Multi-word keys run
        # as LSD radix passes: one stable per-lane sort per key word,
        # least significant first, remaining key words carried as
        # broadcast value operands so they ride the same permutation.
        carried = tuple(jnp.broadcast_to(k[:, None], rows.shape)
                        for k in keys)
        sorted_rows = rows
        for w in range(len(keys) - 1, -1, -1):
            out = jax.lax.sort((carried[w], sorted_rows)
                               + carried[:w] + carried[w + 1:],
                               dimension=0, num_keys=1, is_stable=True)
            sorted_rows = out[1]
            rest = out[2:]
            carried = rest[:w] + (out[0],) + rest[w:]
        sorted_keys = carried[0][:, 0]
    else:
        iota = jnp.arange(rows.shape[0], dtype=jnp.int32)
        # iota as a FINAL KEY makes the order total: duplicate keys
        # order by original position with no reliance on sort
        # stability (a value-operand iota under an unstable sort
        # could permute ties arbitrarily)
        out = jax.lax.sort(keys + (iota,), num_keys=len(keys) + 1)
        sorted_keys, order = out[0], out[-1]
        sorted_rows = jnp.take(rows, order, axis=0)
    if write_back_keys:
        # the key column already equals sorted_keys for valid rows;
        # only padding rows (sentinel keys) need the overwrite
        sorted_rows = sorted_rows.at[:, 0].set(sorted_keys)
    return sorted_rows, sorted_keys


def _row_keys(rows, key_words: int):
    """The per-row sort key vectors, most significant first: column 0
    for single-word u32 keys, ``(hi=col 1, lo=col 0)`` for the
    little-endian packed u64 layout ``shuffle/mesh_service.
    _rows_to_u32`` produces."""
    if key_words == 1:
        return (rows[:, 0],)
    return (rows[:, 1], rows[:, 0])


@functools.lru_cache(maxsize=64)
def make_fused_step(mesh, axis_name: str, row_words: int, *,
                    out_factor: int = 2, impl: str = "auto",
                    sort_mode: str = "gather", key_words: int = 1,
                    partition: str = "range"):
    """Build the jitted fused partition+exchange+local-sort step —
    ``models/terasort.py``'s one-round step generalized into the
    reusable device-plane op. Memoized per full signature so per-job
    callers compile once.

    ``partition`` selects how rows find their destination device:

    * ``"range"`` — uniform u32 key-range split (TeraSort): ONE key
      sort doubles as the destination grouping (range partition is
      monotonic in key), per-destination counts fall out of D-1 binary
      searches. ``step(rows)`` with ``rows: u32[D*cap, row_words]``
      sharded on the leading axis, key = column 0.
    * ``"dest"`` — caller-computed destinations (any partitioner):
      ``step(rows, dest)`` with ``dest: i32[D*cap]``; ``dest < 0``
      marks padding rows (not sent). Rows group by destination, ride
      the exchange, and key-sort on the receiving device
      (``key_words`` 1 = u32 column 0, 2 = u64 packed columns [0,1]).

    Returns ``(sorted_rows, recv_counts[D, D], overflowed[D])`` with
    each device's rows key-sorted, padding at the end (strip with
    ``recv_counts[d].sum()``). ``overflowed[d]`` flags a receive past
    the ``out_factor`` headroom or a dense-slot pair overflow — results
    there are truncated and MUST not be trusted (the engine's remedy:
    degrade the stage to the host dataplane).
    """
    import jax
    import jax.numpy as jnp

    from jax.sharding import PartitionSpec as P

    from sparkrdma_tpu.ops.partition import uniform_splitters
    from sparkrdma_tpu.parallel.exchange import (
        group_by_destination,
        ragged_exchange_shard,
        resolve_transport,
    )
    from sparkrdma_tpu.utils.compat import shard_map

    if sort_mode not in ("gather", "multisort", "colsort"):
        # a typo must not silently measure (and mislabel) the gather path
        raise ValueError(f"unknown sort_mode {sort_mode!r} "
                         "(expected 'gather', 'multisort' or 'colsort')")
    if partition not in ("range", "dest"):
        raise ValueError(f"unknown partition {partition!r} "
                         "(expected 'range' or 'dest')")
    if partition == "range" and key_words != 1:
        raise ValueError("range partitioning is defined on single-word "
                         "u32 keys")
    n = mesh.shape[axis_name]
    impl = resolve_transport(mesh, impl, axis_name)
    spec = P(axis_name)
    sentinel = jnp.uint32(0xFFFFFFFF)
    write_back = key_words == 1
    splitters = uniform_splitters(n, jnp.uint32) if partition == "range" \
        else None

    def sort_received(received, total):
        """Key-sort received rows with pads (index >= total) masked to
        the sentinel on every key word so they sort last; stable order
        within equal keys is arrival (source-major) order."""
        idx = jnp.arange(received.shape[0], dtype=jnp.int32)
        keys = tuple(jnp.where(idx < total, k, sentinel)
                     for k in _row_keys(received, key_words))
        return _local_sort(received, keys, sort_mode, write_back)[0]

    # pallas interpret-mode outputs confuse the vma checker when mixed
    # with collectives; disable it ONLY for the ring transports (same
    # rule as make_chunked_exchange / make_shuffle_exchange)
    in_specs = (spec,) if partition == "range" else (spec, spec)
    shard_kwargs = dict(mesh=mesh, in_specs=in_specs,
                        out_specs=(spec, spec, spec))
    if impl in ("ring", "ring_interpret"):
        shard_kwargs["check_vma"] = False

    if partition == "range":

        @jax.jit
        @functools.partial(shard_map, **shard_kwargs)
        def step(rows):
            keys = (rows[:, 0],)
            if n == 1:
                # single-device: no exchange, one sort is the whole job
                sorted_rows, _ = _local_sort(rows, keys, sort_mode,
                                             write_back)
                counts = jnp.array([[rows.shape[0]]], dtype=jnp.int32)
                return sorted_rows, counts, jnp.zeros((1,), bool)

            # Local sort by KEY once: range partition is monotonic in
            # key, so key-sorted rows are destination-grouped for free —
            # this replaces the separate argsort-by-destination + gather
            # entirely.
            grouped, sorted_keys = _local_sort(rows, keys, sort_mode,
                                               write_back)
            # per-destination counts: D-1 binary searches on sorted keys
            bounds = jnp.searchsorted(sorted_keys, splitters, side="left")
            bounds = jnp.concatenate([
                jnp.zeros(1, bounds.dtype), bounds,
                jnp.array([rows.shape[0]], bounds.dtype)])
            counts = jnp.diff(bounds).astype(jnp.int32)

            output = jnp.zeros((rows.shape[0] * out_factor, row_words),
                               dtype=rows.dtype)
            received, recv_counts, _, overflowed = ragged_exchange_shard(
                grouped, counts, axis_name, output=output, impl=impl)
            sorted_rows = sort_received(received, recv_counts.sum())
            return sorted_rows, recv_counts[None], overflowed[None]

        return step

    @jax.jit
    @functools.partial(shard_map, **shard_kwargs)
    def step(rows, dest):
        dest = dest.reshape(-1)
        if n == 1:
            valid = dest >= 0
            idx_keys = tuple(jnp.where(valid, k, sentinel)
                             for k in _row_keys(rows, key_words))
            sorted_rows, _ = _local_sort(rows, idx_keys, sort_mode,
                                         write_back)
            counts = jnp.sum(valid).astype(jnp.int32).reshape(1, 1)
            return sorted_rows, counts, jnp.zeros((1,), bool)
        grouped, counts = group_by_destination(rows, dest, n)
        output = jnp.zeros((rows.shape[0] * out_factor, row_words),
                           dtype=rows.dtype)
        received, recv_counts, _, overflowed = ragged_exchange_shard(
            grouped, counts, axis_name, output=output, impl=impl)
        sorted_rows = sort_received(received, recv_counts.sum())
        return sorted_rows, recv_counts[None], overflowed[None]

    return step


# ---------------------------------------------------------------------------
# the overlapped host driver
# ---------------------------------------------------------------------------

def run_fused_exchange(mesh, axis_name: str, rows: np.ndarray,
                       dest: np.ndarray, *, key_words: int = 2,
                       rows_per_round: int = 0, out_factor: int = 2,
                       impl: str = "auto", sort_mode: str = "gather",
                       tracer=None, pipeline_rounds: bool = True,
                       ) -> Tuple[List[np.ndarray], int]:
    """Drive the fused step over fully-materialized arrays: bounded
    rounds of ``rows_per_round`` rows per device (0 = one shot) through
    ``run_fused_exchange_rounds``. ``rows: u32[N, W]`` (unpadded),
    ``dest: i32[N]`` destination device per row. Callers whose data
    streams off disk should feed ``run_fused_exchange_rounds`` a block
    generator instead, so host staging holds one round."""
    n = mesh.shape[axis_name]
    row_words = rows.shape[1]
    if len(rows) == 0:
        return [np.zeros((0, row_words), np.uint32) for _ in range(n)], 0
    cap = rows_per_round if rows_per_round > 0 else -(-len(rows) // n)
    per_round = cap * n

    def blocks():
        for start in range(0, len(rows), per_round):
            yield (rows[start:start + per_round],
                   dest[start:start + per_round])

    return run_fused_exchange_rounds(
        mesh, axis_name, blocks(), row_words, cap, key_words=key_words,
        out_factor=out_factor, impl=impl, sort_mode=sort_mode,
        tracer=tracer, pipeline_rounds=pipeline_rounds)


def run_fused_exchange_rounds(mesh, axis_name: str, blocks,
                              row_words: int, rows_per_round: int, *,
                              key_words: int = 2, out_factor: int = 2,
                              impl: str = "auto",
                              sort_mode: str = "gather", tracer=None,
                              pipeline_rounds: bool = True,
                              ) -> Tuple[List[np.ndarray], int]:
    """Drive the fused step over a stream of round blocks: ``blocks``
    yields ``(rows u32[<= rows_per_round * D, row_words], dest i32)``
    per round, so HOST staging holds one round (plus the in-flight one
    when pipelined) no matter how large the stage — the bounded-staging
    discipline ``run_mesh_reduce_streamed`` had, kept. Rounds are
    DOUBLE-BUFFERED: round ``k+1``'s collective is dispatched while
    round ``k``'s on-device sort runs and its results drain
    (``exchange.round`` spans per round, ``exchange.overlap`` instants
    when a dispatch preceded the previous round's collection).

    Returns ``(per_device_sorted_rows, rounds)``: device d's rows
    key-sorted (u64 packed keys when ``key_words == 2``), rounds merged
    via the tournament merge. Raises ``OverflowError`` on any round's
    receive overflow — the caller (engine) degrades the stage to the
    host dataplane.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkrdma_tpu.parallel.exchange import record_exchange

    tracer = tracer if tracer is not None else trace_mod.NULL
    n = mesh.shape[axis_name]
    per_round = max(1, rows_per_round) * n
    step = make_fused_step(mesh, axis_name, row_words,
                           out_factor=out_factor, impl=impl,
                           sort_mode=sort_mode, key_words=key_words,
                           partition="dest")
    sharding = NamedSharding(mesh, P(axis_name))
    runs: List[list] = [[] for _ in range(n)]

    def dispatch(r: int, chunk: np.ndarray, dchunk: np.ndarray):
        """Stage one round (pad to the static shape) and launch its
        collective; jax dispatch is async — no blocking here."""
        with tracer.span("exchange.round", "exchange", round=r,
                         rows=len(chunk)):
            rows_p = np.zeros((per_round, row_words), np.uint32)
            rows_p[:len(chunk)] = chunk
            dest_p = np.full(per_round, -1, np.int32)
            dest_p[:len(chunk)] = dchunk
            out = step(stage_to_device(rows_p, sharding),
                       stage_to_device(dest_p, sharding))
        record_exchange(len(chunk))
        return out

    def collect(results) -> None:
        # np.asarray blocks on the device step (exchange + sort)
        out, counts, overflowed = results
        if np.asarray(overflowed).any():
            raise OverflowError(
                "fused exchange receive overflow: skew exceeds the "
                "out_factor headroom for this round size — the engine "
                "degrades the stage to the host dataplane")
        out = np.asarray(out).reshape(n, -1, row_words)
        counts = np.asarray(counts)
        for d in range(n):
            # .copy(): a view would pin the padded round buffer across
            # all rounds
            runs[d].append(out[d][:int(counts[d].sum())].copy())

    rounds = 0
    if pipeline_rounds:
        in_flight = None
        for chunk, dchunk in blocks:
            nxt = dispatch(rounds, chunk, dchunk)
            if in_flight is not None:
                tracer.instant("exchange.overlap", "exchange",
                               dispatched=rounds, collecting=rounds - 1)
                collect(in_flight)
            in_flight = nxt
            rounds += 1
        if in_flight is not None:
            collect(in_flight)
    else:
        for chunk, dchunk in blocks:
            collect(dispatch(rounds, chunk, dchunk))
            rounds += 1

    if rounds == 0:
        return [np.zeros((0, row_words), np.uint32) for _ in range(n)], 0
    if rounds == 1:
        return [runs[d][0] for d in range(n)], 1

    from sparkrdma_tpu.shuffle.external import merge_runs

    merged = []
    for d in range(n):
        if not runs[d]:
            merged.append(np.zeros((0, row_words), np.uint32))
            continue
        _, out = merge_runs([(_run_keys(r, key_words), r)
                             for r in runs[d]])
        merged.append(out)
    return merged, rounds


# ---------------------------------------------------------------------------
# the hierarchical (two-level) driver: per-slice ICI + DCN residue
# ---------------------------------------------------------------------------

def _run_keys(r: np.ndarray, key_words: int) -> np.ndarray:
    """Sort/merge keys of device-row runs: packed u64 for the 2-word
    layout, column 0 otherwise (shared by the flat and hierarchical
    drivers' tournament merges and the host-side degrade sort)."""
    if key_words == 2:
        return r[:, :2].copy().view(np.uint64).reshape(-1)
    return r[:, 0]


def run_hierarchical_exchange(mesh, axis_name: str,
                              topology: topology_mod.Topology,
                              rows: np.ndarray, dest: np.ndarray,
                              home_slice: np.ndarray, *,
                              key_words: int = 2, rows_per_round: int = 0,
                              out_factor: int = 2, impl: str = "auto",
                              sort_mode: str = "gather", tracer=None,
                              ) -> Tuple[List[np.ndarray], int]:
    """Drive the FACTORED two-phase redistribution over a multi-slice
    topology: local regroup -> cross-slice move -> local regroup, per
    "Memory-efficient array redistribution through portable collective
    communication" (PAPERS.md) — no full intermediate is ever
    materialized.

    * **Phase 1 (intra)**: every row whose destination device lives in
      its home slice rides that slice's fused partition+exchange+sort
      step over the slice sub-mesh (``topology.slice_mesh``) — the bulk
      bytes, on ICI, in budget-bounded rounds exactly like the flat
      driver.
    * **DCN move**: the slice-crossing residue is tallied and charged
      (``topology.record_cross_slice`` + the installed shim) WHILE the
      phase-1 collectives are in flight — the DCN phase overlaps the ICI
      phase (``exchange.overlap``), the two-level analogue of the flat
      driver's double buffering.
    * **Phase 2 (regroup at destination)**: arrived residue rows run the
      destination slice's fused step — the second local regroup.

    ``home_slice: i32[N]`` names each row's producing slice (executor
    slots map to slices via ``Topology.slice_of_slot``); ``dest`` is the
    GLOBAL destination device per row. Returns the flat drivers'
    contract: per-device key-sorted rows (runs merged across phases and
    rounds), plus the total ICI round count.

    Per-slice degrade: a slice whose receive overflows (or whose
    collective fails under a lost device) falls back to host-side
    serving for ITS rows only — byte-identically, the other slices stay
    on ICI (``exchange.degrade`` instant with ``scope="slice"``).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkrdma_tpu.parallel.exchange import record_exchange

    tracer = tracer if tracer is not None else trace_mod.NULL
    n = mesh.shape[axis_name]
    row_words = rows.shape[1]
    if topology is None or topology.is_flat:
        # degenerate single-slice topology: the flat driver IS the plan
        return run_fused_exchange(
            mesh, axis_name, rows, dest, key_words=key_words,
            rows_per_round=rows_per_round, out_factor=out_factor,
            impl=impl, sort_mode=sort_mode, tracer=tracer)
    dest = np.asarray(dest, dtype=np.int32)
    home = np.asarray(home_slice, dtype=np.int32)
    dev_slice = topology.device_slices()
    dest_slice = dev_slice[dest] if len(dest) else dest
    runs: List[list] = [[] for _ in range(n)]
    degraded: set = set()
    rounds = 0
    row_bytes = row_words * 4

    def host_fallback(s: int, chunk: np.ndarray, dchunk: np.ndarray):
        """Serve one slice-chunk host-side, byte-identically: group by
        destination device, key-sort each group (the receiving device's
        sort), append as ordinary runs."""
        lo, hi = topology.slice_bounds(s)
        for d in range(lo, hi):
            sub = chunk[dchunk == d]
            if not len(sub):
                continue
            order = np.argsort(_run_keys(sub, key_words), kind="stable")
            runs[d].append(np.ascontiguousarray(sub[order]))

    def collect(s: int, lo: int, ns: int, result) -> None:
        out, counts, overflowed = result
        if np.asarray(overflowed).any():
            raise OverflowError(
                f"hierarchical exchange receive overflow in slice {s}")
        out = np.asarray(out).reshape(ns, -1, row_words)
        counts = np.asarray(counts)
        for i in range(ns):
            runs[lo + i].append(out[i][:int(counts[i].sum())].copy())

    def run_phase(per_slice: Dict[int, Tuple[np.ndarray, np.ndarray]],
                  phase: str, dcn_moves=None) -> None:
        """Dispatch every slice's budget-bounded rounds; charge the DCN
        residue move while round 0's collectives are in flight; collect
        with per-slice degrade."""
        nonlocal rounds
        sched = []
        for s in sorted(per_slice):
            rs, ds = per_slice[s]
            if not len(rs):
                continue
            lo, hi = topology.slice_bounds(s)
            ns = hi - lo
            cap = rows_per_round if rows_per_round > 0 else -(-len(rs) // ns)
            per_round = max(1, cap) * ns
            submesh = topology_mod.slice_mesh(mesh, axis_name, topology, s)
            step = make_fused_step(submesh, axis_name, row_words,
                                   out_factor=out_factor, impl=impl,
                                   sort_mode=sort_mode, key_words=key_words,
                                   partition="dest")
            sharding = NamedSharding(submesh, P(axis_name))
            chunks = [(rs[o:o + per_round], ds[o:o + per_round])
                      for o in range(0, len(rs), per_round)]
            sched.append((s, lo, ns, per_round, step, sharding, chunks))

        charged = dcn_moves is None

        def charge():
            nonlocal charged
            if charged:
                return
            charged = True
            for (src, dst) in sorted(dcn_moves):
                topology_mod.record_cross_slice(dcn_moves[(src, dst)])

        for r in range(max((len(c[6]) for c in sched), default=0)):
            batch = []
            for s, lo, ns, per_round, step, sharding, chunks in sched:
                if r >= len(chunks):
                    continue
                chunk, dchunk = chunks[r]
                if s in degraded:
                    host_fallback(s, chunk, dchunk)
                    continue
                with tracer.span("exchange.round", "exchange",
                                 round=rounds, phase=phase, slice=s,
                                 rows=len(chunk)):
                    rows_p = np.zeros((per_round, row_words), np.uint32)
                    rows_p[:len(chunk)] = chunk
                    dest_p = np.full(per_round, -1, np.int32)
                    dest_p[:len(chunk)] = dchunk - lo  # slice-local device
                    out = step(stage_to_device(rows_p, sharding),
                               stage_to_device(dest_p, sharding))
                record_exchange(len(chunk))
                batch.append((s, lo, ns, chunk, dchunk, out))
            if batch and not charged:
                # jax dispatch is async: the residue crosses DCN while
                # the ICI collectives above are in flight
                tracer.instant("exchange.overlap", "exchange",
                               dispatched=rounds, collecting=-1,
                               phase=phase)
            charge()
            for s, lo, ns, chunk, dchunk, out in batch:
                try:
                    collect(s, lo, ns, out)
                except OverflowError:
                    # degrade ONLY this slice's residue to host serving;
                    # the other slices stay on ICI
                    degraded.add(s)
                    tracer.instant("exchange.degrade", "exchange",
                                   scope="slice", slice=s,
                                   reason="overflow")
                    host_fallback(s, chunk, dchunk)
            if batch:
                rounds += 1
        charge()  # a phase with no ICI rounds still pays its DCN move

    if len(rows):
        intra = dest_slice == home
        phase1 = {}
        phase2 = {}
        dcn_moves: Dict[Tuple[int, int], int] = {}
        for s in range(topology.num_slices):
            m = intra & (home == s)
            phase1[s] = (rows[m], dest[m])
        inter_rows = 0
        for t in range(topology.num_slices):
            segs_r, segs_d = [], []
            for s in range(topology.num_slices):
                if s == t:
                    continue
                m = (home == s) & (dest_slice == t)
                cnt = int(m.sum())
                if not cnt:
                    continue
                dcn_moves[(s, t)] = cnt * row_bytes
                inter_rows += cnt
                segs_r.append(rows[m])
                segs_d.append(dest[m])
            if segs_r:
                phase2[t] = (np.concatenate(segs_r),
                             np.concatenate(segs_d))
        run_phase(phase1, "intra", dcn_moves=dcn_moves)
        run_phase(phase2, "residue")
        tracer.instant("exchange.hierarchical", "exchange",
                       slices=topology.num_slices,
                       intra_rows=int(intra.sum()), inter_rows=inter_rows,
                       cross_slice_bytes=inter_rows * row_bytes,
                       degraded_slices=sorted(degraded))

    from sparkrdma_tpu.shuffle.external import merge_runs

    merged = []
    for d in range(n):
        if not runs[d]:
            merged.append(np.zeros((0, row_words), np.uint32))
        elif len(runs[d]) == 1:
            merged.append(runs[d][0])
        else:
            _, out = merge_runs([(_run_keys(r, key_words), r)
                                 for r in runs[d]])
            merged.append(out)
    return merged, rounds
