"""The unified exchange dataplane: one interface, two implementations,
a cost model choosing per stage.

The reference has exactly one accelerated dataplane (one-sided READs);
this framework grew two — the HOST dataplane (writer -> resolver ->
fetcher over the control plane, `shuffle/fetcher.py`) and the DEVICE
dataplane (ragged/chunked/ring ICI collectives, `parallel/exchange.py`).
Until now the choice was a config flag (`mesh_impl` / a mesh being
configured at all) and the device path still round-tripped rows through
host staging for the reduce-side sort. This module makes the ICI
all-to-all the *primary* dataplane for on-mesh stages:

* ``Exchange`` — the interface both planes implement: ``supports()``
  (can this plane carry the stage at all) and ``plan()`` (what would it
  cost / how would it run). The engine asks the COST MODEL
  (``select_dataplane``), not a flag.
* ``make_fused_step`` — the ``shard_map``-fused partition + exchange +
  local-sort step, generalized from ``models/terasort.py``'s
  ``make_terasort_step`` into a reusable op: rows are grouped to their
  destination device, exchanged over ICI (ragged all-to-all by default,
  dense/gather/ring fallbacks — `parallel/exchange.py`), and key-sorted
  on the receiving device, so partitions never leave HBM between the
  map output and the sorted reduce input. One-pass, no materialized
  intermediates — the redistribution-plan recipe of "Memory-efficient
  array redistribution through portable collective communication"
  (PAPERS.md).
* ``run_fused_exchange`` — the host driver: bounded rounds auto-sized
  from the HBM byte budget (replacing the static ``mesh_rows_per_round``
  knob), DOUBLE-BUFFERED so round ``k+1``'s collective is dispatched
  while round ``k``'s device sort runs and its results drain
  (``exchange.round`` spans + ``exchange.overlap`` instants prove the
  overlap in the trace).

Overflow (per-pair skew past the dense slot, or a receive past the
capacity headroom) raises ``OverflowError``; the ENGINE degrades exactly
the overflowing stage to the host dataplane instead of failing the job
(`engine.py` catches it and re-serves the stage through the fetcher).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from sparkrdma_tpu.utils import trace as trace_mod

DEVICE_PLANE = "device"
HOST_PLANE = "host"

# conservative per-device HBM footprint of one fused round, in row
# multiples: the input buffer + its destination-grouped copy (2 x cap)
# plus the receive buffer + its sorted copy (2 x out_factor x cap). The
# cost model sizes rounds so this fits the configured budget.
def _footprint_rows(row_bytes: int, out_factor: int) -> int:
    return row_bytes * (2 + 2 * out_factor)


@dataclass(frozen=True)
class StageProfile:
    """What the cost model knows about one stage's exchange.

    ``est_bytes``: committed map-output bytes across the stage (the
    driver/resolvers know this exactly at stage boundary — the same
    size column the adaptive planner consumes). ``row_bytes``: the
    device row stride. ``resident``: whether the stage's inputs can be
    staged straight into this process's HBM (in-process executors; a
    remote-only stage can't ride the local mesh). ``out_factor``:
    receive headroom the runner will allocate.
    """

    est_bytes: int
    row_bytes: int
    resident: bool = True
    out_factor: int = 2


@dataclass(frozen=True)
class ExchangePlan:
    """One stage's dataplane decision: which plane, which transport,
    and (device plane) the auto-sized round bound. ``rows_per_round``
    0 = one shot; ``reason`` is the cost model's audit trail (surfaced
    on the ``exchange.select`` trace instant)."""

    plane: str
    impl: str = ""
    rows_per_round: int = 0
    reason: str = ""


class Exchange:
    """The one interface both dataplanes implement.

    ``supports`` answers "can this plane carry the stage at all";
    ``plan`` answers "how would it run" (None = it shouldn't). The
    cost model (`select_dataplane`) composes the implementations; the
    engine only ever sees the resulting ``ExchangePlan``.
    """

    name: str = ""

    def supports(self, mesh, axis_name: str,
                 profile: StageProfile) -> Tuple[bool, str]:
        raise NotImplementedError

    def plan(self, mesh, axis_name: str, profile: StageProfile, *,
             impl: str = "auto",
             hbm_budget: int = 64 << 20) -> Optional[ExchangePlan]:
        raise NotImplementedError


class DeviceExchange(Exchange):
    """The ICI collective dataplane (fused partition+exchange+sort)."""

    name = DEVICE_PLANE

    def supports(self, mesh, axis_name, profile):
        if mesh is None:
            return False, "no mesh configured"
        if not profile.resident:
            return False, "stage inputs not resident to this process"
        return True, ""

    def plan(self, mesh, axis_name, profile, *, impl="auto",
             hbm_budget=64 << 20):
        ok, why = self.supports(mesh, axis_name, profile)
        if not ok:
            return None
        from sparkrdma_tpu.parallel.exchange import resolve_impl

        resolved = (impl if impl in ("ring", "ring_interpret")
                    else resolve_impl(mesh, impl, axis_name))
        n = mesh.shape[axis_name]
        rows_cap = auto_rows_per_round(profile.row_bytes, hbm_budget,
                                       profile.out_factor)
        if rows_cap < 1:
            return None  # budget can't hold even one row per device
        per_dev_rows = -(-max(0, profile.est_bytes)
                         // max(1, profile.row_bytes) // n) or 1
        if per_dev_rows <= rows_cap:
            return ExchangePlan(
                DEVICE_PLANE, resolved, 0,
                f"fits budget one-shot ({per_dev_rows} rows/dev <= "
                f"{rows_cap} cap)")
        return ExchangePlan(
            DEVICE_PLANE, resolved, rows_cap,
            f"chunked: {per_dev_rows} rows/dev over {rows_cap}-row "
            "budget rounds")


class HostExchange(Exchange):
    """The host dataplane (writer -> resolver -> fetcher): always
    available — it is the fallback plane, the mixed-version plane, and
    the off-mesh plane. The engine serves it through the ordinary
    ``getReader`` path with all its retry/CRC machinery."""

    name = HOST_PLANE

    def supports(self, mesh, axis_name, profile):
        return True, ""

    def plan(self, mesh, axis_name, profile, *, impl="auto",
             hbm_budget=64 << 20):
        return ExchangePlan(HOST_PLANE, "", 0, "host dataplane")


def auto_rows_per_round(row_bytes: int, hbm_budget: int,
                        out_factor: int = 2) -> int:
    """Rows per device per fused round that keep the round's footprint
    (input + grouped copy + receive + sorted copy) inside
    ``hbm_budget`` — the auto-sizing that replaces the static
    ``mesh_rows_per_round`` knob."""
    return max(0, int(hbm_budget) // _footprint_rows(max(1, row_bytes),
                                                     max(1, out_factor)))


_PLANES = (DeviceExchange(), HostExchange())


def select_dataplane(mesh, axis_name: str, profile: StageProfile, *,
                     impl: str = "auto", hbm_budget: int = 64 << 20,
                     override: str = "auto") -> ExchangePlan:
    """The per-stage cost model: device plane when the stage is mesh-
    resident and its bytes fit the HBM budget's round sizing, host
    plane otherwise. ``override`` short-circuits: ``"device"`` /
    ``"host"`` force a plane (the old ``mesh_impl``-flag behavior,
    kept as the escape hatch); ``"auto"`` asks the cost model."""
    if override not in ("auto", DEVICE_PLANE, HOST_PLANE):
        # a typo'd escape hatch must not silently ride the cost model
        # (same rule as make_fused_step's sort_mode)
        raise ValueError(f"unknown dataplane override {override!r} "
                         "(expected 'auto', 'device' or 'host')")
    if override == HOST_PLANE:
        return ExchangePlan(HOST_PLANE, "", 0, "forced by override")
    if override == DEVICE_PLANE:
        device = _PLANES[0]
        ok, why = device.supports(mesh, axis_name, profile)
        if not ok:
            # forcing a plane that declared itself unable to carry the
            # stage (no mesh, non-resident inputs) is a caller error —
            # silently running host under a "device" ask would be worse
            raise ValueError(f"dataplane override 'device': {why}")
        dev = device.plan(mesh, axis_name, profile, impl=impl,
                          hbm_budget=hbm_budget)
        if dev is not None:
            return dev
        # supported but the budget can't hold a row: run minimum rounds
        # rather than silently switching planes under an explicit ask
        from sparkrdma_tpu.parallel.exchange import resolve_impl

        resolved = (impl if impl in ("ring", "ring_interpret")
                    else resolve_impl(mesh, impl, axis_name))
        return ExchangePlan(DEVICE_PLANE, resolved, 1,
                            "forced by override (budget below one row)")
    for plane in _PLANES:
        plan = plane.plan(mesh, axis_name, profile, impl=impl,
                          hbm_budget=hbm_budget)
        if plan is not None:
            return plan
    return ExchangePlan(HOST_PLANE, "", 0, "no plane volunteered")


# ---------------------------------------------------------------------------
# the fused step: partition + exchange + local sort, one shard_map program
# ---------------------------------------------------------------------------

def _local_sort(rows, keys, sort_mode: str, write_back_keys: bool):
    """One local sort of full rows by (pre-masked) keys. The three
    strategies and their trade-offs are documented on
    ``models.terasort.TeraSortConfig.sort_mode`` (gather is
    latency-bound, the sorts bandwidth-bound; bench A/Bs them).

    ``keys`` is a TUPLE of u32 key vectors, most significant first —
    one entry for single-word keys (TeraSort), two for the u64 packed
    ``[lo, hi]`` row layout the mesh shuffle service moves (x64 is
    disabled in this runtime, so multi-word keys sort as multiple u32
    operands instead of one u64). ``write_back_keys`` overwrites
    column 0 with the sorted key (single-word layouts only — padding
    rows get their sentinel visible in the key column, the terasort
    contract)."""
    import jax
    import jax.numpy as jnp

    if sort_mode == "multisort":
        cols = tuple(rows[:, j] for j in range(rows.shape[1]))
        # is_stable: all three modes must order duplicate keys
        # identically (gather is stable via its iota tiebreak)
        out = jax.lax.sort(keys + cols, num_keys=len(keys),
                           is_stable=True)
        sorted_keys = out[0]
        sorted_rows = jnp.stack(out[len(keys):], axis=1)
    elif sort_mode == "colsort":
        # identical keys in every lane + a STABLE sort => every column
        # receives the same permutation, so rows stay intact without a
        # gather and without per-column operands. Multi-word keys run
        # as LSD radix passes: one stable per-lane sort per key word,
        # least significant first, remaining key words carried as
        # broadcast value operands so they ride the same permutation.
        carried = tuple(jnp.broadcast_to(k[:, None], rows.shape)
                        for k in keys)
        sorted_rows = rows
        for w in range(len(keys) - 1, -1, -1):
            out = jax.lax.sort((carried[w], sorted_rows)
                               + carried[:w] + carried[w + 1:],
                               dimension=0, num_keys=1, is_stable=True)
            sorted_rows = out[1]
            rest = out[2:]
            carried = rest[:w] + (out[0],) + rest[w:]
        sorted_keys = carried[0][:, 0]
    else:
        iota = jnp.arange(rows.shape[0], dtype=jnp.int32)
        # iota as a FINAL KEY makes the order total: duplicate keys
        # order by original position with no reliance on sort
        # stability (a value-operand iota under an unstable sort
        # could permute ties arbitrarily)
        out = jax.lax.sort(keys + (iota,), num_keys=len(keys) + 1)
        sorted_keys, order = out[0], out[-1]
        sorted_rows = jnp.take(rows, order, axis=0)
    if write_back_keys:
        # the key column already equals sorted_keys for valid rows;
        # only padding rows (sentinel keys) need the overwrite
        sorted_rows = sorted_rows.at[:, 0].set(sorted_keys)
    return sorted_rows, sorted_keys


def _row_keys(rows, key_words: int):
    """The per-row sort key vectors, most significant first: column 0
    for single-word u32 keys, ``(hi=col 1, lo=col 0)`` for the
    little-endian packed u64 layout ``shuffle/mesh_service.
    _rows_to_u32`` produces."""
    if key_words == 1:
        return (rows[:, 0],)
    return (rows[:, 1], rows[:, 0])


@functools.lru_cache(maxsize=64)
def make_fused_step(mesh, axis_name: str, row_words: int, *,
                    out_factor: int = 2, impl: str = "auto",
                    sort_mode: str = "gather", key_words: int = 1,
                    partition: str = "range"):
    """Build the jitted fused partition+exchange+local-sort step —
    ``models/terasort.py``'s one-round step generalized into the
    reusable device-plane op. Memoized per full signature so per-job
    callers compile once.

    ``partition`` selects how rows find their destination device:

    * ``"range"`` — uniform u32 key-range split (TeraSort): ONE key
      sort doubles as the destination grouping (range partition is
      monotonic in key), per-destination counts fall out of D-1 binary
      searches. ``step(rows)`` with ``rows: u32[D*cap, row_words]``
      sharded on the leading axis, key = column 0.
    * ``"dest"`` — caller-computed destinations (any partitioner):
      ``step(rows, dest)`` with ``dest: i32[D*cap]``; ``dest < 0``
      marks padding rows (not sent). Rows group by destination, ride
      the exchange, and key-sort on the receiving device
      (``key_words`` 1 = u32 column 0, 2 = u64 packed columns [0,1]).

    Returns ``(sorted_rows, recv_counts[D, D], overflowed[D])`` with
    each device's rows key-sorted, padding at the end (strip with
    ``recv_counts[d].sum()``). ``overflowed[d]`` flags a receive past
    the ``out_factor`` headroom or a dense-slot pair overflow — results
    there are truncated and MUST not be trusted (the engine's remedy:
    degrade the stage to the host dataplane).
    """
    import jax
    import jax.numpy as jnp

    from jax.sharding import PartitionSpec as P

    from sparkrdma_tpu.ops.partition import uniform_splitters
    from sparkrdma_tpu.parallel.exchange import (
        group_by_destination,
        ragged_exchange_shard,
        resolve_impl,
    )
    from sparkrdma_tpu.utils.compat import shard_map

    if sort_mode not in ("gather", "multisort", "colsort"):
        # a typo must not silently measure (and mislabel) the gather path
        raise ValueError(f"unknown sort_mode {sort_mode!r} "
                         "(expected 'gather', 'multisort' or 'colsort')")
    if partition not in ("range", "dest"):
        raise ValueError(f"unknown partition {partition!r} "
                         "(expected 'range' or 'dest')")
    if partition == "range" and key_words != 1:
        raise ValueError("range partitioning is defined on single-word "
                         "u32 keys")
    n = mesh.shape[axis_name]
    impl = (impl if impl in ("ring", "ring_interpret")
            else resolve_impl(mesh, impl, axis_name))
    spec = P(axis_name)
    sentinel = jnp.uint32(0xFFFFFFFF)
    write_back = key_words == 1
    splitters = uniform_splitters(n, jnp.uint32) if partition == "range" \
        else None

    def sort_received(received, total):
        """Key-sort received rows with pads (index >= total) masked to
        the sentinel on every key word so they sort last; stable order
        within equal keys is arrival (source-major) order."""
        idx = jnp.arange(received.shape[0], dtype=jnp.int32)
        keys = tuple(jnp.where(idx < total, k, sentinel)
                     for k in _row_keys(received, key_words))
        return _local_sort(received, keys, sort_mode, write_back)[0]

    # pallas interpret-mode outputs confuse the vma checker when mixed
    # with collectives; disable it ONLY for the ring transports (same
    # rule as make_chunked_exchange / make_shuffle_exchange)
    in_specs = (spec,) if partition == "range" else (spec, spec)
    shard_kwargs = dict(mesh=mesh, in_specs=in_specs,
                        out_specs=(spec, spec, spec))
    if impl in ("ring", "ring_interpret"):
        shard_kwargs["check_vma"] = False

    if partition == "range":

        @jax.jit
        @functools.partial(shard_map, **shard_kwargs)
        def step(rows):
            keys = (rows[:, 0],)
            if n == 1:
                # single-device: no exchange, one sort is the whole job
                sorted_rows, _ = _local_sort(rows, keys, sort_mode,
                                             write_back)
                counts = jnp.array([[rows.shape[0]]], dtype=jnp.int32)
                return sorted_rows, counts, jnp.zeros((1,), bool)

            # Local sort by KEY once: range partition is monotonic in
            # key, so key-sorted rows are destination-grouped for free —
            # this replaces the separate argsort-by-destination + gather
            # entirely.
            grouped, sorted_keys = _local_sort(rows, keys, sort_mode,
                                               write_back)
            # per-destination counts: D-1 binary searches on sorted keys
            bounds = jnp.searchsorted(sorted_keys, splitters, side="left")
            bounds = jnp.concatenate([
                jnp.zeros(1, bounds.dtype), bounds,
                jnp.array([rows.shape[0]], bounds.dtype)])
            counts = jnp.diff(bounds).astype(jnp.int32)

            output = jnp.zeros((rows.shape[0] * out_factor, row_words),
                               dtype=rows.dtype)
            received, recv_counts, _, overflowed = ragged_exchange_shard(
                grouped, counts, axis_name, output=output, impl=impl)
            sorted_rows = sort_received(received, recv_counts.sum())
            return sorted_rows, recv_counts[None], overflowed[None]

        return step

    @jax.jit
    @functools.partial(shard_map, **shard_kwargs)
    def step(rows, dest):
        dest = dest.reshape(-1)
        if n == 1:
            valid = dest >= 0
            idx_keys = tuple(jnp.where(valid, k, sentinel)
                             for k in _row_keys(rows, key_words))
            sorted_rows, _ = _local_sort(rows, idx_keys, sort_mode,
                                         write_back)
            counts = jnp.sum(valid).astype(jnp.int32).reshape(1, 1)
            return sorted_rows, counts, jnp.zeros((1,), bool)
        grouped, counts = group_by_destination(rows, dest, n)
        output = jnp.zeros((rows.shape[0] * out_factor, row_words),
                           dtype=rows.dtype)
        received, recv_counts, _, overflowed = ragged_exchange_shard(
            grouped, counts, axis_name, output=output, impl=impl)
        sorted_rows = sort_received(received, recv_counts.sum())
        return sorted_rows, recv_counts[None], overflowed[None]

    return step


# ---------------------------------------------------------------------------
# the overlapped host driver
# ---------------------------------------------------------------------------

def run_fused_exchange(mesh, axis_name: str, rows: np.ndarray,
                       dest: np.ndarray, *, key_words: int = 2,
                       rows_per_round: int = 0, out_factor: int = 2,
                       impl: str = "auto", sort_mode: str = "gather",
                       tracer=None, pipeline_rounds: bool = True,
                       ) -> Tuple[List[np.ndarray], int]:
    """Drive the fused step over fully-materialized arrays: bounded
    rounds of ``rows_per_round`` rows per device (0 = one shot) through
    ``run_fused_exchange_rounds``. ``rows: u32[N, W]`` (unpadded),
    ``dest: i32[N]`` destination device per row. Callers whose data
    streams off disk should feed ``run_fused_exchange_rounds`` a block
    generator instead, so host staging holds one round."""
    n = mesh.shape[axis_name]
    row_words = rows.shape[1]
    if len(rows) == 0:
        return [np.zeros((0, row_words), np.uint32) for _ in range(n)], 0
    cap = rows_per_round if rows_per_round > 0 else -(-len(rows) // n)
    per_round = cap * n

    def blocks():
        for start in range(0, len(rows), per_round):
            yield (rows[start:start + per_round],
                   dest[start:start + per_round])

    return run_fused_exchange_rounds(
        mesh, axis_name, blocks(), row_words, cap, key_words=key_words,
        out_factor=out_factor, impl=impl, sort_mode=sort_mode,
        tracer=tracer, pipeline_rounds=pipeline_rounds)


def run_fused_exchange_rounds(mesh, axis_name: str, blocks,
                              row_words: int, rows_per_round: int, *,
                              key_words: int = 2, out_factor: int = 2,
                              impl: str = "auto",
                              sort_mode: str = "gather", tracer=None,
                              pipeline_rounds: bool = True,
                              ) -> Tuple[List[np.ndarray], int]:
    """Drive the fused step over a stream of round blocks: ``blocks``
    yields ``(rows u32[<= rows_per_round * D, row_words], dest i32)``
    per round, so HOST staging holds one round (plus the in-flight one
    when pipelined) no matter how large the stage — the bounded-staging
    discipline ``run_mesh_reduce_streamed`` had, kept. Rounds are
    DOUBLE-BUFFERED: round ``k+1``'s collective is dispatched while
    round ``k``'s on-device sort runs and its results drain
    (``exchange.round`` spans per round, ``exchange.overlap`` instants
    when a dispatch preceded the previous round's collection).

    Returns ``(per_device_sorted_rows, rounds)``: device d's rows
    key-sorted (u64 packed keys when ``key_words == 2``), rounds merged
    via the tournament merge. Raises ``OverflowError`` on any round's
    receive overflow — the caller (engine) degrades the stage to the
    host dataplane.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkrdma_tpu.parallel.exchange import record_exchange

    tracer = tracer if tracer is not None else trace_mod.NULL
    n = mesh.shape[axis_name]
    per_round = max(1, rows_per_round) * n
    step = make_fused_step(mesh, axis_name, row_words,
                           out_factor=out_factor, impl=impl,
                           sort_mode=sort_mode, key_words=key_words,
                           partition="dest")
    sharding = NamedSharding(mesh, P(axis_name))
    runs: List[list] = [[] for _ in range(n)]

    def dispatch(r: int, chunk: np.ndarray, dchunk: np.ndarray):
        """Stage one round (pad to the static shape) and launch its
        collective; jax dispatch is async — no blocking here."""
        with tracer.span("exchange.round", "exchange", round=r,
                         rows=len(chunk)):
            rows_p = np.zeros((per_round, row_words), np.uint32)
            rows_p[:len(chunk)] = chunk
            dest_p = np.full(per_round, -1, np.int32)
            dest_p[:len(chunk)] = dchunk
            out = step(jax.device_put(rows_p, sharding),
                       jax.device_put(dest_p, sharding))
        record_exchange(len(chunk))
        return out

    def collect(results) -> None:
        # np.asarray blocks on the device step (exchange + sort)
        out, counts, overflowed = results
        if np.asarray(overflowed).any():
            raise OverflowError(
                "fused exchange receive overflow: skew exceeds the "
                "out_factor headroom for this round size — the engine "
                "degrades the stage to the host dataplane")
        out = np.asarray(out).reshape(n, -1, row_words)
        counts = np.asarray(counts)
        for d in range(n):
            # .copy(): a view would pin the padded round buffer across
            # all rounds
            runs[d].append(out[d][:int(counts[d].sum())].copy())

    rounds = 0
    if pipeline_rounds:
        in_flight = None
        for chunk, dchunk in blocks:
            nxt = dispatch(rounds, chunk, dchunk)
            if in_flight is not None:
                tracer.instant("exchange.overlap", "exchange",
                               dispatched=rounds, collecting=rounds - 1)
                collect(in_flight)
            in_flight = nxt
            rounds += 1
        if in_flight is not None:
            collect(in_flight)
    else:
        for chunk, dchunk in blocks:
            collect(dispatch(rounds, chunk, dchunk))
            rounds += 1

    if rounds == 0:
        return [np.zeros((0, row_words), np.uint32) for _ in range(n)], 0
    if rounds == 1:
        return [runs[d][0] for d in range(n)], 1

    from sparkrdma_tpu.shuffle.external import merge_runs

    def run_keys(r: np.ndarray) -> np.ndarray:
        if key_words == 2:
            return r[:, :2].copy().view(np.uint64).reshape(-1)
        return r[:, 0]

    merged = []
    for d in range(n):
        if not runs[d]:
            merged.append(np.zeros((0, row_words), np.uint32))
            continue
        _, out = merge_runs([(run_keys(r), r) for r in runs[d]])
        merged.append(out)
    return merged, rounds
