"""Control-plane RPC message framing.

Re-design of the reference's ``RdmaRpcMsg`` (scala/RdmaRpcMsg.scala): a tiny
self-describing frame — ``[total_length:4][msg_type:4][payload]`` — chopped
into fixed-size segments so each segment fits one pre-posted receive buffer
(scala/RdmaRpcMsg.scala:40-58: segments of ``recvWrSize``). The reference
needs segmentation because RDMA RECV buffers are fixed-size; we keep it as
the flow-control accounting unit (credits are per segment) and as the wire
format for datagram-ish transports, while the TCP transport can also write a
frame contiguously.

The reference defines exactly two message types — Hello (executor→driver,
scala/RdmaRpcMsg.scala:81-112) and Announce (driver→all, 114-173). The TPU
control plane adds table/location/publish messages in
``sparkrdma_tpu.parallel.rpc`` via the same registry.
"""

from __future__ import annotations

import struct
from typing import ClassVar, Dict, Iterator, List, Optional, Type

from sparkrdma_tpu.utils.ids import ShuffleManagerId

HEADER = struct.Struct("<II")  # (total_length incl. header, msg_type)

_REGISTRY: Dict[int, Type["RpcMsg"]] = {}

# THE authoritative wire-number table: every message class's type id, in
# one place, keyed by class name. ``@register()`` call sites look their
# id up here, so a new message means one new row — the id can never be
# assigned twice or drift between the class and a doc. The analyzer
# suite (sparkrdma_tpu/analysis/wire.py) asserts the live registry
# matches this table exactly (unique, dense over the reserved gaps) and
# regenerates the message-ID table in docs/CONFIG.md from it.
WIRE_IDS: Dict[str, int] = {
    "HelloMsg": 1,
    "AnnounceMsg": 2,
    "PublishMsg": 3,
    # 4 reserved: was the publish ack (publish is one-sided now)
    "FetchTableReq": 5,
    "FetchTableResp": 6,
    "FetchOutputReq": 7,
    "FetchOutputResp": 8,
    "FetchBlocksReq": 9,
    "FetchBlocksResp": 10,
    "RunTaskReq": 11,
    "RunTaskResp": 12,
    "CreditReport": 13,
    "GetBroadcastReq": 14,
    "GetBroadcastResp": 15,
    "PingMsg": 16,
    "PongMsg": 17,
    "FetchOutputsReq": 18,
    "FetchOutputsResp": 19,
    "EpochBumpMsg": 20,
    "ShardMapMsg": 21,
    "ShardEntryMsg": 22,
    "FetchShardReq": 23,
    "FetchShardResp": 24,
    "ReducePlanMsg": 25,
    "FetchPlanReq": 26,
    "FetchPlanResp": 27,
    "PushBlocksReq": 28,
    "PushBlocksResp": 29,
    "FinalizeSegmentsReq": 30,
    "FinalizeSegmentsResp": 31,
    "MergedPublishMsg": 32,
    "FetchMergedReq": 33,
    "FetchMergedResp": 34,
    "TenantMapMsg": 35,
    "JoinMsg": 36,
    "MembershipBumpMsg": 37,
    "DrainReq": 38,
    "DrainResp": 39,
    "PushPlannedReq": 40,
    "PushPlannedResp": 41,
    # driver HA (shuffle/ha.py): the op-log replication stream and the
    # lease takeover announcement — one-sided pushes like everything
    # else on the announce channel
    "OpLogAppendMsg": 42,
    "SnapshotMsg": 43,
    "StandbyHelloMsg": 44,
    "TakeoverMsg": 45,
    # partitioned metadata ownership (shuffle/shard_plane.py): the
    # direct-to-owner write path, the owner->driver convergence batch,
    # the per-shard op-log stream, and the handoff announcement
    "ShardPublishMsg": 46,
    "ShardMergedPublishMsg": 47,
    "ShardBatchMsg": 48,
    "ShardOpMsg": 49,
    "ShardHandoffMsg": 50,
    # disaggregated cold tier (shuffle/cold_tier.py): the one-sided
    # blob publish and the reducer's directory pull — the TIERED
    # location class resolved last, before re-execution
    "TieredPublishMsg": 51,
    "FetchTieredReq": 52,
    "FetchTieredResp": 53,
}

# Ids deliberately absent from the dense 1..max range, with the reason
# pinned here so the density check can never be silenced by accident.
RESERVED_WIRE_IDS: Dict[int, str] = {
    4: "was the publish ack; publish is one-sided like the reference's "
       "RDMA WRITE, nothing acks",
}


def register(msg_type: Optional[int] = None):
    """Class decorator registering an ``RpcMsg`` subclass for decode.

    With no argument (every production call site) the wire number comes
    from ``WIRE_IDS[cls.__name__]`` — the one table above. An explicit
    id remains accepted for test/fixture message types outside it.
    """
    def deco(cls: Type["RpcMsg"]):
        mt = msg_type
        if mt is None:
            if cls.__name__ not in WIRE_IDS:
                raise ValueError(f"{cls.__name__} has no WIRE_IDS row")
            mt = WIRE_IDS[cls.__name__]
        if mt in _REGISTRY:
            raise ValueError(f"duplicate msg_type {mt}")
        cls.MSG_TYPE = mt
        _REGISTRY[mt] = cls
        return cls
    return deco


def registry() -> Dict[int, Type["RpcMsg"]]:
    """Snapshot of the live decode registry (analyzer + doc generation)."""
    return dict(_REGISTRY)


class RpcMsg:
    """Base frame. Subclasses implement payload (de)serialization."""

    MSG_TYPE: ClassVar[int] = -1

    def payload(self) -> bytes:
        raise NotImplementedError

    @classmethod
    def from_payload(cls, payload: bytes) -> "RpcMsg":
        raise NotImplementedError

    def encode(self) -> bytes:
        body = self.payload()
        return HEADER.pack(HEADER.size + len(body), self.MSG_TYPE) + body


def decode_message(frame: bytes) -> RpcMsg:
    """Decode one complete frame (scala/RdmaRpcMsg.scala:64-78)."""
    total, msg_type = HEADER.unpack_from(frame, 0)
    if total != len(frame):
        raise ValueError(f"frame length mismatch: header={total} actual={len(frame)}")
    cls = _REGISTRY.get(msg_type)
    if cls is None:
        raise ValueError(f"unknown msg_type {msg_type}")
    return cls.from_payload(frame[HEADER.size:total])


def segments(frame: bytes, seg_size: int) -> List[bytes]:
    """Chop an encoded frame into ≤seg_size chunks
    (scala/RdmaRpcMsg.scala:42-58)."""
    if seg_size < HEADER.size + 1:
        raise ValueError("segment size too small")
    return [frame[i:i + seg_size] for i in range(0, len(frame), seg_size)]


class Reassembler:
    """Streaming decoder: feed arbitrary chunks, yields complete messages.

    Covers both the segmented path and a TCP byte stream.
    """

    def __init__(self, max_frame: int = 1 << 30):
        self._buf = bytearray()
        self._max_frame = max_frame

    def feed(self, chunk: bytes) -> Iterator[RpcMsg]:
        self._buf.extend(chunk)
        while len(self._buf) >= HEADER.size:
            total, _ = HEADER.unpack_from(self._buf, 0)
            if total < HEADER.size or total > self._max_frame:
                raise ValueError(f"bad frame length {total}")
            if len(self._buf) < total:
                return
            frame = bytes(self._buf[:total])
            del self._buf[:total]
            yield decode_message(frame)


@register()
class HelloMsg(RpcMsg):
    """Executor → driver introduction (scala/RdmaRpcMsg.scala:81-112)."""

    def __init__(self, manager_id: ShuffleManagerId):
        self.manager_id = manager_id

    def payload(self) -> bytes:
        return self.manager_id.serialize()

    @classmethod
    def from_payload(cls, payload: bytes) -> "HelloMsg":
        mid, _ = ShuffleManagerId.deserialize(payload)
        return cls(mid)

    def __eq__(self, other):
        return isinstance(other, HelloMsg) and self.manager_id == other.manager_id


@register()
class AnnounceMsg(RpcMsg):
    """Driver → all executors membership broadcast
    (scala/RdmaRpcMsg.scala:114-173).

    ``epoch`` totally orders broadcasts: concurrent announce threads can
    deliver out of order, and tombstoning changes list *content* without
    changing length, so receivers keep the highest epoch, not the longest
    list."""

    def __init__(self, manager_ids: List[ShuffleManagerId], epoch: int = 0):
        self.manager_ids = list(manager_ids)
        self.epoch = epoch

    def payload(self) -> bytes:
        out = [struct.pack("<QI", self.epoch, len(self.manager_ids))]
        out += [m.serialize() for m in self.manager_ids]
        return b"".join(out)

    @classmethod
    def from_payload(cls, payload: bytes) -> "AnnounceMsg":
        epoch, n = struct.unpack_from("<QI", payload, 0)
        off = 12
        ids = []
        for _ in range(n):
            mid, off = ShuffleManagerId.deserialize(payload, off)
            ids.append(mid)
        return cls(ids, epoch)

    def __eq__(self, other):
        return (isinstance(other, AnnounceMsg)
                and self.manager_ids == other.manager_ids
                and self.epoch == other.epoch)
