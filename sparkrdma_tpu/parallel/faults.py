"""Deterministic fault injection for the control-plane transport AND the
storage dataplane.

The chaos harness the hardened failure path is tested with: a
:class:`FaultInjector` wraps a live :class:`ConnectionCache` (and every
``Connection`` it mints) and injects seeded, scenario-scripted faults at
the exact layers real failures enter — the dial, the send, and the
receive dispatch — so every failure mode the fetch path must survive
(connect refusal, mid-stream disconnect, response delay, payload
bit-flips, blackhole/partition) is reproducible in-process over plain
sockets.

Its sibling :class:`StorageFaultInjector` does the same for the disk
half of the dataplane: the writer's spill/merge writes, the resolver's
rename-commit and index/sidecar writes, mmap-opens, and serve-time
reads all consult cheap module-level hook points
(:func:`storage_check` / :func:`storage_write_cap` /
:func:`storage_corrupt` — no-ops until an injector is installed) so
``ENOSPC``, ``EIO``, torn/short writes, slow-disk stalls, and at-rest
corruption are reproducible on the production code paths. The serving
path has no server CPU to notice a bad block (the committed file is
mmap'd and served one-sided, PAPER §0), so integrity and fencing live
in the data and commit protocol — this injector is how that protocol
is proven.

Faults match on ``(kind, peer, message type, direction)`` with
``after``/``times`` windows and an optional per-match probability drawn
from the injector's seeded RNG, so probabilistic scenarios replay
exactly from their seed (``scripts/run_chaos.sh`` prints the seed of a
failing sweep for replay). The shim leaves everything above it untouched
— endpoints, fetcher, recovery — which is the point: the failure path
under test is the production one, not a mock of it.

The reference has no equivalent; its fault story was never testable
below "kill a JVM and watch Spark recompute" (SURVEY §7 hard part #4).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple, Type

from sparkrdma_tpu.parallel.transport import (
    Connection,
    ConnectionCache,
    TransportError,
)

log = logging.getLogger(__name__)

Addr = Tuple[str, int]

# Fault kinds.
REFUSE_CONNECT = "refuse_connect"  # the dial raises ConnectionRefusedError
DISCONNECT = "disconnect"          # the connection closes when the match
#                                    fires (recv: response lost + whole
#                                    window failed; send: reset mid-send)
DELAY = "delay"                    # hold the matched message delay_s on
#                                    the delivering/sending thread
CORRUPT = "corrupt"                # flip bits of the matched message's
#                                    payload attribute before delivery
BLACKHOLE = "blackhole"            # drop the matched message silently
#                                    (partition: the requester's deadline
#                                    or heartbeat owns detection)

KINDS = (REFUSE_CONNECT, DISCONNECT, DELAY, CORRUPT, BLACKHOLE)


@dataclass
class Fault:
    """One scripted fault. Matching is AND across the set criteria;
    unset criteria match anything. ``after`` skips the first N matches
    (arm the fault mid-run), ``times`` bounds firings (a burst),
    ``prob`` gates each firing on the injector's seeded RNG."""

    kind: str
    peer: Optional[Addr] = None
    msg_type: Optional[Type] = None   # ignored by refuse_connect
    on: str = "recv"                  # "recv" | "send" (non-connect kinds)
    after: int = 0
    times: Optional[int] = None
    prob: float = 1.0
    delay_s: float = 0.0              # DELAY
    flip_bits: int = 1                # CORRUPT
    attr: str = "data"                # CORRUPT: message field to mutate
    seen: int = 0                     # matches observed (post-filter)
    fired: int = 0                    # faults actually injected

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")


class FaultInjector:
    """Seeded chaos shim over one or more ``ConnectionCache``s.

    Thread-safe: connection reader threads, fetch threads, and the
    heartbeat monitor all consult the same fault table. ``install`` is
    reversible per cache (``uninstall``); connections already wrapped
    stay wrapped until closed, which chaos tests do anyway.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self._lock = threading.RLock()
        self._faults: List[Fault] = []
        self._installed: List[Tuple[ConnectionCache, Callable]] = []
        self.fired: Dict[str, int] = {}

    # -- scripting -------------------------------------------------------

    def add(self, kind: str, **kw) -> Fault:
        fault = Fault(kind, **kw)
        with self._lock:
            self._faults.append(fault)
        return fault

    def clear(self) -> None:
        with self._lock:
            self._faults.clear()

    def fired_count(self, kind: Optional[str] = None) -> int:
        with self._lock:
            if kind is not None:
                return self.fired.get(kind, 0)
            return sum(self.fired.values())

    # -- installation ----------------------------------------------------

    def install(self, cache: ConnectionCache) -> None:
        """Shadow the cache's per-attempt ``_dial`` (connect faults) and
        its ``_connect`` (to wrap each minted ``Connection``'s send and
        dispatch). Idempotent per cache."""
        with self._lock:
            if any(c is cache for c, _ in self._installed):
                return

            orig_dial = cache._dial
            orig_connect = cache._connect

            def dial(addr, timeout, _orig=orig_dial):
                if self._match(REFUSE_CONNECT, peer=addr) is not None:
                    raise ConnectionRefusedError(
                        f"fault injection: connect to {addr} refused")
                return _orig(addr, timeout)

            def connect(addr, _orig=orig_connect):
                conn = _orig(addr)
                self._wrap_conn(conn, addr)
                return conn

            orig_get = cache.get

            def get(host, port, _orig=orig_get):
                # ensure-wrap on every lookup (idempotent): a dial that
                # was already in flight when install() ran — prewarm
                # threads race exactly this way — inserts its connection
                # past both the connect shim and the snapshot below
                conn = _orig(host, port)
                self._wrap_conn(conn, (host, port))
                return conn

            # instance attributes shadow the class methods; _connect's
            # internal self._dial lookup resolves to the shim
            cache._dial = dial
            cache._connect = connect
            cache.get = get

            def restore(cache=cache):
                cache.__dict__.pop("_dial", None)
                cache.__dict__.pop("_connect", None)
                cache.__dict__.pop("get", None)

            self._installed.append((cache, restore))
            # connections minted before install get wrapped too, so a
            # mid-run install sees pre-warmed/cached peers
            with cache._lock:
                existing = list(cache._conns.items())
        for addr, conn in existing:
            self._wrap_conn(conn, addr)

    def install_endpoint(self, endpoint) -> None:
        """Convenience: shim an endpoint's client-side connection cache
        (covers fetches, heartbeats, and driver traffic it originates)."""
        self.install(endpoint._clients)

    def uninstall(self) -> None:
        with self._lock:
            installed, self._installed = self._installed, []
        for _cache, restore in installed:
            restore()

    # -- fault application -----------------------------------------------

    def _wrap_conn(self, conn: Connection, addr: Addr) -> None:
        if getattr(conn, "_fault_wrapped", False):
            return
        conn._fault_wrapped = True
        orig_dispatch = conn._dispatch
        orig_send = conn.send

        def dispatch(msg, _orig=orig_dispatch, _addr=addr):
            fault = self._match(DELAY, peer=_addr, msg=msg, on="recv")
            if fault is not None:
                # on the reader thread on purpose: later messages on this
                # connection stall behind the delay, exactly like a
                # congested or GC-pausing peer — the window the
                # claim-back-race tests pin open
                time.sleep(fault.delay_s)
            if self._match(BLACKHOLE, peer=_addr, msg=msg,
                           on="recv") is not None:
                log.debug("fault injection: blackholed %s from %s",
                          type(msg).__name__, _addr)
                return
            fault = self._match(CORRUPT, peer=_addr, msg=msg, on="recv")
            if fault is not None:
                self._corrupt(msg, fault)
            if self._match(DISCONNECT, peer=_addr, msg=msg,
                           on="recv") is not None:
                log.debug("fault injection: disconnect from %s before "
                          "delivering %s", _addr, type(msg).__name__)
                conn.close()
                return
            _orig(msg)

        def send(msg, _orig=orig_send, _addr=addr):
            fault = self._match(DELAY, peer=_addr, msg=msg, on="send")
            if fault is not None:
                time.sleep(fault.delay_s)
            if self._match(BLACKHOLE, peer=_addr, msg=msg,
                           on="send") is not None:
                return  # peer never sees it; the deadline owns the rest
            if self._match(DISCONNECT, peer=_addr, msg=msg,
                           on="send") is not None:
                conn.close()
                raise TransportError(
                    f"{conn.name}: fault injection: reset mid-send")
            _orig(msg)

        conn._dispatch = dispatch
        conn.send = send

    def _corrupt(self, msg, fault: Fault) -> None:
        data = getattr(msg, fault.attr, None)
        if not data:
            return
        buf = bytearray(data)
        for _ in range(max(1, fault.flip_bits)):
            with self._lock:
                i = self.rng.randrange(len(buf))
                bit = 1 << self.rng.randrange(8)
            buf[i] ^= bit
        setattr(msg, fault.attr, bytes(buf))
        log.debug("fault injection: flipped %d bit(s) in %s.%s",
                  max(1, fault.flip_bits), type(msg).__name__, fault.attr)

    def _match(self, kind: str, peer: Addr, msg=None,
               on: str = "recv") -> Optional[Fault]:
        with self._lock:
            for fault in self._faults:
                if fault.kind != kind:
                    continue
                if kind != REFUSE_CONNECT and fault.on != on:
                    continue
                if fault.peer is not None and fault.peer != peer:
                    continue
                if (fault.msg_type is not None
                        and not isinstance(msg, fault.msg_type)):
                    continue
                fault.seen += 1
                if fault.seen <= fault.after:
                    continue
                if fault.times is not None and fault.fired >= fault.times:
                    continue
                if fault.prob < 1.0 and self.rng.random() >= fault.prob:
                    continue
                fault.fired += 1
                self.fired[kind] = self.fired.get(kind, 0) + 1
                return fault
        return None


# -- storage faults -------------------------------------------------------

# Storage fault kinds.
ENOSPC = "enospc"              # the op raises OSError(ENOSPC)
EIO = "eio"                    # the op raises OSError(EIO)
TORN_WRITE = "torn_write"      # the write lands SHORT (torn_bytes of it)
#                                then raises OSError(EIO) — the crash
#                                window a rename-commit must mask
SLOW_DISK = "slow_disk"        # hold the op delay_s on the calling thread
CORRUPT_AT_REST = "corrupt_at_rest"  # flip bits in the target file AFTER
#                                the op completes (bit-rot of committed
#                                bytes; the CRC sidecar owns detection)

STORAGE_KINDS = (ENOSPC, EIO, TORN_WRITE, SLOW_DISK, CORRUPT_AT_REST)

# Hook-point op names (the layers real disk failures enter):
#   spill_write   writer background spill file writes
#   merge_write   writer close()-time merge into the data tmp
#   commit        resolver rename-commit of the data file (also the
#                 corrupt-at-rest hook: fires on the COMMITTED file)
#   index_write   resolver index/sidecar durability writes
#   mmap_open     SpillFile/block-server mapping of a committed file
#   serve_read    resolver serve-time block reads


@dataclass
class StorageFault:
    """One scripted storage fault. Matching is AND across set criteria
    (op name, path substring); ``after``/``times``/``prob`` behave as on
    :class:`Fault`."""

    kind: str
    op: Optional[str] = None          # None matches any op
    path_substr: Optional[str] = None
    after: int = 0
    times: Optional[int] = None
    prob: float = 1.0
    delay_s: float = 0.0              # SLOW_DISK
    torn_bytes: int = 64              # TORN_WRITE: bytes that land
    flip_bits: int = 1                # CORRUPT_AT_REST
    seen: int = 0
    fired: int = 0

    def __post_init__(self):
        if self.kind not in STORAGE_KINDS:
            raise ValueError(f"unknown storage fault kind {self.kind!r}")


class StorageFaultInjector:
    """Seeded chaos shim over the storage dataplane.

    Installed process-globally (``install()``/``uninstall()``): the
    writer, resolver, and block server consult the module hook on every
    guarded file op, which is a single ``is None`` check when no
    injector is active. Same ``after``/``times``/``prob`` windows and
    seeded RNG as the transport injector, so a failing
    ``scripts/run_chaos.sh CHAOS_DISK=1`` sweep replays from its seed.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self._lock = threading.RLock()
        self._faults: List[StorageFault] = []
        self.fired: Dict[str, int] = {}

    # -- scripting -------------------------------------------------------

    def add(self, kind: str, **kw) -> StorageFault:
        fault = StorageFault(kind, **kw)
        with self._lock:
            self._faults.append(fault)
        return fault

    def clear(self) -> None:
        with self._lock:
            self._faults.clear()

    def fired_count(self, kind: Optional[str] = None) -> int:
        with self._lock:
            if kind is not None:
                return self.fired.get(kind, 0)
            return sum(self.fired.values())

    # -- installation ----------------------------------------------------

    def install(self) -> None:
        global _STORAGE
        _STORAGE = self

    def uninstall(self) -> None:
        global _STORAGE
        if _STORAGE is self:
            _STORAGE = None

    # -- fault application (called from the module hooks) ----------------

    def check(self, op: str, path: str) -> None:
        """Raise/stall for error-kind faults matching ``(op, path)``."""
        import errno

        fault = self._match(SLOW_DISK, op, path)
        if fault is not None:
            time.sleep(fault.delay_s)
        fault = self._match(ENOSPC, op, path)
        if fault is not None:
            raise OSError(errno.ENOSPC,
                          f"fault injection: no space ({op})", path)
        fault = self._match(EIO, op, path)
        if fault is not None:
            raise OSError(errno.EIO, f"fault injection: I/O error ({op})",
                          path)

    def write_cap(self, op: str, path: str, nbytes: int) -> Optional[int]:
        """TORN_WRITE: how many of ``nbytes`` should actually land before
        the write fails (None = no fault, write everything)."""
        fault = self._match(TORN_WRITE, op, path)
        if fault is None:
            return None
        return max(0, min(fault.torn_bytes, nbytes - 1))

    def corrupt(self, op: str, path: str) -> bool:
        """CORRUPT_AT_REST: flip seeded bits in ``path`` in place (the
        sidecar was already written from the clean bytes — this is rot
        AFTER commit). Returns True if a fault fired."""
        fault = self._match(CORRUPT_AT_REST, op, path)
        if fault is None:
            return False
        try:
            size = os.path.getsize(path)
        except OSError:
            return False
        if size == 0:
            return False
        with open(path, "r+b") as f:
            for _ in range(max(1, fault.flip_bits)):
                with self._lock:
                    pos = self.rng.randrange(size)
                    bit = 1 << self.rng.randrange(8)
                f.seek(pos)
                b = f.read(1)
                f.seek(pos)
                f.write(bytes([b[0] ^ bit]))
        log.debug("fault injection: flipped %d bit(s) at rest in %s",
                  max(1, fault.flip_bits), path)
        return True

    def _match(self, kind: str, op: str, path: str) -> Optional[StorageFault]:
        with self._lock:
            for fault in self._faults:
                if fault.kind != kind:
                    continue
                if fault.op is not None and fault.op != op:
                    continue
                if (fault.path_substr is not None
                        and fault.path_substr not in path):
                    continue
                fault.seen += 1
                if fault.seen <= fault.after:
                    continue
                if fault.times is not None and fault.fired >= fault.times:
                    continue
                if fault.prob < 1.0 and self.rng.random() >= fault.prob:
                    continue
                fault.fired += 1
                self.fired[kind] = self.fired.get(kind, 0) + 1
                return fault
        return None


# Process-global storage injector (None = no chaos, hooks are no-ops).
_STORAGE: Optional[StorageFaultInjector] = None


def storage_check(op: str, path: str) -> None:
    """Production hook: raise/stall if a storage fault matches. A single
    attribute load + ``is None`` test when no injector is installed."""
    inj = _STORAGE
    if inj is not None:
        inj.check(op, path)


def storage_write_cap(op: str, path: str, nbytes: int) -> Optional[int]:
    """Production hook for torn/short writes: bytes to land before
    failing, or None for a full write."""
    inj = _STORAGE
    if inj is not None:
        return inj.write_cap(op, path, nbytes)
    return None


def storage_corrupt(op: str, path: str) -> None:
    """Production hook: flip bits at rest in ``path`` if a
    CORRUPT_AT_REST fault matches (no-op otherwise)."""
    inj = _STORAGE
    if inj is not None:
        inj.corrupt(op, path)


# -- blob-store faults ----------------------------------------------------

# Blob fault kinds (the cold tier's failure surface — shuffle/cold_tier.py).
BLOB_UNAVAILABLE = "unavailable"       # the op raises OSError (store down)
BLOB_SLOW = "slow"                     # hold the op delay_s on the caller
TORN_UPLOAD = "torn_upload"            # the put lands SHORT (torn_bytes)
#                                        then errors — must never become
#                                        visible (the atomicity contract)
BLOB_CORRUPT = "corrupt_at_rest"       # flip bits in the stored blob AFTER
#                                        the put commits (rot; the entry
#                                        CRC owns detection on restore)
QUOTA_EXHAUSTED = "quota_exhausted"    # the put raises OSError(EDQUOT)

BLOB_KINDS = (BLOB_UNAVAILABLE, BLOB_SLOW, TORN_UPLOAD, BLOB_CORRUPT,
              QUOTA_EXHAUSTED)

# Hook-point op names (the blob contract's four verbs):
#   put     TieringService uploads (segments + drain rows)
#   get     reducer-side restores
#   list    reap/GC prefix scans
#   delete  tombstone reaps


@dataclass
class BlobFault:
    """One scripted blob-store fault. Matching is AND across set
    criteria (op name, key substring); ``after``/``times``/``prob``
    behave as on :class:`Fault`."""

    kind: str
    op: Optional[str] = None          # None matches any op
    key_substr: Optional[str] = None
    after: int = 0
    times: Optional[int] = None
    prob: float = 1.0
    delay_s: float = 0.0              # BLOB_SLOW
    torn_bytes: int = 64              # TORN_UPLOAD: bytes that land
    flip_bits: int = 1                # BLOB_CORRUPT
    seen: int = 0
    fired: int = 0

    def __post_init__(self):
        if self.kind not in BLOB_KINDS:
            raise ValueError(f"unknown blob fault kind {self.kind!r}")


class BlobFaultInjector:
    """Seeded chaos shim over the blob store, sibling of
    :class:`StorageFaultInjector`: installed process-globally, the
    :class:`~sparkrdma_tpu.shuffle.cold_tier.FSBlobStore` consults the
    module hooks on every put/get/list/delete — a single ``is None``
    check when no injector is active. Same ``after``/``times``/``prob``
    windows and seeded RNG, so a failing
    ``scripts/run_chaos.sh CHAOS_COLD=1`` sweep replays from its seed."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self._lock = threading.RLock()
        self._faults: List[BlobFault] = []
        self.fired: Dict[str, int] = {}

    # -- scripting -------------------------------------------------------

    def add(self, kind: str, **kw) -> BlobFault:
        fault = BlobFault(kind, **kw)
        with self._lock:
            self._faults.append(fault)
        return fault

    def clear(self) -> None:
        with self._lock:
            self._faults.clear()

    def fired_count(self, kind: Optional[str] = None) -> int:
        with self._lock:
            if kind is not None:
                return self.fired.get(kind, 0)
            return sum(self.fired.values())

    # -- installation ----------------------------------------------------

    def install(self) -> None:
        global _BLOB
        _BLOB = self

    def uninstall(self) -> None:
        global _BLOB
        if _BLOB is self:
            _BLOB = None

    # -- fault application (called from the module hooks) ----------------

    def check(self, op: str, key: str) -> None:
        """Raise/stall for error-kind faults matching ``(op, key)``."""
        import errno

        fault = self._match(BLOB_SLOW, op, key)
        if fault is not None:
            time.sleep(fault.delay_s)
        fault = self._match(BLOB_UNAVAILABLE, op, key)
        if fault is not None:
            raise OSError(errno.EIO,
                          f"fault injection: blob store unavailable ({op})",
                          key)
        fault = self._match(QUOTA_EXHAUSTED, op, key)
        if fault is not None:
            raise OSError(errno.EDQUOT,
                          f"fault injection: blob quota exhausted ({op})",
                          key)

    def write_cap(self, op: str, key: str, nbytes: int) -> Optional[int]:
        """TORN_UPLOAD: how many of ``nbytes`` should land before the
        put fails (None = no fault, write everything)."""
        fault = self._match(TORN_UPLOAD, op, key)
        if fault is None:
            return None
        return max(0, min(fault.torn_bytes, nbytes - 1))

    def corrupt(self, op: str, path: str) -> bool:
        """BLOB_CORRUPT: flip seeded bits in the committed blob file in
        place (rot AFTER the put — the published CRC covers the clean
        bytes, so restore-time verification owns detection). Returns
        True if a fault fired."""
        fault = self._match(BLOB_CORRUPT, op, path)
        if fault is None:
            return False
        try:
            size = os.path.getsize(path)
        except OSError:
            return False
        if size == 0:
            return False
        with open(path, "r+b") as f:
            for _ in range(max(1, fault.flip_bits)):
                with self._lock:
                    pos = self.rng.randrange(size)
                    bit = 1 << self.rng.randrange(8)
                f.seek(pos)
                b = f.read(1)
                f.seek(pos)
                f.write(bytes([b[0] ^ bit]))
        log.debug("fault injection: flipped %d bit(s) in blob %s",
                  max(1, fault.flip_bits), path)
        return True

    def _match(self, kind: str, op: str, key: str) -> Optional[BlobFault]:
        with self._lock:
            for fault in self._faults:
                if fault.kind != kind:
                    continue
                if fault.op is not None and fault.op != op:
                    continue
                if (fault.key_substr is not None
                        and fault.key_substr not in key):
                    continue
                fault.seen += 1
                if fault.seen <= fault.after:
                    continue
                if fault.times is not None and fault.fired >= fault.times:
                    continue
                if fault.prob < 1.0 and self.rng.random() >= fault.prob:
                    continue
                fault.fired += 1
                self.fired[kind] = self.fired.get(kind, 0) + 1
                return fault
        return None


# Process-global blob injector (None = no chaos, hooks are no-ops).
_BLOB: Optional[BlobFaultInjector] = None


def blob_check(op: str, key: str) -> None:
    """Production hook: raise/stall if a blob fault matches. A single
    attribute load + ``is None`` test when no injector is installed."""
    inj = _BLOB
    if inj is not None:
        inj.check(op, key)


def blob_write_cap(op: str, key: str, nbytes: int) -> Optional[int]:
    """Production hook for torn uploads: bytes to land before failing,
    or None for a full write."""
    inj = _BLOB
    if inj is not None:
        return inj.write_cap(op, key, nbytes)
    return None


def blob_corrupt(op: str, path: str) -> None:
    """Production hook: flip bits at rest in the committed blob file if
    a BLOB_CORRUPT fault matches (no-op otherwise)."""
    inj = _BLOB
    if inj is not None:
        inj.corrupt(op, path)
